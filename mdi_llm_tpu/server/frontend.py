"""Thread bridge between an open request stream and the serving engine.

`ServingEngine` is single-threaded by contract: the scheduler's host
bookkeeping, the pool free-lists and the donated device arrays all
assume one caller.  `ServingFrontend` keeps that contract while turning
the engine into an open system, by pinning ALL engine work to one
dedicated thread and exchanging data with it only through two seams the
engine already exposes:

- **in**: `submit()` (any thread) appends to a bounded channel under a
  lock; the ENGINE thread drains the channel into `Scheduler.add` at
  every `step_hook` firing and between `run()` calls.  The bound covers
  accepted-but-not-yet-seated work (channel + scheduler waiting queue);
  arrivals past it raise `QueueFullError` — the HTTP layer's 429.
- **out**: the engine's `stream_cb` fires per generated token on the
  engine thread; the frontend routes it to the request's
  `RequestHandle`, which appends host-side and forwards to an optional
  `sink` callable (the HTTP layer passes a
  `loop.call_soon_threadsafe` bridge; tests pass a plain list append).

Zero interference contract (pinned by tests/test_server.py): with every
request submitted before the engine thread starts, the scheduler sees
exactly the sequence of `add` calls a replay would have made, so token
streams, host-sync counts and compile behavior are bit-identical to
`engine.run()` offline — the front-end adds threads around the loop,
never inside it.

Lifecycle: `start()` → serve → `drain()` (stop accepting, let in-flight
finish) → `stop()` (join; with `hard=True` abort the loop at the next
step boundary).  `cancel(rid)` retires a live request at the next step
boundary and completes its handle with the tokens emitted so far.

Concurrency contract (checked statically by mdi-lint's thread rules,
see docs/analysis.md "Concurrency analysis"): every mutable attribute
shared between the engine thread and submitters is touched only under
`self._lock`.  The `_yield_point()` calls below are mdi-race's seams —
no-ops in production (one global read), but the deterministic schedule
explorer (`server/explorer.py`) installs a seeded scheduler there to
force adversarial interleavings in tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "FrontendClosedError",
    "QueueFullError",
    "RequestHandle",
    "ServingFrontend",
]

#: mdi-race hook: tests install a callable via
#: `server.explorer.ScheduleExplorer.install()`; production never does.
_YIELD: Optional[Callable[[str], None]] = None


def _yield_point(tag: str) -> None:
    """A named interleaving seam.  With no explorer installed this is a
    single global load — zero overhead on the serving path."""
    y = _YIELD
    if y is not None:
        y(tag)


class QueueFullError(RuntimeError):
    """Admission queue at its bound — backpressure (HTTP 429)."""


class FrontendClosedError(RuntimeError):
    """Frontend draining or stopped — no new work (HTTP 503)."""


class _HardStop(Exception):
    """Raised from the step hook to abort `engine.run` mid-queue."""


class RequestHandle:
    """One submitted request's streaming state and completion latch.

    `tokens` grows on the ENGINE thread; `done` is a `threading.Event`
    any thread may wait on.  `sink(event)` — when given — is called on
    the engine thread with `("token", tok)`, then exactly one of
    `("done", result_tokens)` / `("cancelled", tokens_so_far)` /
    `("error", message)`; sinks must be cheap and non-blocking (the HTTP
    layer hands a threadsafe asyncio bridge, never a direct writer).
    """

    def __init__(self, rid: str, n_prompt: int, max_new_tokens: int,
                 sink: Optional[Callable[[Tuple], None]] = None):
        self.rid = rid
        self.n_prompt = n_prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.result: Optional[List[int]] = None  # prompt + kept generation
        self.error: Optional[str] = None
        self.cancelled = False
        self.done = threading.Event()
        self.submitted_s = time.perf_counter()
        self._sink = sink

    def _event(self, kind: str, payload) -> None:  # mdi-thread: engine
        if self._sink is not None:
            self._sink((kind, payload))

    def _on_token(self, tok: int) -> None:  # mdi-thread: engine
        # single writer (engine thread); mid-flight readers get a
        # GIL-atomic snapshot of streaming progress by design
        # mdi-lint: disable-next-line=unguarded-shared-state -- lock-free by design, see above
        self.tokens.append(tok)
        self._event("token", tok)

    def _complete(self, result: List[int]) -> None:  # mdi-thread: engine
        # written once, strictly before done.set(): Event.set()/wait()
        # is the publication barrier readers synchronize on
        # mdi-lint: disable-next-line=unguarded-shared-state -- published via done Event, see above
        self.result = result
        self._event("done", result)
        self.done.set()

    def _cancel(self) -> None:  # mdi-thread: engine
        self.cancelled = True
        self._event("cancelled", list(self.tokens))
        self.done.set()

    def _fail(self, msg: str) -> None:  # mdi-thread: engine
        self.error = msg
        self._event("error", msg)
        self.done.set()

    def generated(self) -> List[int]:  # mdi-thread: any
        """Kept generated tokens: the stop-trimmed result suffix once
        finished, else the stream so far."""
        if self.result is not None:
            return self.result[self.n_prompt:]
        return list(self.tokens)


class ServingFrontend:
    """Open-system front door for one `ServingEngine`.

    Build from a fresh engine (nothing queued), `start()` the engine
    thread, `submit()` from any thread, `drain()`/`stop()` to land it::

        front = ServingFrontend(gen.serve(max_batch=8, obs=obs))
        front.start()
        h = front.submit(prompt_tokens, max_new_tokens=64)
        h.done.wait()
        front.drain(); front.stop()

    `max_queue` bounds accepted-but-unseated requests (None → the
    engine config's `resolved_admission_queue()`, 4 × max_batch).
    """

    #: engine-thread idle wait between wake checks (seconds); the wake
    #: event short-circuits it on every submit/drain/stop
    IDLE_WAIT_S = 0.05

    def __init__(self, engine, max_queue: Optional[int] = None):
        self.engine = engine
        self.max_queue = (
            int(max_queue) if max_queue is not None
            else engine.cfg.resolved_admission_queue()
        )
        if self.max_queue < 1:
            raise ValueError(
                f"max_queue {self.max_queue} must be >= 1: a server that "
                "can never accept a request serves nothing (mdi-audit: "
                "bad-server-config)"
            )
        self._lock = threading.Lock()
        self._channel: List[Tuple] = []  # (handle, request kwargs)
        self._handles: Dict[str, RequestHandle] = {}  # live (unfinished)
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self._stopped = False
        self._hard_stop = False
        self._cancels: List[str] = []
        self._rid_counter = 0
        self._offered = 0  # accepted + rejected arrivals
        self._t_first: Optional[float] = None

    # -- submission side (any thread) ----------------------------------------

    def queue_depth(self) -> int:
        """Accepted-but-not-yet-seated requests: the submission channel
        plus the scheduler's waiting queue.  `len()` on both is a GIL
        atomic read and the count is only used for admission control, so
        a stale-by-one view is acceptable by design.  MUST stay lock-free:
        `submit()` calls it while already holding the non-reentrant
        `self._lock`."""
        # mdi-lint: disable-next-line=unguarded-shared-state -- GIL-atomic len(); locking here would deadlock submit()
        return len(self._channel) + len(self.engine.scheduler.waiting)

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        rid: Optional[str] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
        priority: int = 0,
        tenant: str = "",
        ttft_slo_s: Optional[float] = None,
        sink: Optional[Callable[[Tuple], None]] = None,
    ) -> RequestHandle:
        """Accept one request or raise: `ValueError` for requests that can
        never fit (the scheduler's add-time wall, checked HERE so the
        caller gets it synchronously — HTTP 400), `QueueFullError` at the
        admission bound (429), `FrontendClosedError` when draining or
        stopped (503)."""
        from mdi_llm_tpu.serving.scheduler import Request

        prompt = [int(t) for t in prompt]
        _yield_point("submit:enter")
        with self._lock:
            # the closed check comes FIRST: an arrival that loses the
            # race with drain() gets a deterministic 503 with zero side
            # effects — it is not offered load against a closed server
            # (pinned by the drain-window explorer seeds)
            if self._draining or self._stopped:
                raise FrontendClosedError(
                    "frontend is draining/stopped; not accepting requests"
                )
            now = time.perf_counter()
            if self._t_first is None:
                self._t_first = now
            self._offered += 1
            elapsed = max(now - self._t_first, 1e-9)
            # offered-rate-so-far: arrivals (accepted + rejected) per
            # second since the first one — the denominator of every
            # open-system claim; replay runs never touch it
            self.engine.stats.offered_qps = (
                self._offered / elapsed if self._offered > 1 else 0.0
            )
            if rid is None:
                rid = f"req{self._rid_counter}"
                self._rid_counter += 1
            if rid in self._handles:
                raise ValueError(f"request id {rid!r} already in flight")
            req = Request(
                rid=rid, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                stop_sequences=stop_sequences, priority=int(priority),
                tenant=str(tenant), ttft_slo_s=ttft_slo_s,
            )
            # feasibility wall BEFORE the bound check: an impossible
            # request is a 400, not a 429, and must not count as load
            self.engine.scheduler.validate(req)
            if self.queue_depth() >= self.max_queue:
                self.engine.stats.requests_rejected += 1
                if self.engine.obs is not None:
                    self.engine.obs.request_rejected(rid)
                raise QueueFullError(
                    f"admission queue full ({self.max_queue} waiting); "
                    "retry later"
                )
            handle = RequestHandle(rid, len(prompt), int(max_new_tokens),
                                   sink=sink)
            self._handles[rid] = handle
            self._channel.append((handle, req))
        _yield_point("submit:queued")
        self._wake.set()
        return handle

    def cancel(self, rid: str) -> bool:
        """Request cancellation (client went away): queued requests drop
        before admission, live ones retire at the next step boundary,
        keeping the tokens already generated.  Returns False for unknown/
        finished rids.  The handle completes via its "cancelled" event."""
        _yield_point("cancel:enter")
        with self._lock:
            if rid not in self._handles:
                return False
            self._cancels.append(rid)
        _yield_point("cancel:queued")
        self._wake.set()
        return True

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingFrontend":
        if self._thread is not None:
            raise RuntimeError("frontend already started")
        self._thread = threading.Thread(
            target=self._pump, name="mdi-serving-engine", daemon=True
        )
        self._thread.start()
        return self

    @property
    def idle(self) -> bool:
        """No channel entries, no scheduler work, no live handles."""
        with self._lock:
            return (
                not self._channel
                and not self.engine.scheduler.has_work
                and not self._handles
            )

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop accepting (submit → FrontendClosedError),
        let everything in flight finish.  Returns True when idle within
        `timeout` (None = wait forever)."""
        _yield_point("drain:enter")
        with self._lock:
            self._draining = True
        _yield_point("drain:flagged")
        self._wake.set()
        deadline = None if timeout is None else time.perf_counter() + timeout
        while not self.idle:
            if self._thread is None or not self._thread.is_alive():
                break
            if deadline is not None and time.perf_counter() > deadline:
                return False
            time.sleep(0.01)
        return self.idle

    def stop(self, hard: bool = False) -> None:
        """Stop the engine thread.  `hard=True` aborts at the next step
        boundary, failing unfinished handles; the default lets the
        current `run()` finish its queue first (call `drain()` before
        `stop()` for a clean shutdown)."""
        _yield_point("stop:enter")
        with self._lock:
            self._stopped = True
            self._draining = True
            self._hard_stop = self._hard_stop or hard
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)

    # -- engine thread -------------------------------------------------------

    def _drain_channel(self) -> None:
        """ENGINE THREAD: hand queued submissions to the scheduler.  The
        channel entry was validated at submit time, so add() can only
        fail on a racing geometry change — fail the handle, not the
        loop."""
        _yield_point("engine:drain-channel")
        with self._lock:
            batch, self._channel = self._channel, []
        for handle, req in batch:
            try:
                self.engine.scheduler.add(req)
            except ValueError as e:  # pragma: no cover - validated at submit
                with self._lock:
                    self._handles.pop(handle.rid, None)
                handle._fail(str(e))

    def _apply_cancels(self) -> None:
        """ENGINE THREAD: drop queued / retire live cancelled requests."""
        _yield_point("engine:cancels")
        with self._lock:
            cancels, self._cancels = self._cancels, []
            # snapshot the handles in the same critical section as the
            # swap: a lone `_handles.get` outside it races submit/collect
            handles = {rid: self._handles.get(rid) for rid in cancels}
        if not cancels:
            return
        sched = self.engine.scheduler
        for rid in cancels:
            handle = handles.get(rid)
            if handle is None:
                continue
            # not yet handed over: drop from the channel
            with self._lock:
                for i, (h, _req) in enumerate(self._channel):
                    if h.rid == rid:
                        del self._channel[i]
                        break
            # waiting in the scheduler: remove before admission
            for i, req in enumerate(sched.waiting):
                if req.rid == rid:
                    del sched.waiting[i]
                    break
            for i, (req, _toks) in enumerate(sched.preempted):
                if req.rid == rid:
                    del sched.preempted[i]
                    # host tier: a swapped-out entry also holds host slots
                    sched.drop_swap_record(rid)
                    break
            # live in a slot: retire, releasing its blocks
            for seq in sched.running():
                if seq.req.rid == rid:
                    sched.retire(seq)
                    self.engine.pop_result(rid)  # retire() never filled it
                    break
            with self._lock:
                self._handles.pop(rid, None)
            handle._cancel()

    def _collect_finished(self) -> None:
        """ENGINE THREAD: complete handles whose requests retired."""
        _yield_point("engine:collect")
        with self._lock:
            live = list(self._handles.items())
        for rid, handle in live:
            result = self.engine.pop_result(rid)
            if result is not None:
                with self._lock:
                    self._handles.pop(rid, None)
                handle._complete(result)
        # the scheduler's finished list is write-only bookkeeping for the
        # replay path; a long-lived server must not let it grow forever
        self.engine.scheduler.finished.clear()

    def _on_token(self, rid: str, tok: int) -> None:
        _yield_point("engine:token")
        with self._lock:
            handle = self._handles.get(rid)
        if handle is not None:
            handle._on_token(tok)

    def _on_step(self, _i: int) -> None:
        """The engine's `step_hook` seam: admissions, cancellations and
        completions all land here, ON the engine thread, BETWEEN steps —
        exactly where the replay loop does its own scheduler work."""
        self._apply_cancels()
        self._drain_channel()
        self._collect_finished()
        with self._lock:
            hard = self._hard_stop
        if hard:
            raise _HardStop

    def _pump(self) -> None:
        eng = self.engine
        try:
            while True:
                self._apply_cancels()
                self._drain_channel()
                if eng.scheduler.has_work:
                    try:
                        eng.run(stream_cb=self._on_token,
                                step_hook=self._on_step)
                    except _HardStop:
                        break  # unfinished handles fail in the finally
                    self._collect_finished()
                    continue
                with self._lock:
                    should_exit = self._stopped or (
                        self._draining and not self._channel
                        and not self._handles
                    )
                if should_exit:
                    break
                self._wake.wait(self.IDLE_WAIT_S)
                self._wake.clear()
        except Exception as e:  # engine died: fail every live handle
            msg = f"{type(e).__name__}: {e}"
            with self._lock:
                dead = list(self._handles.values())
                self._handles.clear()
            for handle in dead:
                handle._fail(msg)
            raise
        finally:
            self._collect_finished()
            with self._lock:
                orphans = list(self._handles.values())
                self._handles.clear()
            for handle in orphans:
                handle._fail("frontend stopped before completion")
