"""mdi-race: a deterministic schedule explorer for `ServingFrontend`.

The thread rules (`analysis/threads.py`) prove the locking discipline
statically; this module hammers it dynamically.  `ServingFrontend`
exposes named *yield points* (`frontend._yield_point(tag)`) at every
channel/lock/event seam — one global load each, no-ops in production.
A `ScheduleExplorer` installs a seeded visitor there that perturbs the
thread schedule (short sleeps and forced GIL drops), driving the
submit/cancel/drain/stop threads and the engine thread through
adversarial interleavings that a quiet CI box would otherwise never
produce.

What "deterministic" buys here: each seed fixes the perturbation
stream, so a seed that shakes out a bug keeps applying the same
pressure run after run — failing seeds are committed as regression
fixtures (tests/test_explorer.py).  The correctness oracle is seed-
independent by design: for every seed, token streams must be identical
to the offline `engine.run()` replay, every handle must complete, and
the frontend must land idle.  (The OS still owns the scheduler, so a
seed replays a pressure pattern, not an exact thread trace.)

Three entry points:

- `ScheduleExplorer` — the seeded visitor; `install()`/`uninstall()` or
  use as a context manager.
- `run_episode()` — one full adversarial episode against a live CPU
  engine: N submitter threads, optional cancels, optional racing
  drain, final drain+stop.  Returns handles/errors for the caller's
  asserts.
- `doctor_burst()` — self-contained short burst on a throwaway tiny
  model, JSON-able result; the `mdi-doctor threads` stage runs it in a
  subprocess to triage hosts whose concurrency behaviour is broken
  (exotic GIL builds, pathological schedulers).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mdi_llm_tpu.server import frontend as _frontend
from mdi_llm_tpu.server.frontend import RequestHandle, ServingFrontend

__all__ = [
    "ScheduleExplorer",
    "run_episode",
    "doctor_burst",
]


class ScheduleExplorer:
    """Seeded schedule perturbation at the frontend's yield points.

    At each visit the explorer draws from its own `random.Random(seed)`
    (under an internal lock, so the draw sequence is shared across
    threads) and either sleeps a sub-millisecond pause — widening the
    current race window — or calls `time.sleep(0)` to force a GIL drop,
    or falls through untouched.  `record=True` keeps a
    `(thread_name, tag)` trace for debugging a caught seed.
    """

    def __init__(self, seed: int, p_pause: float = 0.35,
                 p_switch: float = 0.35, max_pause_s: float = 0.0008,
                 record: bool = False):
        self.seed = seed
        self.p_pause = p_pause
        self.p_switch = p_switch
        self.max_pause_s = max_pause_s
        self.record = record
        self.visits = 0
        self.trace: List[Tuple[str, str]] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def visit(self, tag: str) -> None:  # mdi-thread: any
        with self._lock:
            self.visits += 1
            roll = self._rng.random()
            pause = self._rng.uniform(0.0, self.max_pause_s)
            if self.record:
                self.trace.append((threading.current_thread().name, tag))
        if roll < self.p_pause:
            time.sleep(pause)
        elif roll < self.p_pause + self.p_switch:
            time.sleep(0)  # drop the GIL: invite a context switch

    # -- installation --------------------------------------------------------

    def install(self) -> "ScheduleExplorer":
        if _frontend._YIELD is not None:
            raise RuntimeError("another schedule explorer is installed")
        _frontend._YIELD = self.visit
        return self

    def uninstall(self) -> None:
        _frontend._YIELD = None

    def __enter__(self) -> "ScheduleExplorer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def run_episode(
    engine,
    trace: Sequence[Tuple[str, Sequence[int], int]],
    seed: int,
    *,
    live: bool = True,
    cancel: Sequence[str] = (),
    drain_race: bool = False,
    submit_threads: int = 2,
    max_queue: Optional[int] = None,
    drain_timeout_s: float = 60.0,
    frontend_cls: Callable[..., ServingFrontend] = ServingFrontend,
    explorer_kwargs: Optional[Dict] = None,
) -> Dict:
    """One seeded adversarial episode against a live CPU engine.

    `trace` is `[(rid, prompt_tokens, max_new_tokens), ...]`.  Requests
    are shuffled across `submit_threads` submitter threads (assignment
    and all structural choices come from the episode seed).  Modes:

    - ``live=False``: every request is submitted BEFORE `start()` — the
      zero-interference shape, where token streams, host-sync counts
      and compile behaviour must be bit-identical to offline
      `engine.run()` whatever the seed does to the submit ordering.
    - ``live=True``: submitters race the running engine thread; with
      `cancel`, a canceller thread cancels those rids as soon as their
      handles exist; with `drain_race=True`, a drainer thread calls
      `drain()` concurrently with the submitters, so arrivals race the
      drain flag (each must deterministically complete OR raise
      `FrontendClosedError` — never hang, never half-admit).

    Returns ``{"handles", "errors", "drained", "frontend", "explorer"}``
    where `errors` maps rid -> raised exception instance for rejected
    submissions.  The frontend is always stopped (and the explorer
    uninstalled) on exit, even when an assert-worthy anomaly occurred.
    """
    rng = random.Random(seed + 1000003)  # structural choices, not pacing
    exp = ScheduleExplorer(seed, **(explorer_kwargs or {}))
    front = frontend_cls(engine, max_queue=max_queue)

    order = list(trace)
    rng.shuffle(order)
    parts: List[List] = [order[i::submit_threads]
                         for i in range(submit_threads)]
    handles: Dict[str, RequestHandle] = {}
    errors: Dict[str, BaseException] = {}
    book = threading.Lock()
    submitted = threading.Event()  # all submitter threads finished

    def submitter(part) -> None:
        for rid, prompt, max_new in part:
            try:
                h = front.submit(prompt, max_new, rid=rid)
            except Exception as e:  # 429/503/400: recorded, not raised
                with book:
                    errors[rid] = e
                continue
            with book:
                handles[rid] = h

    def canceller() -> None:
        for rid in cancel:
            # wait for the handle to exist (or its submit to fail), then
            # cancel — the request may be queued, live, or already done
            while True:
                with book:
                    ready = rid in handles or rid in errors
                if ready or submitted.is_set():
                    break
                time.sleep(0.0002)
            front.cancel(rid)

    def drainer(delay_s: float) -> None:
        time.sleep(delay_s)
        front.drain(timeout=drain_timeout_s)

    threads = [
        threading.Thread(target=submitter, args=(part,),
                         name=f"mdi-submit-{i}", daemon=True)
        for i, part in enumerate(parts) if part
    ]
    if cancel:
        threads.append(threading.Thread(target=canceller,
                                        name="mdi-cancel", daemon=True))
    if drain_race:
        threads.append(threading.Thread(
            target=drainer, args=(rng.uniform(0.0, 0.002),),
            name="mdi-drain", daemon=True))

    drained = False
    with exp:
        try:
            if live:
                front.start()
            for t in threads:
                t.start()
            for t in threads:
                if t.name.startswith("mdi-submit"):
                    t.join()
            submitted.set()
            for t in threads:
                t.join()
            if not live:
                front.start()
            drained = front.drain(timeout=drain_timeout_s)
        finally:
            front.stop(hard=not drained)

    return {
        "handles": handles,
        "errors": errors,
        "drained": drained,
        "frontend": front,
        "explorer": exp,
    }


def doctor_burst(n_seeds: int = 4, n_requests: int = 3,
                 max_new: int = 4) -> Dict:
    """A short self-contained explorer burst for `mdi-doctor threads`.

    Builds a throwaway tiny model on whatever backend JAX_PLATFORMS
    selected (the doctor pins cpu), replays the same request trace
    offline once for the oracle, then runs `n_seeds` pre-start episodes
    and reports every parity mismatch.  Everything in the result is
    JSON-clean; ``ok`` is the stage's health verdict.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import init_params

    cfg = Config(
        name="doctor-tiny", block_size=64, vocab_size=64,
        padded_vocab_size=64, n_layer=1, n_head=2, n_embd=16,
        n_query_groups=2, rotary_percentage=1.0, parallel_residual=False,
        bias=False, norm_class_name="RMSNorm", mlp_class_name="LLaMAMLP",
        intermediate_size=32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    rng = np.random.default_rng(7)
    trace = [
        (f"d{i}", [int(t) for t in rng.integers(1, cfg.vocab_size, 5)],
         max_new)
        for i in range(n_requests)
    ]

    def fresh_engine():
        return gen.serve(block_size=4, max_batch=n_requests,
                         prefill_chunk=8)

    offline = fresh_engine()
    for rid, prompt, m in trace:
        offline.add_request(rid, prompt, m)
    want, stats = offline.run()

    mismatches: List[Dict] = []
    visits = 0
    for seed in range(n_seeds):
        ep = run_episode(fresh_engine(), trace, seed, live=False)
        visits += ep["explorer"].visits
        if not ep["drained"]:
            mismatches.append({"seed": seed, "rid": None,
                               "why": "drain timed out"})
        for rid, prompt, m in trace:
            h = ep["handles"].get(rid)
            if h is None:
                why = f"submit failed: {ep['errors'].get(rid)!r}"
                mismatches.append({"seed": seed, "rid": rid, "why": why})
            elif h.result != want[rid]:
                mismatches.append({"seed": seed, "rid": rid,
                                   "why": "token stream diverged from "
                                          "offline replay"})
    return {
        "seeds": n_seeds,
        "requests": n_requests,
        "offline_host_syncs": stats.host_syncs,
        "yield_point_visits": visits,
        "mismatches": mismatches,
        "ok": not mismatches,
    }
