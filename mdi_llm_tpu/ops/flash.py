"""Pallas TPU flash attention (causal self-attention, fwd + custom VJP).

The reference delegates its fused attention to torch SDPA/cuDNN — and runs
it in training and eval alike (`/root/reference/src/sub/model.py:738-751`);
this is the TPU-native equivalent for the O(T²) path: a Pallas kernel that
streams K/V blocks through VMEM with an online softmax, never materializing
the (T, T) score matrix.  GQA is handled by mapping each query head's grid
slot to its KV group in the BlockSpec index maps.

Training support comes from a `jax.custom_vjp`: the forward saves
(q, k, v, o, lse) and the backward is the FlashAttention-2 recompute — a
dQ kernel (grid over query tiles, streaming K/V) and a dK/dV kernel (grid
over key tiles, streaming Q/dO), with per-query-head dK/dV summed over
each GQA group outside the kernel.  Not differentiating simply runs the
primal kernel — inference pays nothing for the VJP machinery.

Scope: causal self-attention over one fresh chunk (q_pos == k_pos ==
arange(T)) — exactly the generation prefill and training shapes.  Decode
(T=1) is memory-bound and stays on the XLA path.  Falls back automatically
unless running on TPU (or `interpret=True` for CPU tests).

Kernel structure (per pallas_guide.md): grid (B, H, Tq/BQ) (bwd-dKV:
(B, H, Tk/BK)); each program holds one query (key) tile in VMEM and
fori-loops over the other operand's tiles up to the causal frontier with
running f32 scratch."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, scale, block_k, seq_len, causal=True
):
    # blocks carry leading (1, 1) batch/head dims: q_ref (1,1,BQ,hs),
    # k_ref/v_ref (1,1,Tk,hs), o_ref (1,1,BQ,hs).  With lse_ref (the
    # VJP-forward variant) the per-query logsumexp is also written for the
    # FlashAttention-2 backward.  causal=False attends the whole chunk
    # (ring attention's off-diagonal blocks).
    block_q = q_ref.shape[2]
    hs = q_ref.shape[3]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, hs), jnp.float32)

    # causal frontier: last K block index that any query in this tile sees
    T_pad = k_ref.shape[2]
    if causal:
        num_k_blocks = (q_start + block_q + block_k - 1) // block_k
    else:
        num_k_blocks = T_pad // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        q_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < seq_len
        if causal:
            mask &= k_idx <= q_idx
        s = jnp.where(mask, s, NEG_INF)

        m_chunk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_chunk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        # VJP-forward variant: per-query logsumexp for the FA-2 backward
        lse_ref[0, 0, :] = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF
        )


def _sds(shape, dtype, like):
    """ShapeDtypeStruct for a pallas output, inheriting `like`'s varying
    mesh axes so the kernels compose with shard_map's vma checking (the
    ring-attention diagonal block runs inside a shard_map over `sp`)."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _pad_shapes(T: int, block_q: int, block_k: int):
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    T_pad = ((T + block_q - 1) // block_q) * block_q
    T_pad = ((T_pad + block_k - 1) // block_k) * block_k
    return T_pad, block_q, block_k


def _pad_t(x: jnp.ndarray, T_pad: int) -> jnp.ndarray:
    T = x.shape[2]
    if T_pad == T:
        return x
    pad = [(0, 0)] * x.ndim
    pad[2] = (0, T_pad - T)
    return jnp.pad(x, pad)


def _qtile_spec(block_q, hs):
    return pl.BlockSpec(
        (1, 1, block_q, hs), lambda b, h, i: (b, h, i, 0), memory_space=pltpu.VMEM
    )


def _full_spec(T_pad, hs, q_per_kv=None):
    if q_per_kv is None:
        return pl.BlockSpec(
            (1, 1, T_pad, hs), lambda b, h, i: (b, h, 0, 0), memory_space=pltpu.VMEM
        )
    return pl.BlockSpec(
        (1, 1, T_pad, hs),
        lambda b, h, i, _q=q_per_kv: (b, h // _q, 0, 0),
        memory_space=pltpu.VMEM,
    )


def _flash_call(scale, block_q, block_k, interpret, causal, seq_len, q, k, v, with_lse):
    """Shared primal/forward pallas_call; q/k/v already T-padded, `seq_len`
    is the true (unpadded) length for masking."""
    B, H, T_pad, hs = q.shape
    G = k.shape[1]
    q_per_kv = H // G
    # one kernel body for both variants: pallas passes lse_ref positionally
    # only when a second output is declared
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_k=block_k, seq_len=seq_len, causal=causal
    )
    out_shape = [_sds((B, H, T_pad, hs), q.dtype, q)]
    out_specs = [_qtile_spec(block_q, hs)]
    if with_lse:
        out_shape.append(_sds((B, H, T_pad), jnp.float32, q))
        out_specs.append(
            pl.BlockSpec((1, 1, block_q), lambda b, h, i: (b, h, i),
                         memory_space=pltpu.VMEM)
        )
    res = pl.pallas_call(
        kernel,
        grid=(B, H, T_pad // block_q),
        in_specs=[
            _qtile_spec(block_q, hs),
            _full_spec(T_pad, hs, q_per_kv),
            _full_spec(T_pad, hs, q_per_kv),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        interpret=interpret,
    )(q, k, v)
    return res if with_lse else (res, None)


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, dq_ref,
    *, scale, block_k, seq_len, causal=True,
):
    """dQ tile: stream K/V blocks up to the causal frontier.
    dS = P ∘ (dO·Vᵀ − D);  dQ = scale · dS · K."""
    block_q = q_ref.shape[2]
    hs = q_ref.shape[3]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0, :, :].astype(jnp.float32)
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :]
    dsum = dsum_ref[0, 0, :]
    acc0 = jnp.zeros((block_q, hs), jnp.float32)
    if causal:
        num_k_blocks = (q_start + block_q + block_k - 1) // block_k
    else:
        num_k_blocks = k_ref.shape[2] // block_k

    def body(kb, acc):
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < seq_len
        if causal:
            mask &= k_idx <= q_idx
        p = jnp.exp(jnp.minimum(s - lse[:, None], 80.0))
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum[:, None])
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    acc = jax.lax.fori_loop(0, num_k_blocks, body, acc0)
    dq_ref[0, 0, :, :] = (acc * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, dsum_ref, dk_ref, dv_ref,
    *, scale, block_q, seq_len, n_q_blocks, causal=True,
):
    """dK/dV tile (per QUERY head; group-summed outside): stream Q/dO
    blocks from the first one that sees this key tile.
    dV = Pᵀ·dO;  dK = scale · dSᵀ·Q."""
    block_k = k_ref.shape[2]
    hs = k_ref.shape[3]
    ki = pl.program_id(2)
    k_start = ki * block_k

    k_t = k_ref[0, 0, :, :].astype(jnp.float32)
    v_t = v_ref[0, 0, :, :].astype(jnp.float32)
    dk0 = jnp.zeros((block_k, hs), jnp.float32)
    dv0 = jnp.zeros((block_k, hs), jnp.float32)
    first_qb = k_start // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        d_blk = dsum_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = scale * jax.lax.dot_general(
            q_blk, k_t, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        q_idx = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (k_idx < seq_len) & (q_idx < seq_len)
        if causal:
            mask &= k_idx <= q_idx
        p = jnp.exp(jnp.minimum(s - lse_blk[:, None], 80.0))
        p = jnp.where(mask, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v_t, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - d_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk, dv = jax.lax.fori_loop(first_qb, n_q_blocks, body, (dk0, dv0))
    dk_ref[0, 0, :, :] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _flash_fwd_impl(scale, block_q, block_k, interpret, causal, q, k, v, with_lse):
    B, H, T, hs = q.shape
    T_pad, block_q, block_k = _pad_shapes(T, block_q, block_k)
    qp, kp, vp = _pad_t(q, T_pad), _pad_t(k, T_pad), _pad_t(v, T_pad)
    out, lse = _flash_call(
        scale, block_q, block_k, interpret, causal, T, qp, kp, vp, with_lse
    )
    out = out[:, :, :T, :]
    return (out, lse) if with_lse else out  # lse stays T_pad-wide (bwd re-pads q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_core(scale, block_q, block_k, interpret, causal, q, k, v):
    return _flash_fwd_impl(scale, block_q, block_k, interpret, causal, q, k, v, False)


def _flash_core_fwd(scale, block_q, block_k, interpret, causal, q, k, v):
    out, lse = _flash_fwd_impl(
        scale, block_q, block_k, interpret, causal, q, k, v, True
    )
    return out, (q, k, v, out, lse)


def _flash_core_bwd(scale, block_q, block_k, interpret, causal, res, do):
    return _flash_bwd_impl(scale, block_q, block_k, interpret, causal, res, do, None)


def _flash_bwd_impl(scale, block_q, block_k, interpret, causal, res, do, dlse):
    """FA-2 backward; `dlse` (B, H, T) is the optional cotangent of the
    logsumexp output (flash_attention_lse).  It folds into the kernels for
    free: ∂lse_i/∂s_ij = P_ij, so ds = P∘(dP − D) + dlse·P
    = P∘(dP − (D − dlse)) — i.e. shift the dsum operand, no kernel change."""
    q, k, v, out, lse = res
    B, H, T, hs = q.shape
    G = k.shape[1]
    q_per_kv = H // G
    T_pad, block_q, block_k = _pad_shapes(T, block_q, block_k)

    # D_i = dO_i · O_i (f32), padded rows contribute zero
    dsum = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    if dlse is not None:
        dsum = dsum - dlse.astype(jnp.float32)
    qp, kp, vp = _pad_t(q, T_pad), _pad_t(k, T_pad), _pad_t(v, T_pad)
    dop = _pad_t(do.astype(q.dtype), T_pad)
    dsum_p = _pad_t(dsum, T_pad)
    lse_p = lse  # produced at T_pad width by the forward

    lse_tile = pl.BlockSpec(
        (1, 1, block_q), lambda b, h, i: (b, h, i), memory_space=pltpu.VMEM
    )
    lse_full = pl.BlockSpec(
        (1, 1, T_pad), lambda b, h, i: (b, h, 0), memory_space=pltpu.VMEM
    )

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, scale=scale, block_k=block_k, seq_len=T,
            causal=causal,
        ),
        grid=(B, H, T_pad // block_q),
        in_specs=[
            _qtile_spec(block_q, hs),
            _full_spec(T_pad, hs, q_per_kv),
            _full_spec(T_pad, hs, q_per_kv),
            _qtile_spec(block_q, hs),
            lse_tile,
            lse_tile,
        ],
        out_specs=_qtile_spec(block_q, hs),
        out_shape=_sds((B, H, T_pad, hs), q.dtype, qp),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, dsum_p)

    ktile = pl.BlockSpec(
        (1, 1, block_k, hs),
        lambda b, h, i, _q=q_per_kv: (b, h // _q, i, 0),
        memory_space=pltpu.VMEM,
    )
    dkv_out = pl.BlockSpec(
        (1, 1, block_k, hs), lambda b, h, i: (b, h, i, 0), memory_space=pltpu.VMEM
    )
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, block_q=block_q, seq_len=T,
            n_q_blocks=T_pad // block_q, causal=causal,
        ),
        grid=(B, H, T_pad // block_k),
        in_specs=[
            ktile,
            ktile,
            _full_spec(T_pad, hs),
            _full_spec(T_pad, hs),
            lse_full,
            lse_full,
        ],
        out_specs=(dkv_out, dkv_out),
        out_shape=(
            _sds((B, H, T_pad, hs), jnp.float32, qp),
            _sds((B, H, T_pad, hs), jnp.float32, qp),
        ),
        interpret=interpret,
    )(kp, vp, qp, dop, lse_p, dsum_p)

    # GQA: each query head of a group produced its own dK/dV share
    dk = dk_h.reshape(B, G, q_per_kv, T_pad, hs).sum(2)[:, :, :T].astype(k.dtype)
    dv = dv_h.reshape(B, G, q_per_kv, T_pad, hs).sum(2)[:, :, :T].astype(v.dtype)
    return dq[:, :, :T, :], dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_lse_core(scale, block_q, block_k, interpret, causal, q, k, v):
    out, lse = _flash_fwd_impl(
        scale, block_q, block_k, interpret, causal, q, k, v, True
    )
    return out, lse[:, :, : q.shape[2]]


def _flash_lse_core_fwd(scale, block_q, block_k, interpret, causal, q, k, v):
    out, lse = _flash_fwd_impl(
        scale, block_q, block_k, interpret, causal, q, k, v, True
    )
    return (out, lse[:, :, : q.shape[2]]), (q, k, v, out, lse)


def _flash_lse_core_bwd(scale, block_q, block_k, interpret, causal, res, cts):
    do, dlse = cts
    return _flash_bwd_impl(scale, block_q, block_k, interpret, causal, res, do, dlse)


_flash_lse_core.defvjp(_flash_lse_core_fwd, _flash_lse_core_bwd)


def flash_attention_lse(
    q: jnp.ndarray,  # (B, n_head, T, hs)
    k: jnp.ndarray,  # (B, n_groups, T, hs)
    v: jnp.ndarray,  # (B, n_groups, T, hs)
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    causal: bool = True,
):
    """Flash attention returning (out, lse) — the per-query logsumexp lets
    callers merge this block's result with other attention partials (the
    ring-attention blocks, flash-decoding-style two-level softmax
    reductions).  `causal=False` attends the whole K chunk (the ring's
    off-diagonal blocks, where every key precedes every query).  Fully
    differentiable in both outputs (the lse cotangent folds into the same
    backward kernels)."""
    B, H, T, hs = q.shape
    if T != k.shape[2]:
        raise ValueError("flash path is self-attention over one chunk")
    if scale is None:
        scale = 1.0 / (hs**0.5)
    return _flash_lse_core(
        float(scale), int(block_q), int(block_k), bool(interpret), bool(causal),
        q, k, v,
    )


def flash_attention(
    q: jnp.ndarray,  # (B, n_head, T, hs)
    k: jnp.ndarray,  # (B, n_groups, T, hs)
    v: jnp.ndarray,  # (B, n_groups, T, hs)
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal flash self-attention; returns (B, n_head, T, hs).

    Differentiable: reverse-mode AD takes the FlashAttention-2 recompute
    backward (Pallas dQ / dK-dV kernels) instead of unfusing the forward,
    so training never materializes the (T, T) score matrix either."""
    B, H, T, hs = q.shape
    Tk = k.shape[2]
    if T != Tk:
        raise ValueError("flash path is self-attention over one chunk")
    if scale is None:
        scale = 1.0 / (hs**0.5)
    return _flash_core(
        float(scale), int(block_q), int(block_k), bool(interpret), True, q, k, v
    )
