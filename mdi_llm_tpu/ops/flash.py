"""Pallas TPU flash attention (causal self-attention prefill).

The reference delegates its fused attention to torch SDPA/cuDNN
(`/root/reference/src/sub/model.py:738-751`); this is the TPU-native
equivalent for the O(T²) prefill path: a Pallas kernel that streams K/V
blocks through VMEM with an online softmax, never materializing the (T, T)
score matrix.  GQA is handled by mapping each query head's grid slot to its
KV group in the BlockSpec index maps.

Scope: causal self-attention over one fresh chunk (q_pos == k_pos ==
arange(T)) — exactly the generation prefill and training shapes.  Decode
(T=1) is memory-bound and stays on the XLA path.  Falls back automatically
unless running on TPU (or `interpret=True` for CPU tests).

Kernel structure (per pallas_guide.md): grid (B, H, Tq/BQ); each program
holds one (BQ, hs) query tile in VMEM and fori-loops over K tiles up to the
causal frontier with running (m, l, acc) scratch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, block_k, seq_len):
    # blocks carry leading (1, 1) batch/head dims: q_ref (1,1,BQ,hs),
    # k_ref/v_ref (1,1,Tk,hs), o_ref (1,1,BQ,hs)
    block_q = q_ref.shape[2]
    hs = q_ref.shape[3]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, hs), jnp.float32)

    # causal frontier: last K block index that any query in this tile sees
    num_k_blocks = (q_start + block_q + block_k - 1) // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        q_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_idx <= q_idx) & (k_idx < seq_len)
        s = jnp.where(mask, s, NEG_INF)

        m_chunk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_chunk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jnp.ndarray,  # (B, n_head, T, hs)
    k: jnp.ndarray,  # (B, n_groups, T, hs)
    v: jnp.ndarray,  # (B, n_groups, T, hs)
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal flash self-attention; returns (B, n_head, T, hs)."""
    B, H, T, hs = q.shape
    _, G, Tk, _ = k.shape
    assert T == Tk, "flash path is self-attention over one chunk"
    if scale is None:
        scale = 1.0 / (hs**0.5)
    q_per_kv = H // G

    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # pad T to a multiple of the blocks (masked out via seq_len)
    T_pad = ((T + block_q - 1) // block_q) * block_q
    T_pad = ((T_pad + block_k - 1) // block_k) * block_k
    if T_pad != T:
        pad = [(0, 0), (0, 0), (0, T_pad - T), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_k=block_k, seq_len=T
    )
    out = pl.pallas_call(
        kernel,
        grid=(B, H, T_pad // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, hs),
                lambda b, h, i: (b, h, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, T_pad, hs),
                lambda b, h, i, _q=q_per_kv: (b, h // _q, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, T_pad, hs),
                lambda b, h, i, _q=q_per_kv: (b, h // _q, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hs), lambda b, h, i: (b, h, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T_pad, hs), q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :T, :]
