"""Tuning tables for the unified ragged paged-attention kernel.

The kernel (`ops/ragged_paged_attention.py`) is shaped by three block/grid
parameters that trade VMEM residency against grid occupancy:

- ``kv_step`` — KV tokens streamed per grid iteration.  Must divide the
  pool's ``block_size`` (each table-resolved block is walked in
  ``block_size // kv_step`` sub-steps); ``None`` means one whole block per
  step.
- ``q_pack`` — head-packing factor: how many KV groups fold into one
  block-diagonal matmul so (head, query) rows fill full 8x128 sublanes
  when ``head_size`` underfills a lane tile (pythia-14m / tiny-llama
  class).  Must divide ``n_query_groups``; ``None`` means the largest
  divisor with ``q_pack * head_size <= 128``.
- ``scratch_width`` — lane width of the online-softmax m/l VMEM scratch
  rows (the kernel reads column 0; the width is a layout choice).

Resolution (`resolve_kernel_params`) is HOST-side and deterministic per
process, so the chosen parameters are compile-time static — the serving
engine pays zero post-warmup recompiles for them.  Precedence:

1. explicit ``params=`` at the call site,
2. a user tuning table (JSON artifact written by ``mdi-tune``), found via
   the ``MDI_TUNE_TABLE`` env var or an explicit path,
3. the committed per-generation defaults below (v4/v5e/v5p/v6e, the same
   normalization as ``obs/roofline.DEVICE_PEAKS``),
4. conservative defaults for unknown devices — never a guess.

``mdi-tune`` sweeps the candidate grid on-device for one model geometry
and persists the winner as a JSON table; `mdi-audit`'s
``bad-kernel-tuning`` check validates any table entry (divisibility, VMEM
budget vs `obs/roofline.device_vmem_bytes`) before anything compiles.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TUNE_TABLE_ENV",
    "KernelParams",
    "DEFAULT_PARAMS",
    "BUILTIN_TUNING_TABLES",
    "default_q_pack",
    "geometry_key",
    "resolve_kernel_params",
    "validate_kernel_params",
    "estimate_kernel_vmem",
    "load_tuning_table",
    "save_tuning_table",
    "candidate_params",
    "SERVE_TRACE_CASES",
    "autotune",
    "main",
]

# env var naming a user tuning-table JSON (the `mdi-tune --out` artifact);
# wins over the committed defaults for every geometry it covers
TUNE_TABLE_ENV = "MDI_TUNE_TABLE"


@dataclasses.dataclass(frozen=True)
class KernelParams:
    """One tuning-table entry.  ``None`` fields mean "derive from the
    geometry" (see module docstring); `resolved` pins them to ints."""

    kv_step: Optional[int] = None
    q_pack: Optional[int] = None
    scratch_width: int = 128

    def resolved(
        self, block_size: int, n_groups: int, head_size: int
    ) -> "KernelParams":
        """Concrete ints for one pool geometry: ``kv_step=None`` becomes
        the full block, ``q_pack=None`` the auto packing factor."""
        return KernelParams(
            kv_step=int(self.kv_step or block_size),
            q_pack=int(self.q_pack or default_q_pack(n_groups, head_size)),
            scratch_width=int(self.scratch_width),
        )

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KernelParams":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# the conservative entry: whole-block KV steps, geometry-derived head
# packing, one full lane of scratch — correct on anything, tuned for
# nothing.  Unknown device kinds resolve to exactly this.
DEFAULT_PARAMS = KernelParams(kv_step=None, q_pack=None, scratch_width=128)

# Committed per-generation defaults, ``obs/roofline.DEVICE_PEAKS``
# semantics: keyed by the normalized device kind, then by geometry key
# (`geometry_key`) with ``"*"`` as the any-geometry row.  These are the
# defaults `mdi-tune` measures AGAINST — bench's kernel-paged row reports
# tuned-vs-default per variant.  All four generations currently commit
# the conservative entry; a measured win lands here as an exact-geometry
# row, never by loosening ``"*"``.
BUILTIN_TUNING_TABLES: Dict[str, Dict[str, Dict[str, Any]]] = {
    "v4": {"*": DEFAULT_PARAMS.to_dict()},
    "v5e": {"*": DEFAULT_PARAMS.to_dict()},
    "v5p": {"*": DEFAULT_PARAMS.to_dict()},
    "v6e": {"*": DEFAULT_PARAMS.to_dict()},
}


def default_q_pack(n_groups: int, head_size: int) -> int:
    """Largest packing factor p dividing ``n_groups`` with
    ``p * head_size <= 128`` (one lane tile); 1 when ``head_size`` already
    fills a lane.  pythia-14m (G=4, hs=32) packs 4; tiny-llama (G=4,
    hs=64) packs 2; anything with hs >= 128 packs 1."""
    best = 1
    for p in range(1, n_groups + 1):
        if n_groups % p == 0 and p * head_size <= 128:
            best = p
    return best


def geometry_key(
    n_head: int,
    n_groups: int,
    head_size: int,
    kv_dtype: Optional[str],
    block_size: int,
) -> str:
    """The tuning-table row key for one attention geometry."""
    kv = kv_dtype or "fp"
    return f"{n_head}h{n_groups}g{head_size}hs/{kv}/bs{block_size}"


def load_tuning_table(path: str) -> Dict[str, Any]:
    """Read an `mdi-tune` JSON artifact: ``{"device_kind": ...,
    "entries": {geometry_key: params_dict}}`` (a bare entries mapping is
    accepted too)."""
    with open(path) as f:
        d = json.load(f)
    if not isinstance(d, dict):
        raise ValueError(f"tuning table {path}: expected a JSON object")
    if "entries" in d:
        return d
    return {"device_kind": None, "entries": d}


def save_tuning_table(
    path: str,
    device_kind: Optional[str],
    entries: Dict[str, Dict[str, Any]],
    timings_us: Optional[Dict[str, Any]] = None,
) -> None:
    """Persist a tuning table as the `mdi-tune` JSON artifact."""
    doc: Dict[str, Any] = {"device_kind": device_kind, "entries": entries}
    if timings_us:
        doc["timings_us"] = timings_us
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def _lookup(entries: Dict[str, Any], key: str) -> Optional[Dict[str, Any]]:
    if key in entries:
        return entries[key]
    return entries.get("*")


def resolve_kernel_params(
    n_head: int,
    n_groups: int,
    head_size: int,
    block_size: int,
    kv_dtype: Optional[str] = None,
    device_kind: Optional[str] = None,
    table_path: Optional[str] = None,
    params: Optional[KernelParams] = None,
) -> Tuple[KernelParams, Dict[str, Any]]:
    """Pick the kernel parameters for one geometry, host-side.

    Returns ``(resolved KernelParams, meta)`` with
    ``meta = {"tuned", "table_source", "key"}``.  ``tuned`` is True only
    when a user tuning table supplied the entry; the committed builtin
    defaults and the conservative fallback both report ``tuned=False``.
    The lookup is pure host computation on static values — resolving at
    trace time adds zero recompiles.
    """
    from mdi_llm_tpu.obs.roofline import normalize_device_kind

    key = geometry_key(n_head, n_groups, head_size, kv_dtype, block_size)
    meta: Dict[str, Any] = {"tuned": False, "table_source": None, "key": key}
    if params is not None:
        meta["table_source"] = "explicit"
        return params.resolved(block_size, n_groups, head_size), meta
    path = table_path or os.environ.get(TUNE_TABLE_ENV)
    if path:
        table = load_tuning_table(path)  # a bad path/file should be loud
        entry = _lookup(table.get("entries", {}), key)
        if entry is not None:
            meta["tuned"] = True
            meta["table_source"] = f"file:{path}"
            return (
                KernelParams.from_dict(entry).resolved(
                    block_size, n_groups, head_size
                ),
                meta,
            )
    norm = normalize_device_kind(device_kind)
    if norm:
        entry = _lookup(BUILTIN_TUNING_TABLES[norm], key)
        if entry is not None:
            meta["table_source"] = f"builtin:{norm}"
            return (
                KernelParams.from_dict(entry).resolved(
                    block_size, n_groups, head_size
                ),
                meta,
            )
    meta["table_source"] = "conservative"
    return DEFAULT_PARAMS.resolved(block_size, n_groups, head_size), meta


def validate_kernel_params(
    params: KernelParams,
    block_size: int,
    n_groups: int,
    head_size: int,
) -> List[str]:
    """Problems with a RESOLVED entry for one geometry, as actionable
    strings (empty = valid).  The kernel builder raises on these; mdi-audit
    reports them as ``bad-kernel-tuning`` errors before any compile."""
    problems: List[str] = []
    kv = params.kv_step or 0
    if kv < 1 or block_size % kv != 0:
        problems.append(
            f"kv_step={params.kv_step} must be a positive divisor of "
            f"block_size={block_size} (each paged block is walked in "
            "block_size/kv_step sub-steps)"
        )
    qp = params.q_pack or 0
    if qp < 1 or n_groups % qp != 0:
        problems.append(
            f"q_pack={params.q_pack} must be a positive divisor of "
            f"n_query_groups={n_groups} (it folds whole KV groups into "
            "one block-diagonal matmul)"
        )
    if params.scratch_width < 1:
        problems.append(
            f"scratch_width={params.scratch_width} must be >= 1 (lane "
            "width of the online-softmax m/l scratch; 128 is one lane)"
        )
    return problems


def estimate_kernel_vmem(
    n_head: int,
    n_groups: int,
    head_size: int,
    n_tokens: int,
    block_size: int,
    params: KernelParams,
    kv_dtype: Optional[str] = None,
) -> int:
    """Conservative VMEM footprint of one kernel instance in bytes: the
    packed q block + output, double-buffered K/V (+scale) sub-blocks, the
    per-token position vector, and the online-softmax scratch.  Audited
    against `obs/roofline.device_vmem_bytes` by ``bad-kernel-tuning``."""
    p = params.resolved(block_size, n_groups, head_size)
    rows = n_head * n_tokens
    kv_item = 1 if kv_dtype == "int8" else 4
    q_bytes = n_head * n_tokens * head_size * 4  # q block, f32 upper bound
    out_bytes = q_bytes
    # K and V sub-blocks, x2 for pipelined double buffering
    kv_bytes = 2 * 2 * (p.kv_step or block_size) * n_groups * head_size
    kv_bytes *= kv_item
    scale_bytes = (2 * 2 * n_groups * 4) if kv_dtype == "int8" else 0
    qpos_bytes = n_tokens * 4
    scratch = 2 * rows * p.scratch_width * 4 + rows * head_size * 4
    return q_bytes + out_bytes + kv_bytes + scale_bytes + qpos_bytes + scratch


# ---------------------------------------------------------------------------
# on-device sweep (mdi-tune)
# ---------------------------------------------------------------------------


def candidate_params(
    block_size: int, n_groups: int, head_size: int
) -> List[KernelParams]:
    """The sweep grid for one geometry: every kv_step that divides
    block_size (>= 8 where possible), every q_pack dividing n_query_groups
    that fits a lane tile, one-lane scratch."""
    kv_steps = [
        d
        for d in range(1, block_size + 1)
        if block_size % d == 0 and (d >= 8 or d == block_size)
    ]
    q_packs = [
        p
        for p in range(1, n_groups + 1)
        if n_groups % p == 0 and (p == 1 or p * head_size <= 128)
    ]
    return [
        KernelParams(kv_step=kv, q_pack=qp, scratch_width=128)
        for kv in kv_steps
        for qp in q_packs
    ]


def _make_case(n_head, n_groups, head_size, block_size, max_blocks,
               n_tokens, n_slots, kv_dtype):
    """Deterministic synthetic ragged batch: a mixed decode+prefill span
    layout over a shuffled paged pool, the exact operand set the unified
    kernel takes."""
    import jax
    import jax.numpy as jnp

    num_blocks = 1 + n_slots * max_blocks
    key = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(
        kq, (1, n_head, n_tokens, head_size), dtype=jnp.float32
    )
    k_pool = jax.random.normal(
        kk, (num_blocks, block_size, n_groups, head_size), dtype=jnp.float32
    )
    v_pool = jax.random.normal(
        kv_, (num_blocks, block_size, n_groups, head_size), dtype=jnp.float32
    )
    if kv_dtype == "int8":
        def quant(pool):
            s = jnp.max(jnp.abs(pool), axis=(1, 3)) / 127.0  # (NB, G)
            qv = jnp.round(pool / s[:, None, :, None]).astype(jnp.int8)
            return {"q": qv, "scale": s.astype(jnp.float32)}

        k_pool, v_pool = quant(k_pool), quant(v_pool)
    tables = (
        1 + jnp.arange(n_slots * max_blocks, dtype=jnp.int32)
    ).reshape(n_slots, max_blocks)
    # spans: slot 0 takes the leftover-width "prefill" run, the rest are
    # single-token decode lanes — the serving engine's mixed-step shape
    decode = n_slots - 1
    first = n_tokens - decode
    q_len = jnp.array([first] + [1] * decode, dtype=jnp.int32)
    q_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         first + jnp.arange(decode, dtype=jnp.int32)]
    )
    window = max_blocks * block_size
    pos = [jnp.arange(first, dtype=jnp.int32)]
    pos += [jnp.full((1,), window - 1 - i, jnp.int32) for i in range(decode)]
    q_pos = jnp.concatenate(pos)
    lens = jnp.maximum(
        q_pos[jnp.clip(q_start, 0, n_tokens - 1)] + q_len, 1
    ).astype(jnp.int32)
    return q, k_pool, v_pool, tables, q_start, q_len, lens, q_pos


# serving-engine mixed-step geometries, lifted from the serving-cb /
# serving-open bench rows: the default ServingConfig packs
# max_batch(8) + prefill_chunk(128) = 136 tokens into one ragged span
# batch over 8 slots, and steady-state decode is 8 single-token lanes.
# max_blocks=16 gives the prefill span a 256-token window to sit in.
SERVE_TRACE_CASES: List[Dict[str, int]] = [
    {"n_tokens": 136, "n_slots": 8, "max_blocks": 16},
    {"n_tokens": 8, "n_slots": 8, "max_blocks": 16},
]


def _time_us(fn, reps: int) -> float:
    """Best-of-reps wall time of `fn()` in microseconds.  The device sync
    per rep is the measurement, not a hazard."""
    fn().block_until_ready()  # mdi-lint: disable=host-sync -- warmup; timing harness
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn().block_until_ready()  # mdi-lint: disable=host-sync -- the sync IS the measurement
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def autotune(
    n_head: int,
    n_groups: int,
    head_size: int,
    block_size: int = 16,
    max_blocks: int = 8,
    n_tokens: int = 64,
    n_slots: int = 4,
    kv_dtype: Optional[str] = None,
    reps: int = 10,
    interpret: Optional[bool] = None,
    cases: Optional[List[Dict[str, int]]] = None,
    candidates: Optional[List[KernelParams]] = None,
) -> Tuple[KernelParams, List[Dict[str, Any]]]:
    """Sweep `candidate_params` for one geometry on the current backend
    and return ``(winner, results)``.  Each candidate first passes the
    ``bad-kernel-tuning`` preflight (divisibility via
    `validate_kernel_params`, VMEM estimate vs
    `obs/roofline.device_vmem_bytes`); rejects are never timed and their
    rows carry ``params`` and ``rejected`` (the reasons) instead of
    ``us``, so the persisted artifact records WHY an entry is absent.
    Survivors are timed over every case in ``cases`` (ragged span-batch
    geometries; default: the single n_tokens/n_slots case from the
    arguments) and ranked by total time.  Off-TPU the sweep runs the
    kernel in interpret mode — the timings are meaningless for
    performance but exercise every candidate, which is what CPU CI
    wants."""
    import jax

    from mdi_llm_tpu.obs.roofline import device_vmem_bytes
    from mdi_llm_tpu.ops.ragged_paged_attention import ragged_paged_attention

    with jax.named_scope("mdi_tune_autotune"):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if cases is None:
            cases = [{"n_tokens": n_tokens, "n_slots": n_slots,
                      "max_blocks": max_blocks}]
        made = [
            _make_case(
                n_head, n_groups, head_size, block_size,
                c.get("max_blocks", max_blocks),
                c["n_tokens"], c["n_slots"], kv_dtype,
            )
            for c in cases
        ]
        vmem_budget = device_vmem_bytes(jax.devices()[0].device_kind)
        worst_tokens = max(c["n_tokens"] for c in cases)
        if candidates is None:
            candidates = candidate_params(block_size, n_groups, head_size)
        results: List[Dict[str, Any]] = []
        for cand in candidates:
            resolved = cand.resolved(block_size, n_groups, head_size)
            problems = validate_kernel_params(
                resolved, block_size, n_groups, head_size
            )
            need = estimate_kernel_vmem(
                n_head, n_groups, head_size, worst_tokens, block_size,
                resolved, kv_dtype=kv_dtype,
            )
            if need > vmem_budget:
                problems.append(
                    f"estimated VMEM {need} B exceeds the {vmem_budget} B "
                    "budget for this device kind"
                )
            if problems:
                results.append({"params": cand.to_dict(),
                                "rejected": "; ".join(problems)})
                continue
            total = 0.0
            for case in made:
                q, k_pool, v_pool, tables, q_start, q_len, lens, q_pos = case
                fn = jax.jit(  # mdi-lint: disable=jit-in-loop -- one compile per candidate IS the sweep
                    lambda q_, cand_=cand, k_pool=k_pool, v_pool=v_pool,
                    tables=tables, q_start=q_start, q_len=q_len, lens=lens,
                    q_pos=q_pos: ragged_paged_attention(
                        q_, k_pool, v_pool, tables, q_start, q_len, lens,
                        q_pos, scale=1.0 / head_size ** 0.5, params=cand_,
                        interpret=interpret,
                    )
                )
                total += _time_us(lambda fn=fn, q=q: fn(q), reps)
            results.append({"params": cand.to_dict(), "us": total})
        timed = [r for r in results if "us" in r]
        if not timed:
            raise ValueError(
                "every candidate was rejected by the bad-kernel-tuning "
                "preflight for this geometry: "
                + "; ".join(r["rejected"] for r in results)
            )
        best = min(timed, key=lambda r: r["us"])
    return KernelParams.from_dict(best["params"]), results


def main(argv: Optional[List[str]] = None) -> int:
    """``mdi-tune``: sweep the unified ragged paged-attention kernel's
    block/grid parameters for one model geometry on THIS device and
    persist the winner as a JSON tuning table (read back via
    ``MDI_TUNE_TABLE`` or ``--table`` paths elsewhere)."""
    ap = argparse.ArgumentParser(
        prog="mdi-tune",
        description=(
            "Autotune the unified ragged paged-attention kernel "
            "(kv_step / q_pack / scratch_width) for one model geometry on "
            "the current device, and write the winning entries as a JSON "
            "tuning table.  Point MDI_TUNE_TABLE at the artifact to serve "
            "with it; serving resolves the table at trace time, so tuned "
            "parameters add zero post-warmup recompiles."
        ),
    )
    ap.add_argument(
        "--model", default=None,
        help="model config name (Config.from_name) supplying "
        "n_head/n_query_groups/head_size; overridden by the explicit "
        "geometry flags below",
    )
    ap.add_argument("--n-head", type=int, default=None,
                    help="attention heads (with --n-kv-heads/--head-size)")
    ap.add_argument("--n-kv-heads", type=int, default=None,
                    help="KV groups (n_query_groups)")
    ap.add_argument("--head-size", type=int, default=None,
                    help="per-head dimension")
    ap.add_argument("--block-size", type=int, default=16,
                    help="paged-KV block size (ServingConfig.block_size)")
    ap.add_argument("--kv-dtype", default="fp", choices=["fp", "int8"],
                    help="pool dtype family to tune for")
    ap.add_argument("--tokens", type=int, default=None,
                    help="packed query tokens in the sweep batch (pins a "
                    "single case; default: a 64-token case PLUS the "
                    "serve-trace mixed-step geometries)")
    ap.add_argument("--slots", type=int, default=None,
                    help="ragged slots in the sweep batch (see --tokens)")
    ap.add_argument("--max-blocks", type=int, default=8,
                    help="blocks per slot table in the sweep batch")
    ap.add_argument("--reps", type=int, default=10,
                    help="timing repetitions per candidate (best-of)")
    ap.add_argument("--out", default="mdi-tune.json",
                    help="tuning-table JSON artifact to write")
    ap.add_argument(
        "--interpret", action="store_true",
        help="force Pallas interpret mode (the off-TPU default; timings "
        "then rank the interpreter, not the hardware)",
    )
    args = ap.parse_args(argv)

    if args.model:
        from mdi_llm_tpu.config import Config

        cfg = Config.from_name(args.model)
        n_head = args.n_head or cfg.n_head
        n_groups = args.n_kv_heads or cfg.n_query_groups
        head_size = args.head_size or cfg.head_size
    else:
        if None in (args.n_head, args.n_kv_heads, args.head_size):
            ap.error("pass --model NAME or all of --n-head/--n-kv-heads/"
                     "--head-size")
        n_head, n_groups = args.n_head, args.n_kv_heads
        head_size = args.head_size

    import jax

    device = jax.devices()[0]
    kv_dtype = None if args.kv_dtype == "fp" else args.kv_dtype
    interpret = True if args.interpret else None
    if args.tokens is None and args.slots is None:
        # default case list: the classic 64-token sweep batch plus the
        # serving engine's mixed-step geometries (serving-cb/serving-open
        # token-budget packed spans), ranked by total time across all
        cases = [{"n_tokens": 64, "n_slots": 4,
                  "max_blocks": args.max_blocks}] + SERVE_TRACE_CASES
    else:
        cases = [{"n_tokens": args.tokens or 64, "n_slots": args.slots or 4,
                  "max_blocks": args.max_blocks}]
    best, results = autotune(
        n_head, n_groups, head_size,
        block_size=args.block_size, max_blocks=args.max_blocks,
        kv_dtype=kv_dtype, reps=args.reps, interpret=interpret,
        cases=cases,
    )
    key = geometry_key(n_head, n_groups, head_size, kv_dtype,
                       args.block_size)
    default_us = next(
        (r["us"] for r in results
         if "us" in r and KernelParams.from_dict(r["params"])
         == DEFAULT_PARAMS.resolved(args.block_size, n_groups, head_size)),
        None,
    )
    save_tuning_table(
        args.out, device.device_kind, {key: best.to_dict()},
        timings_us={key: results},
    )
    timed = [r for r in results if "us" in r]
    for r in sorted(timed, key=lambda r: r["us"]):
        mark = " <-- best" if r["params"] == best.to_dict() else ""
        print(f"  {r['params']}  {r['us']:10.1f} us{mark}")
    for r in results:
        if "rejected" in r:
            print(f"  {r['params']}  rejected: {r['rejected']}")
    if default_us:
        best_us = min(r["us"] for r in timed)
        print(f"tuned vs default: {default_us / best_us:.2f}x "
              f"({best_us:.1f} vs {default_us:.1f} us)")
    print(f"{key} on {device.device_kind}: wrote {args.out}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
