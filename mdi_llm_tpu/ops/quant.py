"""Weight-only int8 quantization for decode-time memory bandwidth.

Beyond-reference capability (the reference runs fp16/bf16 only;
`gptserver.py:199-209` dtype selection): batched autoregressive decode on
TPU is HBM-bandwidth-bound on weight reads, so storing linear weights as
per-output-channel symmetric int8 halves the bytes/step versus bf16.  The
dequantize stays INSIDE the matmul:

    y = einsum(x, q.astype(x.dtype)) * scale        # scale: per out channel

which is algebraically identical to einsum(x, q*scale) because the scale
factors out of the contraction, and lets XLA fuse the int8→bf16 convert
into the dot's operand read instead of materializing a bf16 copy.

Quantized layout: a linear's param dict {"weight": (..., out, in)} becomes
{"weight_q": int8 (..., out, in), "scale": f32 (..., out)}.  1-D weights
(norms), biases, and the embedding table (gather path, also the tied head)
are left in the original dtype.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = Any

# param-tree keys never quantized: embeddings feed gathers and tied heads;
# norm weights are vectors (per-layer-stacked they look 2-D, hence by name)
SKIP_KEYS = ("wte", "wpe", "norm_1", "norm_2", "ln_f")


def quantize_tensor(w: np.ndarray):
    """Per-output-channel symmetric int8: scale over the last (input) axis.
    Works for stacked layouts too ((L, out, in) → scale (L, out))."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=-1)
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(w / safe[..., None]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_tensor(q: np.ndarray, scale: np.ndarray, dtype=np.float32):
    return (np.asarray(q, np.float32) * np.asarray(scale, np.float32)[..., None]).astype(dtype)


def is_quantized(p: Params) -> bool:
    return isinstance(p, dict) and "weight_q" in p


def quantize_params(params: Params, skip: Sequence[str] = SKIP_KEYS) -> Params:
    """Walk a param tree, replacing every >=2-D "weight" (outside `skip`
    subtrees) with int8 weight_q + f32 scale.  Biases/norm weights pass
    through unchanged."""

    def walk(node, name):
        if not isinstance(node, dict):
            return node
        if name in skip:
            return node
        out = {}
        for k, v in node.items():
            if k == "weight" and np.asarray(v).ndim >= 2:
                q, s = quantize_tensor(np.asarray(v))
                out["weight_q"], out["scale"] = q, s
            else:
                out[k] = walk(v, k)
        return out

    return walk(params, "")


def quantized_einsum(spec: str, x: jnp.ndarray, p: Params) -> jnp.ndarray:
    """einsum against a (possibly) quantized weight dict.  `spec` contracts
    x with the stored (out, in)-layout weight; the per-out-channel scale is
    applied to the result (exact: it factors out of the contraction)."""
    if is_quantized(p):
        y = jnp.einsum(spec, x, p["weight_q"].astype(x.dtype))
        return y * p["scale"].astype(x.dtype)
    return jnp.einsum(spec, x, p["weight"])
