"""Int8 quantization (weight-only or W8A8) for decode-time memory bandwidth.

Beyond-reference capability (the reference runs fp16/bf16 only;
`gptserver.py:199-209` dtype selection): batched autoregressive decode on
TPU is HBM-bandwidth-bound on weight reads, so storing linear weights as
per-output-channel symmetric int8 halves the bytes/step versus bf16.  The
dequantize stays INSIDE the matmul:

    y = einsum(x, q.astype(x.dtype)) * scale        # scale: per out channel

which is algebraically identical to einsum(x, q*scale) because the scale
factors out of the contraction, and lets XLA fuse the int8→bf16 convert
into the dot's operand read instead of materializing a bf16 copy.

Quantized layout: a linear's param dict {"weight": (..., out, in)} becomes
{"weight_q": int8 (..., out, in), "scale": f32 (..., out)}.  1-D weights
(norms), biases, and the embedding table (gather path, also the tied head)
are left in the original dtype.

Two execution modes, chosen at quantization time:

- `mode="w8"` (default): weight-only — the int8 weight is upcast to the
  activation dtype inside the matmul.  Exact numerics up to the weight
  rounding.
- `mode="w8a8"`: activations are ALSO quantized per token (dynamic
  symmetric int8), and the contraction runs int8×int8→int32 — on TPU v5e
  this hits the MXU's double-rate int8 path and reads no bf16 weight copy
  at all.  Stored under key "weight_q8" so the einsum can dispatch without
  any plumbing; slightly coarser numerics (pinned by tests).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = Any

# CLI/engine flag → storage-mode mapping, shared by every quantizing entry
# point (Generator, PipelineEngine, bench)
FLAG_TO_MODE = {"int8": "w8", "w8a8": "w8a8", "int4": "w4"}

# param-tree keys never quantized: embeddings feed gathers and tied heads;
# norm weights are vectors (per-layer-stacked they look 2-D, hence by name)
SKIP_KEYS = ("wte", "wpe", "norm_1", "norm_2", "ln_f")


def quantize_tensor(w: np.ndarray):
    """Per-output-channel symmetric int8: scale over the last (input) axis.
    Works for stacked layouts too ((L, out, in) → scale (L, out))."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=-1)
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(w / safe[..., None]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_tensor(q: np.ndarray, scale: np.ndarray, dtype=np.float32):
    return (np.asarray(q, np.float32) * np.asarray(scale, np.float32)[..., None]).astype(dtype)


W4_GROUP = 128  # input-axis group size for int4 scales


def w4_group_size(in_d: int, group: int = W4_GROUP) -> int:
    """Largest power-of-two divisor of `in_d` that is <= `group` — the
    actual int4 scale-group width for an input dim (shared by the real
    quantizer and the synthetic bench initializer so their scale shapes
    agree for every in_d)."""
    g = min(group, in_d)
    while in_d % g:
        g //= 2
    return g


def quantize_tensor4(w: np.ndarray, group: int = W4_GROUP):
    """Group-wise symmetric int4, packed two nibbles per int8 byte.

    The int4 dtype itself is avoided on purpose: some backends cannot
    re-lay-out S4 arrays at jit boundaries (observed on the remote-attached
    v5e), while packed int8 moves everywhere and the unpack is two in-graph
    shifts that fuse into the consuming matmul.

    Returns (packed int8 (..., out, in/2), scale f32 (..., out, in/group)).
    Group scales sit along the *contracted* axis, so dequantization must
    happen before the dot (unlike the per-out-channel int8 path where the
    scale factors out)."""
    w = np.asarray(w, np.float32)
    in_d = w.shape[-1]
    if in_d % 2:
        raise ValueError(f"int4 packing needs an even input dim, got {in_d}")
    g = w4_group_size(in_d, group)
    wg = w.reshape(*w.shape[:-1], in_d // g, g)
    amax = np.max(np.abs(wg), axis=-1)
    scale = (amax / 7.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(wg / safe[..., None]), -7, 7).astype(np.int8)
    q = q.reshape(*w.shape[:-1], in_d)
    lo, hi = q[..., 0::2], q[..., 1::2]
    packed = ((lo & 0x0F) | (hi << 4)).astype(np.int8)
    return packed, scale


def unpack_w4(packed: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """In-graph nibble unpack + group dequant → (..., out, in) in `dtype`."""
    # arithmetic shifts on int8 sign-extend: (p << 4) >> 4 is the low nibble,
    # p >> 4 the high one
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    q = jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], -1)
    in_d = q.shape[-1]
    n_g = scale.shape[-1]
    qg = q.reshape(*q.shape[:-1], n_g, in_d // n_g).astype(scale.dtype)
    w = qg * scale[..., None]
    return w.reshape(*q.shape[:-1], in_d).astype(dtype)


def is_quantized(p: Params) -> bool:
    return isinstance(p, dict) and (
        "weight_q" in p or "weight_q8" in p or "weight_q4" in p
    )


def tree_has_quantized(params: Params) -> bool:
    """True if any subtree is a quantized linear — detects pre-quantized
    checkpoints structurally, independent of any --quantize flag (a
    prepare_model --quantize sibling loads with quantize='none')."""
    if isinstance(params, dict):
        return is_quantized(params) or any(
            tree_has_quantized(v) for v in params.values()
        )
    return False


def quantize_params(
    params: Params, skip: Sequence[str] = SKIP_KEYS, mode: str = "w8"
) -> Params:
    """Walk a param tree, replacing every >=2-D "weight" (outside `skip`
    subtrees) with int8 weight_q (+ f32 scale).  Biases/norm weights pass
    through unchanged.  `mode` selects the execution path ("w8" weight-only
    upcast vs "w8a8" full int8 matmul) via the storage key."""
    if mode not in ("w8", "w8a8", "w4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    wkey = {"w8": "weight_q", "w8a8": "weight_q8", "w4": "weight_q4"}[mode]

    def walk(node, name):
        if not isinstance(node, dict):
            return node
        if name in skip:
            return node
        out = {}
        for k, v in node.items():
            if k == "weight" and np.asarray(v).ndim >= 2:  # mdi-lint: disable=host-sync -- one-time host-side quantization walk
                if mode == "w4":
                    q, s = quantize_tensor4(np.asarray(v))  # mdi-lint: disable=host-sync -- one-time host-side quantization walk
                else:
                    q, s = quantize_tensor(np.asarray(v))  # mdi-lint: disable=host-sync -- one-time host-side quantization walk
                out[wkey], out["scale"] = q, s
            else:
                out[k] = walk(v, k)
        return out

    return walk(params, "")


def init_quantized_params(cfg, seed: int = 0, mode: str = "w8", dtype=None):
    """Random ALREADY-QUANTIZED parameters for benchmarking large models:
    builds the int8 linears directly (uniform int8 with scales chosen so the
    dequantized std matches `init_params`, incl. the 1/sqrt(2L) output-proj
    scaling) so an 8B-class model never exists in f32/bf16 — peak footprint
    is the int8 tree itself.  Norms/embeddings/head are bf16 as in real
    quantized checkpoints.  For throughput benchmarking, NOT accuracy work
    (the int8 values are uniform, not rounded gaussians)."""
    import ml_dtypes

    if mode not in ("w8", "w8a8", "w4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    wkey = {"w8": "weight_q", "w8a8": "weight_q8", "w4": "weight_q4"}[mode]
    np_dtype = ml_dtypes.bfloat16 if dtype in (None, jnp.bfloat16) else np.dtype(dtype)
    rng = np.random.default_rng(seed)
    L, D, V, I = cfg.n_layer, cfg.n_embd, cfg.padded_vocab_size, cfg.intermediate_size
    std = 0.02
    proj_std = std / (2 * L) ** 0.5  # ≡ init_params output-projection scaling

    def qlin(out_d, in_d, s=std, lead=None):
        """Quantized linear with leading stack dims `lead` (default (L,)):
        matches quantize_tensor's last-axis scale convention for any rank,
        so MoE expert stacks (L, E, out, in) mirror the real quantizer."""
        lead = (L,) if lead is None else lead
        if mode == "w4":
            # random packed nibbles in [-8, 7]; rms 4.61 → matching scale
            packed = rng.integers(-128, 128, (*lead, out_d, in_d // 2), dtype=np.int8)
            g = w4_group_size(in_d)  # same halving rule as quantize_tensor4
            return {
                wkey: packed,
                "scale": np.full((*lead, out_d, in_d // g), s / 4.61, np.float32),
            }
        q = rng.integers(-127, 128, size=(*lead, out_d, in_d), dtype=np.int8)
        # per-channel scale so the dequantized std matches init_params
        # (73.3 = rms of uniform int8 in [-127, 127])
        return {wkey: q, "scale": np.full((*lead, out_d), s / 73.3, np.float32)}

    def norm():
        p = {"weight": np.ones((L, D), np_dtype)}
        if cfg.norm_class_name == "LayerNorm" and cfg.bias:
            p["bias"] = np.zeros((L, D), np_dtype)
        return p

    def emb(rows):
        return (rng.standard_normal((rows, D)).astype(np.float32) * 0.02).astype(np_dtype)

    attn = {
        "qkv": qlin(cfg.qkv_size, D),
        "proj": qlin(D, cfg.attn_out_size, proj_std),
    }
    if cfg.mlp_class_name == "GptNeoxMLP":
        mlp = {"fc": qlin(I, D), "proj": qlin(D, I, proj_std)}
    elif cfg.mlp_class_name in ("LLaMAMLP", "GemmaMLP"):
        mlp = {
            "fc_1": qlin(I, D),
            "fc_2": qlin(I, D),
            "proj": qlin(D, I, proj_std),
        }
    elif cfg.mlp_class_name == "LLaMAMoE":
        E = cfg.n_expert
        mlp = {
            "gate": qlin(E, D),  # (L, E, D): router logits einsum
            "experts": {
                "fc_1": qlin(I, D, lead=(L, E)),
                "fc_2": qlin(I, D, lead=(L, E)),
                "proj": qlin(D, I, proj_std, lead=(L, E)),
            },
        }
    else:
        raise NotImplementedError(
            f"init_quantized_params: unknown mlp_class_name "
            f"{cfg.mlp_class_name!r}"
        )
    blocks = {"norm_1": norm(), "attn": attn, "mlp": mlp}
    if not cfg.shared_attention_norm:
        blocks["norm_2"] = norm()
    params = {
        "wte": {"weight": emb(V)},
        "blocks": blocks,
        "ln_f": {"weight": np.ones((D,), np_dtype)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": emb(V)}
    return params


def _apply_scale(spec: str, y: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Multiply `y` (the quantized contraction's result) by the per-channel
    scale, aligned by einsum label rather than trailing-axis broadcasting:
    the scale's dims are the weight subscripts minus the contracted last
    one, which need not be trailing in the output (the expert-parallel
    dispatch uses "emd,eid->emi", where scale (e, i) straddles m)."""
    xin, out = spec.split("->")
    _, w_sub = xin.split(",")
    kept = w_sub[:-1]  # quantize scale shape == weight dims minus the last
    return jnp.einsum(f"{out},{kept}->{out}", y, scale)


def quantized_einsum(spec: str, x: jnp.ndarray, p: Params) -> jnp.ndarray:
    """einsum against a (possibly) quantized weight dict.  `spec` contracts
    x with the stored (out, in)-layout weight; the per-out-channel scale is
    applied to the result (exact: it factors out of the contraction)."""
    with jax.named_scope("quantized_einsum"):
        if "weight_q8" in p:
            # dynamic per-token symmetric activation quant + int8×int8 MXU dot
            amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
            xs = jnp.maximum(amax / 127.0, 1e-10)
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs), -127, 127).astype(
                jnp.int8
            )
            y = jnp.einsum(spec, xq, p["weight_q8"], preferred_element_type=jnp.int32)
            # xs covers x's leading (token/batch) dims; pad trailing singleton
            # axes so it broadcasts over whatever output dims the spec appended
            # (1 for plain linears, 2 for the expert einsums)
            extra = y.ndim - (x.ndim - 1)
            xs = xs.reshape(xs.shape[:-1] + (1,) * max(extra, 1))
            return _apply_scale(spec, y.astype(jnp.float32) * xs, p["scale"]).astype(
                x.dtype
            )
        if "weight_q" in p:
            y = jnp.einsum(spec, x, p["weight_q"].astype(x.dtype))
            return _apply_scale(spec, y, p["scale"].astype(x.dtype))
        if "weight_q4" in p:
            return _w4_einsum(spec, x, p)
        return jnp.einsum(spec, x, p["weight"])


def _w4_einsum(spec: str, x: jnp.ndarray, p: Params) -> jnp.ndarray:
    """int4 contraction without ever concatenating the nibble planes.

    A naive unpack (shift → stack → reshape) contains a concatenate, which
    XLA cannot fuse into a dot operand — the dequantized bf16 weights then
    materialize in HBM every step and int4 runs SLOWER than bf16 (measured
    664 vs 2283 tok/s/chip on v5e).  Instead the low/high nibble planes stay
    separate (each is just shift+convert, fusable into its dot), contracting
    the even/odd input positions respectively, and the group scales — which
    lie along the contracted axis and so cannot factor out of a single dot —
    are applied in a second tiny einsum over the kept group axis:

        z[.., out, g] = xe_g · lo_g[out] + xo_g · hi_g[out]
        y[.., out]    = Σ_g z[.., out, g] · scale[out, g]
    """
    xin, out = spec.split("->")
    x_sub, w_sub = xin.split(",")
    if x_sub[-1] != w_sub[-1] or "g" in spec or "k" in spec:
        # explicit raise (not assert): the contract must survive python -O,
        # or an unsupported spec would silently contract the wrong axes
        raise NotImplementedError(
            f"_w4_einsum requires a last-subscript contraction and reserves "
            f"letters 'g'/'k' for the group axes; got {spec!r}"
        )
    packed, scale = p["weight_q4"], p["scale"]
    nG = scale.shape[-1]
    Gh = packed.shape[-1] // nG  # per-plane group width
    # arithmetic shifts on int8 sign-extend the nibbles
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    xe = x[..., 0::2].reshape(*x.shape[:-1], nG, Gh)
    xo = x[..., 1::2].reshape(*x.shape[:-1], nG, Gh)
    wl = lo.reshape(*packed.shape[:-1], nG, Gh).astype(x.dtype)
    wh = hi.reshape(*packed.shape[:-1], nG, Gh).astype(x.dtype)
    zspec = f"{x_sub[:-1]}gk,{w_sub[:-1]}gk->{out}g"
    z = jnp.einsum(zspec, xe, wl) + jnp.einsum(zspec, xo, wh)
    return jnp.einsum(f"{out}g,{w_sub[:-1]}g->{out}", z, scale.astype(x.dtype))
