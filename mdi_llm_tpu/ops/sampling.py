"""Token sampling under jit: temperature, top-k, top-p.

Capability parity with the reference sampling helpers
(`/root/reference/src/sub/model.py:42-90`: `sample_top_p`, `sample`), built
on `jax.random` so the whole decode step stays on-device.  Greedy decoding
(temperature == 0) is exact argmax — the parity mode used by the
golden-token tests (SURVEY.md §7 "output parity").

Two surfaces:

- `sample` — host-side convenience: dispatches on Python float values
  (greedy / top-p / top-k).  Fine eagerly; as a STATIC jit argument those
  floats key the compile cache on their value (mdi-lint: static-float-arg).
- `sample_traced` + `sample_mode` + `sampling_operands` — the jit-friendly
  split: the branch structure is a tiny static string (`mode`) while
  temperature/top_p ride along as traced f32 scalars, so sweeping
  temperature 0.7 -> 0.8 reuses the same XLA executable.  Token streams
  are identical to `sample` for the matching mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def logits_to_probs(
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """Softmax with temperature and optional top-k clamp (matches the
    reference's order: scale, top-k filter, softmax — model.py:77-90)."""
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        logits = logits / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        # lax.top_k(k) beats a full-vocab sort for the kth threshold
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.nn.softmax(logits, axis=-1)


def _nucleus_filter(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Mask logits outside the smallest set whose cumulative probability
    exceeds `top_p` (always keeping the most probable token).  `top_p` may
    be a Python float or a traced f32 scalar — the math is identical."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # mask tokens whose prefix-sum (exclusive) already exceeded top_p
    exceeded = (cum - sorted_probs) > top_p
    sorted_logits = jnp.where(exceeded, -jnp.inf, sorted_logits)
    # map the threshold back to the unsorted logits: keep logits >= cutoff
    cutoff = jnp.min(
        jnp.where(exceeded, jnp.inf, sorted_logits), axis=-1, keepdims=True
    )
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _topk_filter(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def sample_top_p(
    logits: jnp.ndarray, key: jax.Array, top_p: float, temperature: float = 1.0
) -> jnp.ndarray:
    """Nucleus sampling (reference `sample_top_p`, model.py:42-58)."""
    with jax.named_scope("sample_top_p"):
        logits = logits.astype(jnp.float32)
        if temperature > 0:
            logits = logits / temperature
        return jax.random.categorical(key, _nucleus_filter(logits, top_p), axis=-1)


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Sample next token ids from logits (..., vocab).

    temperature == 0 → greedy argmax (deterministic parity mode).
    Mirrors reference `sample` (model.py:61-74) dispatch order: top-p wins if
    set, else temperature+top-k, else greedy.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    if top_p is not None and 0.0 < top_p < 1.0:
        return sample_top_p(logits, key, top_p, temperature)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        logits = _topk_filter(logits, top_k)
    return jax.random.categorical(key, logits, axis=-1)


# ---------------------------------------------------------------------------
# jit-friendly split: static mode string, traced float knobs
# ---------------------------------------------------------------------------


def sample_mode(
    temperature: float, top_k: Optional[int] = None, top_p: Optional[float] = None
) -> str:
    """The STATIC dispatch key for `sample_traced`, derived host-side from
    the Python-valued knobs with exactly `sample`'s branch order.  Only this
    tiny hashable string (and the int `top_k`) belongs in static_argnames —
    never the floats themselves."""
    if temperature == 0.0:
        return "greedy"
    if top_p is not None and 0.0 < top_p < 1.0:
        return "top_p"
    return "top_k"


def sampling_operands(
    temperature: float, top_p: Optional[float]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device operands for `sample_traced`'s traced knobs.  Unused knobs get
    harmless placeholders (1.0) so greedy/top-k calls share one signature;
    XLA dead-code-eliminates them from modes that ignore them."""
    t = temperature if temperature and temperature > 0 else 1.0
    p = top_p if top_p is not None else 1.0
    return jnp.asarray(t, jnp.float32), jnp.asarray(p, jnp.float32)


def filtered_logits(
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    mode: str,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """The f32 logits `sample_traced` hands to `jax.random.categorical`:
    temperature-scaled, then nucleus- or top-k-filtered per `mode`.  Split
    out so the speculative rejection verify (`speculative_verify`) draws
    from EXACTLY the distribution the per-step sampler uses — softmaxing
    this array is the verify distribution p."""
    logits = logits.astype(jnp.float32)
    if mode == "greedy":
        return logits
    logits = logits / temperature
    if mode == "top_p":
        logits = _nucleus_filter(logits, top_p)
    elif top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        logits = _topk_filter(logits, top_k)
    return logits


def sample_traced(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    mode: str,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """`sample` for jitted decode steps: `temperature`/`top_p` are traced
    f32 scalars (from `sampling_operands`), so distinct float values reuse
    one executable; only `mode` (from `sample_mode`) and the int `top_k`
    shape the graph.  Token streams match `sample` bit-for-bit for the
    corresponding knob values."""
    with jax.named_scope(f"sample_{mode}"):
        if mode == "greedy":
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(
            key,
            filtered_logits(logits, temperature, top_p, mode=mode, top_k=top_k),
            axis=-1,
        )


def speculative_verify(
    logits: jnp.ndarray,
    draft: jnp.ndarray,
    draft_len: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    mode: str,
    top_k: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rejection-sampled speculative verify for DETERMINISTIC drafts
    (n-gram lookup or a greedy draft model — `p_draft` is a one-hot).

    The standard acceptance rule (Leviathan et al.; Chen et al.) accepts
    draft token d with probability `min(1, p_verify(d) / p_draft(d))` and
    otherwise resamples from the normalized residual
    `max(p_verify - p_draft, 0)`.  With one-hot `p_draft` that reduces to:
    accept d w.p. `p(d)`, else draw from p with d masked out — so each
    emitted token is distributed EXACTLY as the per-step sampler's
    (distribution preservation, draw-for-draw), and at temperature 0
    (`p` one-hot too) it degenerates to exact-match accept.

    Args (all traced; `mode`/`top_k` are the only static knobs, shared
    with `sample_traced` so the compile set stays fixed):
      logits:    (B, K+1, V) — row i is the verify model's successor
                 distribution of input position i (input = pending token
                 followed by the K drafted tokens).
      draft:     (B, K) int32 — drafted tokens (draft[:, i] proposes
                 input position i+1).
      draft_len: (B,) int32 — valid drafts per row (0..K; rows with 0
                 drafts reduce to one plain sample from position 0).
      key:       PRNG key consumed for this verify step.

    Returns (out_tokens (B, K+1) int32, n_emit (B,) int32): row b emits
    `out_tokens[b, :n_emit[b]]` — the accepted draft prefix followed by
    one resampled (on rejection) or bonus (all accepted) token.
    """
    B, K1, V = logits.shape
    K = K1 - 1
    f = filtered_logits(logits, temperature, top_p, mode=mode, top_k=top_k)
    with jax.named_scope("speculative_verify"):
        if mode == "greedy":
            # exact-match accept: emitted greedy successors vs the draft
            g = jnp.argmax(f, axis=-1).astype(jnp.int32)  # (B, K+1)
            match = (g[:, :K] == draft) & (
                jnp.arange(K)[None, :] < draft_len[:, None]
            )
            a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=-1), axis=-1)
            return g, a.astype(jnp.int32) + 1
        probs = jax.nn.softmax(f, axis=-1)  # (B, K+1, V) — the verify p
        ku, kr = jax.random.split(key)
        u = jax.random.uniform(ku, (B, max(K, 1)))[:, :K]
        p_draft_tok = jnp.take_along_axis(
            probs[:, :K, :], draft[..., None], axis=-1
        )[..., 0]  # (B, K): p_i(d_i)
        valid = jnp.arange(K)[None, :] < draft_len[:, None]
        accept = (u < p_draft_tok) & valid
        # accepted length = leading run of accepts
        a = jnp.sum(
            jnp.cumprod(accept.astype(jnp.int32), axis=-1), axis=-1
        ).astype(jnp.int32)  # (B,)
        # position a's draw: the residual (rejected token masked) when a
        # rejection happened, the untouched bonus distribution otherwise
        row_f = jnp.take_along_axis(f, a[:, None, None], axis=1)[:, 0, :]
        rejected = a < draft_len  # (B,)
        rej_tok = jnp.take_along_axis(
            draft, jnp.minimum(a, max(K - 1, 0))[:, None], axis=1
        )[:, 0] if K > 0 else jnp.zeros((B,), jnp.int32)
        masked = jnp.where(
            jnp.arange(V)[None, :] == rej_tok[:, None], -jnp.inf, row_f
        )
        row_f = jnp.where(rejected[:, None], masked, row_f)
        last = jax.random.categorical(kr, row_f, axis=-1).astype(jnp.int32)
        cols = jnp.arange(K1)[None, :]
        padded = jnp.pad(draft, ((0, 0), (0, 1)))  # (B, K+1)
        out = jnp.where(cols < a[:, None], padded, 0)
        out = jnp.where(cols == a[:, None], last[:, None], out)
        return out.astype(jnp.int32), a + 1
