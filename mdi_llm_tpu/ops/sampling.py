"""Token sampling under jit: temperature, top-k, top-p.

Capability parity with the reference sampling helpers
(`/root/reference/src/sub/model.py:42-90`: `sample_top_p`, `sample`), built
on `jax.random` so the whole decode step stays on-device.  Greedy decoding
(temperature == 0) is exact argmax — the parity mode used by the
golden-token tests (SURVEY.md §7 "output parity").

Two surfaces:

- `sample` — host-side convenience: dispatches on Python float values
  (greedy / top-p / top-k).  Fine eagerly; as a STATIC jit argument those
  floats key the compile cache on their value (mdi-lint: static-float-arg).
- `sample_traced` + `sample_mode` + `sampling_operands` — the jit-friendly
  split: the branch structure is a tiny static string (`mode`) while
  temperature/top_p ride along as traced f32 scalars, so sweeping
  temperature 0.7 -> 0.8 reuses the same XLA executable.  Token streams
  are identical to `sample` for the matching mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def logits_to_probs(
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """Softmax with temperature and optional top-k clamp (matches the
    reference's order: scale, top-k filter, softmax — model.py:77-90)."""
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        logits = logits / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        # lax.top_k(k) beats a full-vocab sort for the kth threshold
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.nn.softmax(logits, axis=-1)


def _nucleus_filter(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Mask logits outside the smallest set whose cumulative probability
    exceeds `top_p` (always keeping the most probable token).  `top_p` may
    be a Python float or a traced f32 scalar — the math is identical."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # mask tokens whose prefix-sum (exclusive) already exceeded top_p
    exceeded = (cum - sorted_probs) > top_p
    sorted_logits = jnp.where(exceeded, -jnp.inf, sorted_logits)
    # map the threshold back to the unsorted logits: keep logits >= cutoff
    cutoff = jnp.min(
        jnp.where(exceeded, jnp.inf, sorted_logits), axis=-1, keepdims=True
    )
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _topk_filter(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def sample_top_p(
    logits: jnp.ndarray, key: jax.Array, top_p: float, temperature: float = 1.0
) -> jnp.ndarray:
    """Nucleus sampling (reference `sample_top_p`, model.py:42-58)."""
    with jax.named_scope("sample_top_p"):
        logits = logits.astype(jnp.float32)
        if temperature > 0:
            logits = logits / temperature
        return jax.random.categorical(key, _nucleus_filter(logits, top_p), axis=-1)


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Sample next token ids from logits (..., vocab).

    temperature == 0 → greedy argmax (deterministic parity mode).
    Mirrors reference `sample` (model.py:61-74) dispatch order: top-p wins if
    set, else temperature+top-k, else greedy.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    if top_p is not None and 0.0 < top_p < 1.0:
        return sample_top_p(logits, key, top_p, temperature)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        logits = _topk_filter(logits, top_k)
    return jax.random.categorical(key, logits, axis=-1)


# ---------------------------------------------------------------------------
# jit-friendly split: static mode string, traced float knobs
# ---------------------------------------------------------------------------


def sample_mode(
    temperature: float, top_k: Optional[int] = None, top_p: Optional[float] = None
) -> str:
    """The STATIC dispatch key for `sample_traced`, derived host-side from
    the Python-valued knobs with exactly `sample`'s branch order.  Only this
    tiny hashable string (and the int `top_k`) belongs in static_argnames —
    never the floats themselves."""
    if temperature == 0.0:
        return "greedy"
    if top_p is not None and 0.0 < top_p < 1.0:
        return "top_p"
    return "top_k"


def sampling_operands(
    temperature: float, top_p: Optional[float]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device operands for `sample_traced`'s traced knobs.  Unused knobs get
    harmless placeholders (1.0) so greedy/top-k calls share one signature;
    XLA dead-code-eliminates them from modes that ignore them."""
    t = temperature if temperature and temperature > 0 else 1.0
    p = top_p if top_p is not None else 1.0
    return jnp.asarray(t, jnp.float32), jnp.asarray(p, jnp.float32)


def sample_traced(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    mode: str,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """`sample` for jitted decode steps: `temperature`/`top_p` are traced
    f32 scalars (from `sampling_operands`), so distinct float values reuse
    one executable; only `mode` (from `sample_mode`) and the int `top_k`
    shape the graph.  Token streams match `sample` bit-for-bit for the
    corresponding knob values."""
    with jax.named_scope(f"sample_{mode}"):
        if mode == "greedy":
            return jnp.argmax(logits, axis=-1)
        logits = logits.astype(jnp.float32) / temperature
        if mode == "top_p":
            logits = _nucleus_filter(logits, top_p)
        elif top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
            logits = _topk_filter(logits, top_k)
        return jax.random.categorical(key, logits, axis=-1)
