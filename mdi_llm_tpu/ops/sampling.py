"""Token sampling under jit: temperature, top-k, top-p.

Capability parity with the reference sampling helpers
(`/root/reference/src/sub/model.py:42-90`: `sample_top_p`, `sample`), built
on `jax.random` so the whole decode step stays on-device.  Greedy decoding
(temperature == 0) is exact argmax — the parity mode used by the
golden-token tests (SURVEY.md §7 "output parity").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def logits_to_probs(
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
) -> jnp.ndarray:
    """Softmax with temperature and optional top-k clamp (matches the
    reference's order: scale, top-k filter, softmax — model.py:77-90)."""
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        logits = logits / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        # lax.top_k(k) beats a full-vocab sort for the kth threshold
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.nn.softmax(logits, axis=-1)


def sample_top_p(
    logits: jnp.ndarray, key: jax.Array, top_p: float, temperature: float = 1.0
) -> jnp.ndarray:
    """Nucleus sampling (reference `sample_top_p`, model.py:42-58).

    Keeps the smallest set of tokens whose cumulative probability exceeds
    `top_p` (always including the most probable token), renormalizes, samples.
    """
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        logits = logits / temperature
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # mask tokens whose prefix-sum (exclusive) already exceeded top_p
    exceeded = (cum - sorted_probs) > top_p
    sorted_logits = jnp.where(exceeded, -jnp.inf, sorted_logits)
    # map the threshold back to the unsorted logits: keep logits >= cutoff
    cutoff = jnp.min(
        jnp.where(exceeded, jnp.inf, sorted_logits), axis=-1, keepdims=True
    )
    filtered = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, filtered, axis=-1)


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Sample next token ids from logits (..., vocab).

    temperature == 0 → greedy argmax (deterministic parity mode).
    Mirrors reference `sample` (model.py:61-74) dispatch order: top-p wins if
    set, else temperature+top-k, else greedy.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    if top_p is not None and 0.0 < top_p < 1.0:
        return sample_top_p(logits, key, top_p, temperature)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)
