"""Causal multi-head / grouped-query attention.

TPU-native replacement for the reference's `CausalSelfAttention.scaled_dot_product_attention`
(`/root/reference/src/sub/model.py:632-779`, which delegates to torch SDPA).
Here attention is a pure function over (q, k, v) designed so that XLA fuses
the softmax chain and maps the two matmuls onto the MXU; a Pallas
flash-attention kernel (`mdi_llm_tpu.ops.flash`) can be swapped in for long
sequences.

Masking model: queries carry absolute positions `q_pos` (B, Tq); keys are a
cache of length S where entries at absolute position `k_pos[j] = j` are valid
iff `j <= q_pos[i]` and `j < kv_len`.  This one rule covers prefill
(q_pos = arange(T)) and batched decode (q_pos = per-sample input_pos,
Tq == 1) without separate mask cache machinery (reference builds an explicit
(S, S) bool mask cache, model.py:940-947).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def multihead_attention(
    q: jnp.ndarray,  # (B, n_head, Tq, hs)
    k: jnp.ndarray,  # (B, n_query_groups, Tk, hs)
    v: jnp.ndarray,  # (B, n_query_groups, Tk, hs)
    q_pos: jnp.ndarray,  # (B, Tq) absolute positions of the queries
    kv_valid_len: Optional[jnp.ndarray] = None,  # (B,) number of valid cache slots
    k_pos: Optional[jnp.ndarray] = None,  # (B, Tk) absolute key positions
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Causal attention with implicit GQA (heads grouped over KV heads).

    `k_pos` defaults to cache-slot indexing (absolute position j stored in
    slot j); pass it explicitly for uncached chunks at a nonzero offset.
    Returns (B, n_head, Tq, hs).
    """
    B, n_head, Tq, hs = q.shape
    _, n_groups, Tk, _ = k.shape
    if scale is None:
        scale = 1.0 / (hs**0.5)
    if k.dtype != q.dtype:  # narrow KV cache (e.g. fp8): upcast at the read
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)

    with jax.named_scope("multihead_attention"):
        q_per_kv = n_head // n_groups
        # fold the query heads into groups: (B, G, q_per_kv, Tq, hs)
        qg = q.reshape(B, n_groups, q_per_kv, Tq, hs)

        # logits in f32 for numerical stability on bf16 inputs
        logits = jnp.einsum(
            "bgqth,bgsh->bgqts", qg, k, preferred_element_type=jnp.float32
        )
        logits = logits * scale

        # causal + validity mask from absolute positions
        if k_pos is None:
            k_pos = jnp.broadcast_to(jnp.arange(Tk, dtype=q_pos.dtype), (B, Tk))
        mask = k_pos[:, None, :] <= q_pos[:, :, None]  # (B, Tq, Tk)
        if kv_valid_len is not None:
            slot = jnp.arange(Tk, dtype=q_pos.dtype)
            mask = mask & (slot[None, None, :] < kv_valid_len[:, None, None])
        logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

        probs = jnp.exp(
            logits - jnp.max(logits, axis=-1, keepdims=True)
        )
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        probs = probs.astype(v.dtype)

        out = jnp.einsum("bgqts,bgsh->bgqth", probs, v)
        return out.reshape(B, n_head, Tq, hs)
