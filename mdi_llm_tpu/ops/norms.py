"""Normalization layers as pure functions.

Semantics: RMSNorm matches reference `RMSNorm` (model.py:950-981) including
the Gemma unit-offset variant (weight + 1); LayerNorm matches torch
`nn.LayerNorm` with optional bias.  Accumulation is always float32 (TPU
bf16-safe), cast back to the input dtype at the end.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    add_unit_offset: bool = False,
) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    norm = xf * jax_rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if add_unit_offset:
        w = 1.0 + w
    return (norm * w).astype(x.dtype)


def layer_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    eps: float = 1e-5,
) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax_rsqrt(var + eps) * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def jax_rsqrt(x: jnp.ndarray) -> jnp.ndarray:
    return 1.0 / jnp.sqrt(x)
