"""Paged decode attention over a pooled, block-table-indexed KV cache.

The serving subsystem (`mdi_llm_tpu.serving`) replaces the one-contiguous-
cache-per-run model of `generation.py` with a shared pool of fixed-width
KV blocks: layer cache `(num_blocks, block_size, G, hs)`, and each sequence
owns an ordered list of block ids (its *block table*).  Slot `i` of a
sequence's table holds the KV entries for absolute positions
`[i*block_size, (i+1)*block_size)`, so flattening the table recovers the
contiguous layout and the absolute-position masking contract of
`ops/attention.py` carries over unchanged — key at flattened slot `j` is
valid iff `j <= q_pos`.

Two implementations:

- **lax fallback** (`_paged_attention_lax`): gather the table's blocks into
  a per-sequence contiguous view and call `multihead_attention` on it.
  Bit-for-bit the same softmax chain as the dense op — this is what the
  tier-1 CPU parity tests pin down, and what guarantees the serving engine's
  greedy streams match `Generator.generate`.
- **Pallas kernels**: TPU block-table decode kernels in the spirit of
  "Ragged Paged Attention" (PAPERS.md, arxiv 2604.15464): grid
  `(B, max_blocks)`, the block table rides in as a scalar-prefetch operand
  so the index map DMAs exactly the blocks each sequence owns (unneeded
  trailing grid steps remap to block 0 and skip compute), online-softmax
  accumulation in VMEM scratch.  `_paged_attention_kernel` is the
  single-query (Tq == 1) decode step; `_paged_attention_ragged_kernel`
  generalizes it to **ragged multi-query decode** — each sequence attends
  with up to `Tq` query tokens at its own absolute positions, which is the
  shape the serving engine's batched speculative verify dispatches (K
  drafted tokens + 1 per slot, every slot at a different depth).  Semantics
  are validated against the fallback in interpreter mode; the fallback
  remains the default off-TPU.
- **`paged_prefill`**: the unified serving step's ragged mixed
  prefill+decode attention — every live slot's tokens (decode lanes at 1
  token, prefill chunks at their fed width) packed slot-major into ONE
  query axis, per-slot `q_start/q_len/q_pos` scalar-prefetched, causal
  masking inside each slot's own chunk, one online-softmax row per
  (head, packed token).  Plus the bit-exact per-token gather fallback.

Writes go through `paged_update`: a scatter of the chunk's K/V into
`(block, offset)` slots resolved through the table.  Positions past the
table's coverage (prefill bucket padding) are redirected to block 0, which
the serving pool reserves as a write-only trash block.

**Quantized pools** (`ServingConfig(kv_dtype="int8")`): each of k/v is a
dict `{"q": int8 (num_blocks, block_size, G, hs), "scale": f32
(num_blocks, G)}` — symmetric per-BLOCK-per-KV-group scales, so the side
array costs 4 bytes per (block, group) against block_size*hs int8 payload
bytes (the ~2x capacity win stays real even at small head sizes, where
per-token scales would eat it).  `paged_update` quantizes on scatter with
a monotone scale: the block's scale only ever grows (`.at[].max` over the
written tokens' max-abs/127), and when it grows the block's existing int8
payload is requantized in the same update (gather the written blocks,
rescale by old/new, scatter back — a transient of written blocks only,
never the pool).  Consequences the serving engine relies on, pinned by
tests: a frozen-lane rewrite of the same (token, position) leaves scale
and payload bytes bit-identical, and a block's final scale is independent
of how its tokens were grouped into update calls.  All three kernels
dequantize INSIDE their KV-block loop (`k = int8_block * scale[group]` in
f32, fused after the block DMA) — no gathered-fp pool transient — and the
lax fallbacks run the same dequant-to-f32 math so kernel==fallback parity
holds at int8 exactly like fp.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from mdi_llm_tpu.ops.attention import NEG_INF, multihead_attention

__all__ = [
    "paged_attention",
    "paged_prefill",
    "paged_update",
    "gather_paged_kv",
    "RAGGED_KERNEL_MAX_TQ",
]


def _pool_parts(pool):
    """(payload, scale-or-None) view of a pool: fp pools are bare arrays,
    int8 pools are {"q": int8 blocks, "scale": f32 (num_blocks, G)}."""
    if isinstance(pool, dict):
        return pool["q"], pool["scale"]
    return pool, None


def _quantized_update(pool, new, blk, off):
    """Quantizing scatter into one int8 pool: `new` (N, G, hs) fp values
    land at (blk[n], off[n]) under the block's per-group scale.

    The scale is a monotone running max (`.at[].max` of the written tokens'
    max-abs/127, duplicates folded correctly), so a rewrite of the same
    value at the same slot is byte-idempotent and the final scale is
    independent of how tokens were grouped into update calls.  When a write
    DOES grow a block's scale, the block's existing payload requantizes by
    old/new in the same scatter — the transient is the written blocks only
    (N × block_size × G × hs int8), never a pool-wide or gathered-fp copy.
    """
    q, s = pool["q"], pool["scale"]
    vals = new.astype(jnp.float32)
    tok_scale = jnp.max(jnp.abs(vals), axis=-1) / 127.0  # (N, G)
    new_s = s.at[blk].max(tok_scale)
    old_g = s[blk]  # (N, G) pre-update block scales
    new_g = new_s[blk]  # (N, G) post-update (>= old, monotone)
    # rescale existing payload where the scale grew; an all-zero block
    # (scale 0) maps 0 -> 0 whatever the factor, so the guard only dodges
    # the 0/0
    factor = jnp.where(new_g > 0, old_g / jnp.maximum(new_g, 1e-30), 0.0)
    requant = jnp.round(
        q[blk].astype(jnp.float32) * factor[:, None, :, None]
    ).astype(jnp.int8)
    q = q.at[blk].set(requant)  # duplicate blk entries scatter identical
    # blocks (same source block, same old/new scale), so order is moot
    tok_q = jnp.clip(
        jnp.round(vals / jnp.maximum(new_g, 1e-30)[..., None]), -127, 127
    ).astype(jnp.int8)
    q = q.at[blk, off].set(tok_q)
    return {"q": q, "scale": new_s}


def paged_update(
    k_pool,  # (num_blocks, block_size, G, hs), or int8 {"q", "scale"}
    v_pool,
    k_new: jnp.ndarray,  # (B, T, G, hs)
    v_new: jnp.ndarray,
    block_tables: jnp.ndarray,  # (B, max_blocks) int32
    pos: jnp.ndarray,  # (B, T) absolute positions of the chunk's tokens
):
    """Scatter a chunk's K/V into the pool through the block tables.

    Slot for position p: block `table[p // block_size]`, offset
    `p % block_size`.  Positions whose block index falls outside the table
    (bucket padding past the sequence budget) write to block 0 — the pool's
    reserved trash block — so padding can never corrupt a live block.

    int8 pools quantize on scatter (`_quantized_update`): per-block
    per-group scales grow monotonically and the written blocks requantize
    in place when they do.
    """
    MB = block_tables.shape[1]
    BS = _pool_parts(k_pool)[0].shape[1]
    idx = pos // BS
    blk = jnp.take_along_axis(block_tables, jnp.clip(idx, 0, MB - 1), axis=1)
    blk = jnp.where(idx < MB, blk, 0)
    off = pos % BS
    if isinstance(k_pool, dict):
        blk_f, off_f = blk.reshape(-1), off.reshape(-1)
        G, hs = k_new.shape[-2:]
        k_pool = _quantized_update(
            k_pool, k_new.reshape(-1, G, hs), blk_f, off_f
        )
        v_pool = _quantized_update(
            v_pool, v_new.reshape(-1, G, hs), blk_f, off_f
        )
        return k_pool, v_pool
    k_pool = k_pool.at[blk, off].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[blk, off].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def gather_paged_kv(
    pool,  # (num_blocks, block_size, G, hs), or int8 {"q", "scale"}
    block_tables: jnp.ndarray,  # (B, max_blocks)
) -> jnp.ndarray:
    """Materialize each sequence's contiguous (B, G, S, hs) view,
    S = max_blocks * block_size.  Flattened slot j holds absolute position
    j by the table-layout contract.  int8 pools dequantize to f32 — the
    same `int8 * scale` math the kernels run inside their block loop, so
    the fallback stays the kernels' parity reference at int8 too."""
    if isinstance(pool, dict):
        g = pool["q"][block_tables].astype(jnp.float32)  # (B, MB, BS, G, hs)
        s = pool["scale"][block_tables]  # (B, MB, G)
        g = g * s[:, :, None, :, None]
    else:
        g = pool[block_tables]  # (B, MB, BS, G, hs)
    B, MB, BS, G, hs = g.shape
    return g.reshape(B, MB * BS, G, hs).transpose(0, 2, 1, 3)


def _paged_attention_lax(q, k_pool, v_pool, block_tables, q_pos, scale):
    k = gather_paged_kv(k_pool, block_tables)
    v = gather_paged_kv(v_pool, block_tables)
    if isinstance(k_pool, dict):
        # dequantized KV is f32; run q in f32 too so the softmax chain is
        # the exact math the kernels compute (multihead_attention would
        # otherwise downcast the f32 KV to q's dtype at the read)
        out = multihead_attention(
            q.astype(jnp.float32), k, v, q_pos, scale=scale
        )
        return out.astype(q.dtype)
    # identical masking/softmax to the dense op: slot j valid iff j <= q_pos
    return multihead_attention(q, k, v, q_pos, scale=scale)


# ---------------------------------------------------------------------------
# Pallas kernel path (TPU): block-table decode, one query token per sequence
# ---------------------------------------------------------------------------

# import guarded so a stripped jax build without pallas still serves the
# lax fallback (pallas itself imports fine on plain CPU)
try:  # pragma: no cover - import guard
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Pallas calls cannot be GSPMD-partitioned, so the tensor-parallel serving
# engine runs them per shard under jax.shard_map (the same manual-region
# pattern as parallel/pipeline.py).  Gated like the rest of the repo's
# shard_map users: older jax builds fall back to the lax path under a mesh.
_HAS_SHARD_MAP = hasattr(jax, "shard_map")


def _run_sharded_kernel(kernel_fn, mesh, axis, q, k_pool, v_pool, *scalars):
    """Run a paged Pallas kernel per tensor-parallel shard: q splits on its
    head axis (1), the pools on their KV-group axis (2), block tables and
    ragged metadata replicate, and the output heads stay sharded — the
    caller's row-parallel attn proj reduces them, which is the one
    all-reduce per layer the dense tp forward pays.  GQA grouping survives
    the split because n_head and G shard by the same factor (q_per_kv is
    shard-invariant); `validate_tp_divisibility` guarantees both divide."""
    from jax.sharding import PartitionSpec as P

    q_spec = P(None, axis, None, None)

    def pool_spec(pool):
        # int8 pools carry their per-block-per-group scale alongside; it
        # shards on the same KV-group axis, so each device dequantizes its
        # own group-slice with its own scale slice — no cross-shard reads
        if isinstance(pool, dict):
            return {"q": P(None, None, axis, None), "scale": P(None, axis)}
        return P(None, None, axis, None)

    rep = tuple(P(*([None] * x.ndim)) for x in scalars)
    return jax.shard_map(
        kernel_fn,
        mesh=mesh,
        in_specs=(q_spec, pool_spec(k_pool), pool_spec(v_pool)) + rep,
        out_specs=q_spec,
        check_vma=False,
    )(q, k_pool, v_pool, *scalars)


def _decode_kernel(
    # scalar prefetch
    tables_ref,  # (B, MB) int32
    lens_ref,  # (B,) int32 — valid KV length per sequence (q_pos + 1)
    # blocks
    q_ref,  # (1, n_head, hs)
    k_ref,  # (1, BS, G, hs) — the table-resolved block for this grid step
    v_ref,
    # quantized pools insert (ks_ref, vs_ref) — the block's (1, G) f32
    # scales, riding the same table-resolved index map as k/v — before the
    # output; fp pools go straight to o_ref
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    block_size: int,
    n_groups: int,
    scale: float,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    n_live = lens_ref[b]

    @pl.when(i * block_size < n_live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (n_head, hs)
        k = k_ref[0].astype(jnp.float32)  # (BS, G, hs)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # in-loop dequant: the int8 block just DMA'd scales by its own
            # per-group factor — no fp copy of the pool ever materializes
            k = k * ks_ref[0][None, :, None]
            v = v * vs_ref[0][None, :, None]
        n_head, hs = q.shape
        q_per_kv = n_head // n_groups
        qg = q.reshape(n_groups, q_per_kv, hs)
        # (G, q_per_kv, BS) logits; batch dim G maps heads onto their group
        s = jax.lax.dot_general(
            qg,
            k.transpose(1, 2, 0),  # (G, hs, BS)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        jpos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        s = jnp.where(jpos < n_live, s, NEG_INF)
        s = s.reshape(n_head, block_size)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # (n_head, BS)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(n_groups, q_per_kv, block_size),
            v.transpose(1, 0, 2),  # (G, BS, hs)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_head, hs)
        acc_ref[...] = corr * acc_ref[...] + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == pl.num_programs(1) - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


# widest multi-query width the ragged kernel accepts: each (head, query)
# pair is an independent online-softmax row in VMEM scratch, so scratch
# grows linearly with Tq — speculative verify widths (K+1 <= ~9) are the
# target; prefill chunks (Tq ~ 128) stay on the gather fallback
RAGGED_KERNEL_MAX_TQ = 16


def _ragged_decode_kernel(
    # scalar prefetch
    tables_ref,  # (B, MB) int32
    lens_ref,  # (B,) int32 — valid KV length per sequence (max q_pos + 1)
    qpos_ref,  # (B, Tq) int32 — absolute position of every query token
    # blocks
    q_ref,  # (1, n_head, Tq, hs)
    k_ref,  # (1, BS, G, hs) — the table-resolved block for this grid step
    v_ref,
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref — see
    # _decode_kernel: quantized pools insert the block's (1, G) scales
    block_size: int,
    n_groups: int,
    n_queries: int,
    scale: float,
    quantized: bool = False,
):
    # o_ref (1, n_head, Tq, hs); scratch: every (head, query) pair is one
    # independent softmax row — m/l (n_head * Tq, 128), acc (n_head*Tq, hs)
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    n_live = lens_ref[b]

    @pl.when(i * block_size < n_live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (n_head, Tq, hs)
        n_head, Tq, hs = q.shape
        q_per_kv = n_head // n_groups
        k = k_ref[0].astype(jnp.float32)  # (BS, G, hs)
        v = v_ref[0].astype(jnp.float32)
        if quantized:  # in-loop dequant, see _decode_kernel
            k = k * ks_ref[0][None, :, None]
            v = v * vs_ref[0][None, :, None]
        # heads map onto their KV group; the Tq queries fold into the row
        # dim so one dot_general scores every (head, query) pair
        qg = q.reshape(n_groups, q_per_kv * Tq, hs)
        s = jax.lax.dot_general(
            qg,
            k.transpose(1, 2, 0),  # (G, hs, BS)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s.reshape(n_head, Tq, block_size)
        # ragged causal mask: key at absolute position j is valid for query
        # t iff j <= q_pos[t] — the dense op's one rule, per query row
        jpos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        # scalar-prefetch reads are scalar loads; Tq is static and small
        qpos = jnp.stack([qpos_ref[b, t] for t in range(n_queries)])
        s = jnp.where(jpos <= qpos[None, :, None], s, NEG_INF)
        s = s.reshape(n_head * Tq, block_size)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # (n_head * Tq, BS)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(n_groups, q_per_kv * Tq, block_size),
            v.transpose(1, 0, 2),  # (G, BS, hs)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_head * Tq, hs)
        acc_ref[...] = corr * acc_ref[...] + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == pl.num_programs(1) - 1)
    def _finalize():
        # fully-masked rows (a query past the slot's live length, e.g. a
        # padded draft lane) have l == 0; the floor keeps them finite —
        # their output is garbage by contract and discarded by the caller
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        out = acc_ref[...] / denom
        n_head_tq, hs = out.shape
        o_ref[0] = out.reshape(
            n_head_tq // n_queries, n_queries, hs
        ).astype(o_ref.dtype)


def _paged_attention_ragged_kernel(
    q, k_pool, v_pool, block_tables, q_pos, scale, interpret=False
):
    """q: (B, n_head, Tq, hs) → (B, n_head, Tq, hs), per-slot q_pos (B, Tq)."""
    B, n_head, Tq, hs = q.shape
    k_arr, k_sc = _pool_parts(k_pool)
    v_arr, v_sc = _pool_parts(v_pool)
    quantized = k_sc is not None
    NB, BS, G, _ = k_arr.shape
    MB = block_tables.shape[1]
    lens = (jnp.max(q_pos, axis=1) + 1).astype(jnp.int32)
    tables = block_tables.astype(jnp.int32)

    def kv_index(bidx, i, tables_ref, lens_ref, qpos_ref):
        # see _paged_attention_kernel: trailing grid steps remap to block 0
        needed = i * BS < lens_ref[bidx]
        return (jnp.where(needed, tables_ref[bidx, i], 0), 0, 0, 0)

    def scale_index(bidx, i, tables_ref, lens_ref, qpos_ref):
        needed = i * BS < lens_ref[bidx]
        return (jnp.where(needed, tables_ref[bidx, i], 0), 0)

    in_specs = [
        pl.BlockSpec((1, n_head, Tq, hs), lambda b, i, *_: (b, 0, 0, 0)),
        pl.BlockSpec((1, BS, G, hs), kv_index),
        pl.BlockSpec((1, BS, G, hs), kv_index),
    ]
    operands = [q, k_arr, v_arr]
    if quantized:
        in_specs += [pl.BlockSpec((1, G), scale_index)] * 2
        operands += [k_sc, v_sc]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_head, Tq, hs), lambda b, i, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_head * Tq, 128), jnp.float32),
            pltpu.VMEM((n_head * Tq, 128), jnp.float32),
            pltpu.VMEM((n_head * Tq, hs), jnp.float32),
        ],
    )
    kern = functools.partial(
        _ragged_decode_kernel,
        block_size=BS, n_groups=G, n_queries=Tq, scale=scale,
        quantized=quantized,
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_head, Tq, hs), q.dtype),
        interpret=interpret,
    )(tables, lens, q_pos.astype(jnp.int32), *operands)
    return out


def _ragged_prefill_kernel(
    # scalar prefetch (per SLOT, not per token — the whole point of the
    # packed layout is that slot metadata is O(slots), not O(tokens))
    tables_ref,  # (S, MB) int32
    qstart_ref,  # (S,) int32 — offset of slot s's query span in the packed axis
    qlen_ref,  # (S,) int32 — span length (0 = slot absent this step)
    qpos0_ref,  # (S,) int32 — absolute position of the span's FIRST token
    # blocks
    q_ref,  # (1, n_head, T, hs) — the whole packed batch rides every step
    k_ref,  # (1, BS, G, hs) — the table-resolved block for this grid step
    v_ref,
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref — see
    # _decode_kernel: quantized pools insert the block's (1, G) scales
    block_size: int,
    n_groups: int,
    n_tokens: int,
    scale: float,
    quantized: bool = False,
):
    # o_ref (1, n_head, T, hs); scratch: every (head, packed token) pair
    # is one online-softmax row — m/l (n_head * T, 128), acc (n_head*T, hs)
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    # Known tradeoff: every grid step scores the WHOLE packed q against the
    # step's kv block and masks rows outside the current slot's span, so
    # ~(1 - 1/n_live_slots) of each matmul is discarded.  The static shapes
    # keep the kernel one compile and the scratch layout trivial; if this
    # waste ever shows up on profiles, the fix is a q-tile grid axis with a
    # host-computed tile->slot map in scalar prefetch so each step's matmul
    # covers only one slot's span.
    s_id = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(jnp.logical_and(s_id == 0, i == 0))
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qstart_ref[s_id]
    q_len = qlen_ref[s_id]
    q_pos0 = qpos0_ref[s_id]
    n_live = q_pos0 + q_len  # KV slots visible to the span's deepest query

    @pl.when(jnp.logical_and(q_len > 0, i * block_size < n_live))
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (n_head, T, hs)
        n_head, T, hs = q.shape
        q_per_kv = n_head // n_groups
        k = k_ref[0].astype(jnp.float32)  # (BS, G, hs)
        v = v_ref[0].astype(jnp.float32)
        if quantized:  # in-loop dequant, see _decode_kernel
            k = k * ks_ref[0][None, :, None]
            v = v * vs_ref[0][None, :, None]
        qg = q.reshape(n_groups, q_per_kv * T, hs)
        s = jax.lax.dot_general(
            qg,
            k.transpose(1, 2, 0),  # (G, hs, BS)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s.reshape(n_head, T, block_size)
        # the slot owns packed rows [q_start, q_start + q_len); its spans are
        # contiguous position runs, so token t's absolute position is
        # q_pos0 + (t - q_start) — causal masking inside the slot's own
        # chunk falls out of the one rule: key at j valid iff j <= q_pos[t]
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, T, 1), 1)
        in_span = jnp.logical_and(t_idx >= q_start, t_idx < q_start + q_len)
        qpos = q_pos0 + (t_idx - q_start)
        jpos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        s = jnp.where(jnp.logical_and(in_span, jpos <= qpos), s, NEG_INF)
        s = s.reshape(n_head * T, block_size)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # (n_head * T, BS)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(n_groups, q_per_kv * T, block_size),
            v.transpose(1, 0, 2),  # (G, BS, hs)
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_head * T, hs)
        # rows OUTSIDE this slot's span must keep their state untouched:
        # NEG_INF is finite, so a fully-masked untouched row would compute
        # p = exp(NEG_INF - NEG_INF) = 1 and pollute another slot's
        # accumulator with this slot's V blocks — gate the update per row
        row = jnp.broadcast_to(
            in_span.reshape(1, T), (n_head, T)
        ).reshape(n_head * T, 1)
        m_ref[...] = jnp.where(
            row, jnp.broadcast_to(m_new, m_ref.shape), m_ref[...]
        )
        l_ref[...] = jnp.where(
            row, jnp.broadcast_to(l_new, l_ref.shape), l_ref[...]
        )
        acc_ref[...] = jnp.where(row, corr * acc_ref[...] + pv, acc_ref[...])

    @pl.when(jnp.logical_and(
        s_id == pl.num_programs(0) - 1, i == pl.num_programs(1) - 1
    ))
    def _finalize():
        # padding rows no slot owns never accumulate (l == 0): the floor
        # keeps them finite — garbage by contract, discarded by the caller
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        out = acc_ref[...] / denom
        n_head_t, hs = out.shape
        o_ref[0] = out.reshape(
            n_head_t // n_tokens, n_tokens, hs
        ).astype(o_ref.dtype)


def _paged_prefill_kernel(
    q, k_pool, v_pool, block_tables, q_start, q_len, q_pos, scale,
    interpret=False,
):
    """q: (1, n_head, T, hs) packed slot-major → (1, n_head, T, hs)."""
    B, n_head, T, hs = q.shape
    assert B == 1, "paged_prefill packs every slot into one ragged batch"
    k_arr, k_sc = _pool_parts(k_pool)
    v_arr, v_sc = _pool_parts(v_pool)
    quantized = k_sc is not None
    NB, BS, G, _ = k_arr.shape
    S, MB = block_tables.shape
    tables = block_tables.astype(jnp.int32)
    qstart = q_start.astype(jnp.int32)
    qlen = q_len.astype(jnp.int32)
    # the span's first absolute position (spans are contiguous runs); the
    # clip only guards absent slots, whose q_len == 0 skips all compute
    qpos0 = q_pos.astype(jnp.int32)[jnp.clip(qstart, 0, T - 1)]

    def kv_index(sidx, i, tables_ref, qstart_ref, qlen_ref, qpos0_ref):
        # see _paged_attention_kernel: unneeded grid steps remap to block 0
        needed = jnp.logical_and(
            qlen_ref[sidx] > 0,
            i * BS < qpos0_ref[sidx] + qlen_ref[sidx],
        )
        return (jnp.where(needed, tables_ref[sidx, i], 0), 0, 0, 0)

    def scale_index(sidx, i, tables_ref, qstart_ref, qlen_ref, qpos0_ref):
        needed = jnp.logical_and(
            qlen_ref[sidx] > 0,
            i * BS < qpos0_ref[sidx] + qlen_ref[sidx],
        )
        return (jnp.where(needed, tables_ref[sidx, i], 0), 0)

    in_specs = [
        pl.BlockSpec((1, n_head, T, hs), lambda s, i, *_: (0, 0, 0, 0)),
        pl.BlockSpec((1, BS, G, hs), kv_index),
        pl.BlockSpec((1, BS, G, hs), kv_index),
    ]
    operands = [q, k_arr, v_arr]
    if quantized:
        in_specs += [pl.BlockSpec((1, G), scale_index)] * 2
        operands += [k_sc, v_sc]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, n_head, T, hs), lambda s, i, *_: (0, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((n_head * T, 128), jnp.float32),
            pltpu.VMEM((n_head * T, 128), jnp.float32),
            pltpu.VMEM((n_head * T, hs), jnp.float32),
        ],
    )
    kern = functools.partial(
        _ragged_prefill_kernel,
        block_size=BS, n_groups=G, n_tokens=T, scale=scale,
        quantized=quantized,
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, n_head, T, hs), q.dtype),
        interpret=interpret,
    )(tables, qstart, qlen, qpos0, *operands)


# packed tokens per gather in the lax fallback: each lane materializes its
# slot's full-window KV view, so an unchunked (T, window) gather would be
# token_budget-fold the old B=1 prefill fallback's footprint (~hundreds of
# MB per layer per step at TinyLlama scale); lax.map over fixed chunks
# keeps the transient ∝ chunk while staying exact per row
_LAX_FALLBACK_CHUNK = 16


def _paged_prefill_lax(q, k_pool, v_pool, block_tables, q_slot, q_pos, scale):
    """Exact fallback: each packed token is one lane of the decode fallback
    with its OWN slot's table — per-token gather, the dense softmax chain
    bit-for-bit (the serving engine's greedy parity contract).  Wide packed
    batches run the same math in fixed-size chunks of the token axis
    (sequential lax.map) to bound the gathered-KV transient."""
    qt = q[0].transpose(1, 0, 2)[:, :, None, :]  # (T, n_head, 1, hs)
    T = qt.shape[0]
    C = _LAX_FALLBACK_CHUNK
    if T <= C:
        out = _paged_attention_lax(
            qt, k_pool, v_pool, block_tables[q_slot], q_pos[:, None], scale
        )
        return out[:, :, 0, :].transpose(1, 0, 2)[None]
    pad = -T % C
    # pad rows carry slot 0 / position 0: garbage by contract, sliced off
    qt_p = jnp.pad(qt, ((0, pad), (0, 0), (0, 0), (0, 0)))
    slot_p = jnp.pad(q_slot, (0, pad))
    pos_p = jnp.pad(q_pos, (0, pad))

    def chunk(args):
        qc, sc, pc = args
        return _paged_attention_lax(
            qc, k_pool, v_pool, block_tables[sc], pc[:, None], scale
        )

    out = jax.lax.map(chunk, (
        qt_p.reshape(-1, C, *qt.shape[1:]),
        slot_p.reshape(-1, C),
        pos_p.reshape(-1, C),
    ))
    out = out.reshape(-1, *out.shape[2:])[:T]
    return out[:, :, 0, :].transpose(1, 0, 2)[None]


def paged_prefill(
    q: jnp.ndarray,  # (1, n_head, T, hs) packed slot-major ragged queries
    k_pool: jnp.ndarray,  # (num_blocks, block_size, G, hs)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # (n_slots, max_blocks) int32
    q_slot: jnp.ndarray,  # (T,) slot id per packed token (fallback path)
    q_start: jnp.ndarray,  # (n_slots,) span offset per slot (kernel path)
    q_len: jnp.ndarray,  # (n_slots,) span length (0 = slot absent)
    q_pos: jnp.ndarray,  # (T,) absolute position per packed token
    scale: Optional[float] = None,
    use_kernel: Optional[bool] = None,  # None → auto (TPU backend)
    interpret: bool = False,
    shard_axes: Optional[Tuple] = None,  # (Mesh, tp_axis): run the kernel
    # per tensor-parallel shard (heads/KV groups split, tables replicated)
) -> jnp.ndarray:
    """Ragged mixed prefill+decode attention over the paged pool.

    The unified serving step packs every live slot's tokens — one pending
    decode token per decoding lane, up to the step's remaining token budget
    of prompt tokens per prefilling lane — slot-major into ONE (1, T) token
    axis; each packed token attends through its own slot's block table at
    its own absolute position.  Slot spans are contiguous position runs, so
    per-slot (q_start, q_len, first position) fully describe the raggedness
    — the kernel scalar-prefetches exactly that.  Packed positions no slot
    owns (batch-tail padding) return garbage rows the caller discards.

    With `shard_axes` (the tensor-parallel serving engine), the kernel path
    runs inside `jax.shard_map` over the tp axis: each device scores its
    own head-slice against its own KV-group slice of the pool.  The lax
    fallback needs no wrapper — it is plain jnp and GSPMD partitions it.

    Returns (1, n_head, T, hs).
    """
    hs = q.shape[-1]
    if scale is None:
        scale = 1.0 / (hs**0.5)
    if use_kernel is None:
        use_kernel = (
            _HAS_PALLAS
            and jax.default_backend() == "tpu"
            and (shard_axes is None or _HAS_SHARD_MAP)
        )
    if use_kernel and _HAS_PALLAS:
        if shard_axes is not None:
            if not _HAS_SHARD_MAP:
                raise ValueError(
                    "paged_prefill kernel under a mesh needs jax.shard_map "
                    "(missing in this jax build); use the lax fallback "
                    "(use_kernel=False)"
                )
            mesh, axis = shard_axes
            kern = functools.partial(
                _shard_prefill_body, scale=scale, interpret=interpret
            )
            return _run_sharded_kernel(
                kern, mesh, axis, q, k_pool, v_pool,
                block_tables.astype(jnp.int32), q_start.astype(jnp.int32),
                q_len.astype(jnp.int32), q_pos.astype(jnp.int32),
            )
        return _paged_prefill_kernel(
            q, k_pool, v_pool, block_tables, q_start, q_len, q_pos, scale,
            interpret=interpret,
        )
    return _paged_prefill_lax(
        q, k_pool, v_pool, block_tables, q_slot, q_pos, scale
    )


def _shard_prefill_body(q, k_pool, v_pool, tables, q_start, q_len, q_pos,
                        *, scale, interpret):
    return _paged_prefill_kernel(
        q, k_pool, v_pool, tables, q_start, q_len, q_pos, scale,
        interpret=interpret,
    )


def _paged_attention_kernel(
    q, k_pool, v_pool, block_tables, q_pos, scale, interpret=False
):
    """q: (B, n_head, 1, hs) → (B, n_head, 1, hs)."""
    B, n_head, Tq, hs = q.shape
    assert Tq == 1, "kernel path is decode-only (Tq == 1)"
    k_arr, k_sc = _pool_parts(k_pool)
    v_arr, v_sc = _pool_parts(v_pool)
    quantized = k_sc is not None
    NB, BS, G, _ = k_arr.shape
    MB = block_tables.shape[1]
    lens = (q_pos[:, 0] + 1).astype(jnp.int32)
    tables = block_tables.astype(jnp.int32)

    def kv_index(bidx, i, tables_ref, lens_ref):
        # unneeded trailing blocks remap to block 0: the DMA still happens
        # (the grid is static) but never re-reads a far block
        needed = i * BS < lens_ref[bidx]
        return (jnp.where(needed, tables_ref[bidx, i], 0), 0, 0, 0)

    def scale_index(bidx, i, tables_ref, lens_ref):
        needed = i * BS < lens_ref[bidx]
        return (jnp.where(needed, tables_ref[bidx, i], 0), 0)

    in_specs = [
        pl.BlockSpec((1, n_head, hs), lambda b, i, *_: (b, 0, 0)),
        pl.BlockSpec((1, BS, G, hs), kv_index),
        pl.BlockSpec((1, BS, G, hs), kv_index),
    ]
    operands = [q[:, :, 0, :], k_arr, v_arr]
    if quantized:
        in_specs += [pl.BlockSpec((1, G), scale_index)] * 2
        operands += [k_sc, v_sc]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_head, hs), lambda b, i, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_head, 128), jnp.float32),
            pltpu.VMEM((n_head, 128), jnp.float32),
            pltpu.VMEM((n_head, hs), jnp.float32),
        ],
    )
    kern = functools.partial(
        _decode_kernel, block_size=BS, n_groups=G, scale=scale,
        quantized=quantized,
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_head, hs), q.dtype),
        interpret=interpret,
    )(tables, lens, *operands)
    return out[:, :, None, :]


def paged_attention(
    q: jnp.ndarray,  # (B, n_head, Tq, hs)
    k_pool: jnp.ndarray,  # (num_blocks, block_size, G, hs)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # (B, max_blocks) int32
    q_pos: jnp.ndarray,  # (B, Tq) absolute query positions
    scale: Optional[float] = None,
    use_kernel: Optional[bool] = None,  # None → auto (TPU backend, decode)
    interpret: bool = False,
    shard_axes: Optional[Tuple] = None,  # (Mesh, tp_axis): run the kernel
    # per tensor-parallel shard (heads/KV groups split, tables replicated)
) -> jnp.ndarray:
    """Causal GQA/MQA attention through per-sequence block tables.

    Returns (B, n_head, Tq, hs).  Tq == 1 (the hot decode step) runs the
    single-query kernel; 1 < Tq <= RAGGED_KERNEL_MAX_TQ (ragged speculative
    verify: each slot scores K+1 tokens at its own depth) runs the ragged
    multi-query kernel; wider Tq (chunked prefill attending through the
    pool) always takes the gather fallback.  With `shard_axes`, the kernel
    paths run inside `jax.shard_map` over the tp axis (see `paged_prefill`).
    """
    hs = q.shape[-1]
    Tq = q.shape[2]
    if scale is None:
        scale = 1.0 / (hs**0.5)
    if use_kernel is None:
        use_kernel = (
            _HAS_PALLAS
            and jax.default_backend() == "tpu"
            and Tq <= RAGGED_KERNEL_MAX_TQ
            and (shard_axes is None or _HAS_SHARD_MAP)
        )
    if use_kernel and _HAS_PALLAS and Tq <= RAGGED_KERNEL_MAX_TQ:
        body = (
            _paged_attention_kernel if Tq == 1
            else _paged_attention_ragged_kernel
        )
        if shard_axes is not None:
            if not _HAS_SHARD_MAP:
                raise ValueError(
                    "paged_attention kernel under a mesh needs "
                    "jax.shard_map (missing in this jax build); use the "
                    "lax fallback (use_kernel=False)"
                )
            mesh, axis = shard_axes
            kern = functools.partial(
                _shard_attention_body, body=body, scale=scale,
                interpret=interpret,
            )
            return _run_sharded_kernel(
                kern, mesh, axis, q, k_pool, v_pool,
                block_tables.astype(jnp.int32), q_pos.astype(jnp.int32),
            )
        return body(
            q, k_pool, v_pool, block_tables, q_pos, scale,
            interpret=interpret,
        )
    return _paged_attention_lax(q, k_pool, v_pool, block_tables, q_pos, scale)


def _shard_attention_body(q, k_pool, v_pool, tables, q_pos, *, body, scale,
                          interpret):
    return body(q, k_pool, v_pool, tables, q_pos, scale, interpret=interpret)
