"""Paged decode attention over a pooled, block-table-indexed KV cache.

The serving subsystem (`mdi_llm_tpu.serving`) replaces the one-contiguous-
cache-per-run model of `generation.py` with a shared pool of fixed-width
KV blocks: layer cache `(num_blocks, block_size, G, hs)`, and each sequence
owns an ordered list of block ids (its *block table*).  Slot `i` of a
sequence's table holds the KV entries for absolute positions
`[i*block_size, (i+1)*block_size)`, so flattening the table recovers the
contiguous layout and the absolute-position masking contract of
`ops/attention.py` carries over unchanged — key at flattened slot `j` is
valid iff `j <= q_pos`.

Two implementations:

- **lax fallback** (`_paged_attention_lax` / `_paged_prefill_lax`): gather
  each table's blocks into a contiguous per-slot view and run the dense
  softmax chain on it.  Bit-for-bit the same math as the dense op — this
  is what the tier-1 CPU parity tests pin down, and what guarantees the
  serving engine's greedy streams match `Generator.generate`.
- **the unified Pallas kernel** (`ops/ragged_paged_attention.py`): ONE
  kernel for every serving shape — pure decode (Tq == 1), ragged
  multi-query decode at ANY width (batched speculative verify, no
  16-token cap), and packed ragged mixed prefill+decode — over one
  scalar-prefetched span layout.  `paged_attention` packs its per-sequence
  (B, n_head, Tq, hs) batch into the span layout (each sequence = one
  span of width Tq); `paged_prefill` passes its packed layout through.
  Kernel block/grid parameters (`ops/tuning.py`: kv_step, q_pack,
  scratch_width) resolve host-side at trace time from the committed or
  `mdi-tune`d tables, so the choice is compile-time static — zero
  post-warmup recompiles.  Semantics are validated against the fallback
  in interpreter mode; the fallback remains the default off-TPU.

Explicit `use_kernel=True` with anything unsupported (no pallas build, an
invalid tuning entry, a malformed pool) raises actionably — it never
silently degrades to the fallback; `use_kernel=None` auto-routes.

Writes go through `paged_update`: a scatter of the chunk's K/V into
`(block, offset)` slots resolved through the table.  Positions past the
table's coverage (prefill bucket padding) are redirected to block 0, which
the serving pool reserves as a write-only trash block.

**Quantized pools** (`ServingConfig(kv_dtype="int8")`): each of k/v is a
dict `{"q": int8 (num_blocks, block_size, G, hs), "scale": f32
(num_blocks, G)}` — symmetric per-BLOCK-per-KV-group scales, so the side
array costs 4 bytes per (block, group) against block_size*hs int8 payload
bytes (the ~2x capacity win stays real even at small head sizes, where
per-token scales would eat it).  `paged_update` quantizes on scatter with
a monotone scale: the block's scale only ever grows (`.at[].max` over the
written tokens' max-abs/127), and when it grows the block's existing int8
payload is requantized in the same update (gather the written blocks,
rescale by old/new, scatter back — a transient of written blocks only,
never the pool).  Consequences the serving engine relies on, pinned by
tests: a frozen-lane rewrite of the same (token, position) leaves scale
and payload bytes bit-identical, and a block's final scale is independent
of how its tokens were grouped into update calls.  The unified kernel
dequantizes INSIDE its KV-block loop (`k = int8_block * scale[group]` in
f32, fused after the block DMA) — no gathered-fp pool transient — and the
lax fallbacks run the same dequant-to-f32 math so kernel==fallback parity
holds at int8 exactly like fp.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from mdi_llm_tpu.ops.attention import NEG_INF, multihead_attention
from mdi_llm_tpu.ops.ragged_paged_attention import (
    _HAS_PALLAS,
    ragged_paged_attention,
)
from mdi_llm_tpu.ops.tuning import KernelParams, resolve_kernel_params

__all__ = [
    "paged_attention",
    "paged_prefill",
    "paged_update",
    "gather_paged_kv",
    "KernelParams",
]


def _pool_parts(pool):
    """(payload, scale-or-None) view of a pool: fp pools are bare arrays,
    int8 pools are {"q": int8 blocks, "scale": f32 (num_blocks, G)}."""
    if isinstance(pool, dict):
        return pool["q"], pool["scale"]
    return pool, None


def _quantized_update(pool, new, blk, off):
    """Quantizing scatter into one int8 pool: `new` (N, G, hs) fp values
    land at (blk[n], off[n]) under the block's per-group scale.

    The scale is a monotone running max (`.at[].max` of the written tokens'
    max-abs/127, duplicates folded correctly), so a rewrite of the same
    value at the same slot is byte-idempotent and the final scale is
    independent of how tokens were grouped into update calls.  When a write
    DOES grow a block's scale, the block's existing payload requantizes by
    old/new in the same scatter — the transient is the written blocks only
    (N × block_size × G × hs int8), never a pool-wide or gathered-fp copy.
    """
    q, s = pool["q"], pool["scale"]
    vals = new.astype(jnp.float32)
    tok_scale = jnp.max(jnp.abs(vals), axis=-1) / 127.0  # (N, G)
    new_s = s.at[blk].max(tok_scale)
    old_g = s[blk]  # (N, G) pre-update block scales
    new_g = new_s[blk]  # (N, G) post-update (>= old, monotone)
    # rescale existing payload where the scale grew; an all-zero block
    # (scale 0) maps 0 -> 0 whatever the factor, so the guard only dodges
    # the 0/0
    factor = jnp.where(new_g > 0, old_g / jnp.maximum(new_g, 1e-30), 0.0)
    requant = jnp.round(
        q[blk].astype(jnp.float32) * factor[:, None, :, None]
    ).astype(jnp.int8)
    q = q.at[blk].set(requant)  # duplicate blk entries scatter identical
    # blocks (same source block, same old/new scale), so order is moot
    tok_q = jnp.clip(
        jnp.round(vals / jnp.maximum(new_g, 1e-30)[..., None]), -127, 127
    ).astype(jnp.int8)
    q = q.at[blk, off].set(tok_q)
    return {"q": q, "scale": new_s}


def paged_update(
    k_pool,  # (num_blocks, block_size, G, hs), or int8 {"q", "scale"}
    v_pool,
    k_new: jnp.ndarray,  # (B, T, G, hs)
    v_new: jnp.ndarray,
    block_tables: jnp.ndarray,  # (B, max_blocks) int32
    pos: jnp.ndarray,  # (B, T) absolute positions of the chunk's tokens
):
    """Scatter a chunk's K/V into the pool through the block tables.

    Slot for position p: block `table[p // block_size]`, offset
    `p % block_size`.  Positions whose block index falls outside the table
    (bucket padding past the sequence budget) write to block 0 — the pool's
    reserved trash block — so padding can never corrupt a live block.

    int8 pools quantize on scatter (`_quantized_update`): per-block
    per-group scales grow monotonically and the written blocks requantize
    in place when they do.
    """
    MB = block_tables.shape[1]
    BS = _pool_parts(k_pool)[0].shape[1]
    idx = pos // BS
    blk = jnp.take_along_axis(block_tables, jnp.clip(idx, 0, MB - 1), axis=1)
    blk = jnp.where(idx < MB, blk, 0)
    off = pos % BS
    if isinstance(k_pool, dict):
        blk_f, off_f = blk.reshape(-1), off.reshape(-1)
        G, hs = k_new.shape[-2:]
        k_pool = _quantized_update(
            k_pool, k_new.reshape(-1, G, hs), blk_f, off_f
        )
        v_pool = _quantized_update(
            v_pool, v_new.reshape(-1, G, hs), blk_f, off_f
        )
        return k_pool, v_pool
    k_pool = k_pool.at[blk, off].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[blk, off].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def gather_paged_kv(
    pool,  # (num_blocks, block_size, G, hs), or int8 {"q", "scale"}
    block_tables: jnp.ndarray,  # (B, max_blocks)
) -> jnp.ndarray:
    """Materialize each sequence's contiguous (B, G, S, hs) view,
    S = max_blocks * block_size.  Flattened slot j holds absolute position
    j by the table-layout contract.  int8 pools dequantize to f32 — the
    same `int8 * scale` math the kernel runs inside its block loop, so
    the fallback stays the kernel's parity reference at int8 too."""
    if isinstance(pool, dict):
        g = pool["q"][block_tables].astype(jnp.float32)  # (B, MB, BS, G, hs)
        s = pool["scale"][block_tables]  # (B, MB, G)
        g = g * s[:, :, None, :, None]
    else:
        g = pool[block_tables]  # (B, MB, BS, G, hs)
    B, MB, BS, G, hs = g.shape
    return g.reshape(B, MB * BS, G, hs).transpose(0, 2, 1, 3)


def _paged_attention_lax(q, k_pool, v_pool, block_tables, q_pos, scale):
    k = gather_paged_kv(k_pool, block_tables)
    v = gather_paged_kv(v_pool, block_tables)
    if isinstance(k_pool, dict):
        # dequantized KV is f32; run q in f32 too so the softmax chain is
        # the exact math the kernel computes (multihead_attention would
        # otherwise downcast the f32 KV to q's dtype at the read)
        out = multihead_attention(
            q.astype(jnp.float32), k, v, q_pos, scale=scale
        )
        return out.astype(q.dtype)
    # identical masking/softmax to the dense op: slot j valid iff j <= q_pos
    return multihead_attention(q, k, v, q_pos, scale=scale)


# ---------------------------------------------------------------------------
# Pallas kernel path (TPU): the unified ragged kernel behind both entries
# ---------------------------------------------------------------------------

# Pallas calls cannot be GSPMD-partitioned, so the tensor-parallel serving
# engine runs them per shard under jax.shard_map (the same manual-region
# pattern as parallel/pipeline.py).  Gated like the rest of the repo's
# shard_map users: older jax builds fall back to the lax path under a mesh.
_HAS_SHARD_MAP = hasattr(jax, "shard_map")


def _kernel_auto(shard_axes) -> bool:
    """The `use_kernel=None` routing rule: the unified kernel serves every
    shape on a pallas-enabled TPU backend (no Tq width cap — the old
    RAGGED_KERNEL_MAX_TQ=16 cliff is gone); anything else falls back."""
    return (
        _HAS_PALLAS
        and jax.default_backend() == "tpu"
        and (shard_axes is None or _HAS_SHARD_MAP)
    )


def _run_sharded_kernel(kernel_fn, mesh, axis, q, k_pool, v_pool, *scalars):
    """Run a paged Pallas kernel per tensor-parallel shard: q splits on its
    head axis (1), the pools on their KV-group axis (2), block tables and
    ragged metadata replicate, and the output heads stay sharded — the
    caller's row-parallel attn proj reduces them, which is the one
    all-reduce per layer the dense tp forward pays.  GQA grouping survives
    the split because n_head and G shard by the same factor (q_per_kv is
    shard-invariant); `validate_tp_divisibility` guarantees both divide."""
    from jax.sharding import PartitionSpec as P

    q_spec = P(None, axis, None, None)

    def pool_spec(pool):
        # int8 pools carry their per-block-per-group scale alongside; it
        # shards on the same KV-group axis, so each device dequantizes its
        # own group-slice with its own scale slice — no cross-shard reads
        if isinstance(pool, dict):
            return {"q": P(None, None, axis, None), "scale": P(None, axis)}
        return P(None, None, axis, None)

    rep = tuple(P(*([None] * x.ndim)) for x in scalars)
    return jax.shard_map(
        kernel_fn,
        mesh=mesh,
        in_specs=(q_spec, pool_spec(k_pool), pool_spec(v_pool)) + rep,
        out_specs=q_spec,
        check_vma=False,
    )(q, k_pool, v_pool, *scalars)


def _shard_unified_body(q, k_pool, v_pool, tables, q_start, q_len, lens,
                        q_pos, *, scale, params, interpret):
    # inside shard_map: local head/KV-group slices, replicated metadata.
    # params resolved OUTSIDE on the global geometry; the builder folds
    # q_pack down to the local group count (gcd), deterministically.
    return ragged_paged_attention(
        q, k_pool, v_pool, tables, q_start, q_len, lens, q_pos,
        scale=scale, params=params, interpret=interpret,
    )


def _dispatch_unified(q, k_pool, v_pool, block_tables, q_start, q_len, lens,
                      q_pos, scale, params, interpret, shard_axes, who):
    """Shared kernel-path dispatch for both public entries: resolve the
    tuning-table entry (host-side, trace-time — compile-time static), then
    run the unified kernel directly or per tp shard under shard_map."""
    n_head, hs = q.shape[1], q.shape[-1]
    k_arr = _pool_parts(k_pool)[0]
    BS, G = k_arr.shape[1], k_arr.shape[2]
    if params is None:
        device_kind = None
        if jax.default_backend() == "tpu":
            device_kind = jax.devices()[0].device_kind
        params, _ = resolve_kernel_params(
            n_head=n_head, n_groups=G, head_size=hs, block_size=BS,
            kv_dtype="int8" if isinstance(k_pool, dict) else None,
            device_kind=device_kind,
        )
    if shard_axes is not None:
        if not _HAS_SHARD_MAP:
            raise ValueError(
                f"{who} kernel under a mesh needs jax.shard_map (missing "
                "in this jax build); use the lax fallback (use_kernel="
                "False)"
            )
        mesh, axis = shard_axes
        kern = functools.partial(
            _shard_unified_body, scale=scale, params=params,
            interpret=interpret,
        )
        return _run_sharded_kernel(
            kern, mesh, axis, q, k_pool, v_pool,
            block_tables.astype(jnp.int32), q_start.astype(jnp.int32),
            q_len.astype(jnp.int32), lens.astype(jnp.int32),
            q_pos.astype(jnp.int32),
        )
    return ragged_paged_attention(
        q, k_pool, v_pool, block_tables, q_start, q_len, lens, q_pos,
        scale=scale, params=params, interpret=interpret,
    )


# packed tokens per chunk in the lax fallback: each lane reads its slot's
# full-window KV view, so an unchunked (T, window) score matrix would be
# token_budget-fold the old B=1 prefill fallback's footprint; lax.map over
# fixed chunks keeps the attention transient ∝ chunk while staying exact
# per row
_LAX_FALLBACK_CHUNK = 16


def _paged_prefill_lax(q, k_pool, v_pool, block_tables, q_slot, q_pos, scale):
    """Exact fallback: each packed token is one lane of the decode fallback
    reading its OWN slot's contiguous view — the dense softmax chain
    bit-for-bit (the serving engine's greedy parity contract).

    The pool is gathered ONCE per call into per-slot dense views (one take
    over the slot axis), and the chunked `lax.map` body only INDEXES those
    views per lane — the old shape gathered the pool through
    `block_tables[sc]` inside every chunk, paying O(T) tiny per-token
    gathers that dominated CPU CI and kernel-less TPU builds.  Same
    elements either way (`pool[tables][sc] == pool[tables[sc]]`
    row-for-row), so the outputs are bit-identical to the old fallback;
    wide packed batches still run fixed-size chunks of the token axis
    (sequential lax.map) to bound the attention transient."""
    quantized = isinstance(k_pool, dict)
    k = gather_paged_kv(k_pool, block_tables)  # (S, G, W, hs)
    v = gather_paged_kv(v_pool, block_tables)
    qt = q[0].transpose(1, 0, 2)[:, :, None, :]  # (T, n_head, 1, hs)
    if quantized:
        # dequantized KV is f32; run q in f32 too (see _paged_attention_lax)
        qt = qt.astype(jnp.float32)
    T = qt.shape[0]
    C = _LAX_FALLBACK_CHUNK

    def run(qc, sc, pc):
        return multihead_attention(qc, k[sc], v[sc], pc[:, None], scale=scale)

    if T <= C:
        out = run(qt, q_slot, q_pos)
    else:
        pad = -T % C
        # pad rows carry slot 0 / position 0: garbage by contract, sliced
        qt_p = jnp.pad(qt, ((0, pad), (0, 0), (0, 0), (0, 0)))
        slot_p = jnp.pad(q_slot, (0, pad))
        pos_p = jnp.pad(q_pos, (0, pad))
        out = jax.lax.map(lambda a: run(*a), (
            qt_p.reshape(-1, C, *qt.shape[1:]),
            slot_p.reshape(-1, C),
            pos_p.reshape(-1, C),
        ))
        out = out.reshape(-1, *out.shape[2:])[:T]
    out = out[:, :, 0, :].transpose(1, 0, 2)[None]
    return out.astype(q.dtype) if quantized else out


def paged_prefill(
    q: jnp.ndarray,  # (1, n_head, T, hs) packed slot-major ragged queries
    k_pool: jnp.ndarray,  # (num_blocks, block_size, G, hs)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # (n_slots, max_blocks) int32
    q_slot: jnp.ndarray,  # (T,) slot id per packed token (fallback path)
    q_start: jnp.ndarray,  # (n_slots,) span offset per slot (kernel path)
    q_len: jnp.ndarray,  # (n_slots,) span length (0 = slot absent)
    q_pos: jnp.ndarray,  # (T,) absolute position per packed token
    scale: Optional[float] = None,
    use_kernel: Optional[bool] = None,  # None → auto (TPU backend)
    interpret: bool = False,
    shard_axes: Optional[Tuple] = None,  # (Mesh, tp_axis): run the kernel
    # per tensor-parallel shard (heads/KV groups split, tables replicated)
    params: Optional[KernelParams] = None,  # kernel tuning override; None
    # resolves the mdi-tune/builtin tables at trace time (ops/tuning.py)
) -> jnp.ndarray:
    """Ragged mixed prefill+decode attention over the paged pool.

    The unified serving step packs every live slot's tokens — one pending
    decode token per decoding lane, up to the step's remaining token budget
    of prompt tokens per prefilling lane — slot-major into ONE (1, T) token
    axis; each packed token attends through its own slot's block table at
    its own absolute position.  Slot spans are contiguous position runs, so
    per-slot (q_start, q_len, first position) fully describe the raggedness
    — this is the unified kernel's native layout and passes straight
    through.  Packed positions no slot owns (batch-tail padding) return
    garbage rows the caller discards.

    With `shard_axes` (the tensor-parallel serving engine), the kernel path
    runs inside `jax.shard_map` over the tp axis: each device scores its
    own head-slice against its own KV-group slice of the pool.  The lax
    fallback needs no wrapper — it is plain jnp and GSPMD partitions it.

    Returns (1, n_head, T, hs).
    """
    hs = q.shape[-1]
    T = q.shape[2]
    if scale is None:
        scale = 1.0 / (hs**0.5)
    if use_kernel is None:
        use_kernel = _kernel_auto(shard_axes)
    elif use_kernel and not _HAS_PALLAS:
        raise ValueError(
            "paged_prefill: use_kernel=True but this jax build has no "
            "jax.experimental.pallas — drop use_kernel (lax fallback) or "
            "install a pallas-enabled jax"
        )
    if use_kernel:
        qstart = q_start.astype(jnp.int32)
        qlen = q_len.astype(jnp.int32)
        # the span's deepest visible KV position + 1 (spans are contiguous
        # runs from the first token's position); the clip only guards
        # absent slots, whose q_len == 0 skips all compute anyway
        lens = q_pos.astype(jnp.int32)[jnp.clip(qstart, 0, T - 1)] + qlen
        return _dispatch_unified(
            q, k_pool, v_pool, block_tables, qstart, qlen, lens, q_pos,
            scale, params, interpret, shard_axes, "paged_prefill",
        )
    return _paged_prefill_lax(
        q, k_pool, v_pool, block_tables, q_slot, q_pos, scale
    )


def paged_attention(
    q: jnp.ndarray,  # (B, n_head, Tq, hs)
    k_pool: jnp.ndarray,  # (num_blocks, block_size, G, hs)
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # (B, max_blocks) int32
    q_pos: jnp.ndarray,  # (B, Tq) absolute query positions
    scale: Optional[float] = None,
    use_kernel: Optional[bool] = None,  # None → auto (TPU backend)
    interpret: bool = False,
    shard_axes: Optional[Tuple] = None,  # (Mesh, tp_axis): run the kernel
    # per tensor-parallel shard (heads/KV groups split, tables replicated)
    params: Optional[KernelParams] = None,  # kernel tuning override; None
    # resolves the mdi-tune/builtin tables at trace time (ops/tuning.py)
) -> jnp.ndarray:
    """Causal GQA/MQA attention through per-sequence block tables.

    Returns (B, n_head, Tq, hs).  The kernel path packs the batch into the
    unified kernel's span layout — sequence b becomes the span
    `[b*Tq, (b+1)*Tq)` of a (1, n_head, B*Tq, hs) ragged batch with its
    own per-token positions — so ONE kernel serves the hot decode step
    (Tq == 1), ragged speculative verify at ANY width (each slot scores
    K+1 tokens at its own depth; the old 16-token cap is gone), and
    chunked prefill attending through the pool.  With `shard_axes`, the
    kernel runs inside `jax.shard_map` over the tp axis (see
    `paged_prefill`).
    """
    B, n_head, Tq, hs = q.shape
    if scale is None:
        scale = 1.0 / (hs**0.5)
    if use_kernel is None:
        use_kernel = _kernel_auto(shard_axes)
    elif use_kernel and not _HAS_PALLAS:
        raise ValueError(
            "paged_attention: use_kernel=True but this jax build has no "
            "jax.experimental.pallas — drop use_kernel (lax fallback) or "
            "install a pallas-enabled jax"
        )
    if use_kernel:
        # pack (B, n_head, Tq, hs) slot-major: sequence b owns packed
        # tokens [b*Tq, (b+1)*Tq) at its own absolute positions
        qp = q.transpose(1, 0, 2, 3).reshape(1, n_head, B * Tq, hs)
        qstart = jnp.arange(B, dtype=jnp.int32) * Tq
        qlen = jnp.full((B,), Tq, dtype=jnp.int32)
        lens = (jnp.max(q_pos, axis=1) + 1).astype(jnp.int32)
        out = _dispatch_unified(
            qp, k_pool, v_pool, block_tables, qstart, qlen, lens,
            q_pos.reshape(-1), scale, params, interpret, shard_axes,
            "paged_attention",
        )
        return out[0].reshape(n_head, B, Tq, hs).transpose(1, 0, 2, 3)
    return _paged_attention_lax(q, k_pool, v_pool, block_tables, q_pos, scale)
