"""Ring attention: causal attention with the sequence sharded over a mesh
axis.

Long-context/sequence parallelism is absent from the reference
(SURVEY.md §5.7 — context is bounded by one device's memory); on TPU it is a
first-class design axis.  This implements blockwise ring attention
(Liu et al., "Ring Attention with Blockwise Transformers"-style): each
device on the `sp` axis holds a sequence chunk of Q, K, V; K/V chunks (with
their absolute positions) rotate around the ring via `jax.lax.ppermute`
while each device accumulates its queries' attention with an online-softmax
(running max / denominator / weighted sum), so the full (T, T) score matrix
is never materialized and context length scales linearly with the number of
devices.

Must be called inside a `shard_map` context where `axis_name` is a mesh
axis.  Numerics: f32 accumulators, output matches dense attention to
~1e-6 (pinned by tests against `multihead_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,  # (B, n_head, Tq_local, hs)
    k: jnp.ndarray,  # (B, n_groups, Tk_local, hs)
    v: jnp.ndarray,  # (B, n_groups, Tk_local, hs)
    q_pos: jnp.ndarray,  # (B, Tq_local) absolute query positions
    k_pos: jnp.ndarray,  # (B, Tk_local) absolute key positions (local chunk)
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    use_flash: bool = False,
    flash_interpret: bool = False,
) -> jnp.ndarray:
    """Returns (B, n_head, Tq_local, hs) — attention of the local queries
    over the ENTIRE (distributed) key/value sequence.

    `use_flash` runs EVERY block through the Pallas flash kernel
    (ops/flash.flash_attention_lse): the diagonal block (own chunk) as
    causal self-attention seeding the online-softmax carry from its
    (out, lse), and each rotated chunk as an unmasked (causal=False) block
    gated per batch row by whether the chunk precedes the local queries —
    valid because ring chunks are contiguous disjoint position ranges, so
    a rotated chunk is entirely before or entirely after the local
    queries, never interleaved.  Caller contract: causal=True and
    q_pos == k_pos == contiguous per-device ranges (the sp
    training/prefill geometry).  Differentiable — each lse carries its own
    cotangent into the FA-2 backward kernels."""
    B, n_head, Tq, hs = q.shape
    _, n_groups, Tk, _ = k.shape
    if scale is None:
        scale = 1.0 / (hs**0.5)
    P = jax.lax.axis_size(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    q_per_kv = n_head // n_groups
    qg = q.reshape(B, n_groups, q_per_kv, Tq, hs)

    if use_flash and causal:
        from mdi_llm_tpu.ops.flash import flash_attention_lse

        o_n, lse = flash_attention_lse(
            q, k, v, scale=scale, interpret=flash_interpret
        )
        # carry in rescaled form: (m, l, o) and (lse, 1, o_normalized)
        # are equivalent under the merge rules (dividing the unnormalized
        # accumulator and its log-weight by l leaves o/l and m+log l fixed)
        m0 = lse.reshape(B, n_groups, q_per_kv, Tq)
        l0 = jnp.ones_like(m0)
        o0 = o_n.reshape(B, n_groups, q_per_kv, Tq, hs).astype(jnp.float32)
    else:
        # derive accumulators from q so they inherit q's varying mesh axes
        # (JAX vma typing: the scan carry becomes device-varying after the
        # first ppermute round; fresh constants would type as unvarying and
        # mismatch)
        zero = (qg[..., 0] * 0.0).astype(jnp.float32)  # (B, G, q_per_kv, Tq)
        m0 = zero + NEG_INF
        l0 = zero
        o0 = (qg * 0.0).astype(jnp.float32)

    def body(carry, _):
        k_c, v_c, kp_c, m, l, o = carry
        s = jnp.einsum(
            "bgqth,bgsh->bgqts", qg, k_c, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            mask = kp_c[:, None, :] <= q_pos[:, :, None]  # (B, Tq, Tk)
            s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)

        m_chunk = jnp.max(s, axis=-1)  # (B, g, q, Tq)
        m_new = jnp.maximum(m, m_chunk)
        # guard fully-masked rows: keep exp argument finite
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        p = jnp.exp(jnp.maximum(s - m_new[..., None], -80.0))
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bgqts,bgsh->bgqth", p, v_c.astype(jnp.float32)
        )
        # rotate the K/V chunk (and its positions) to the next device
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        kp_n = jax.lax.ppermute(kp_c, axis_name, perm)
        return (k_n, v_n, kp_n, m_new, l, o), None

    def flash_body(carry, _):
        from mdi_llm_tpu.ops.flash import flash_attention_lse

        k_c, v_c, kp_c, m, l, o = carry
        # unmasked flash over the rotated chunk, then a two-way normalized
        # merge; gate per batch row on "this chunk precedes every local
        # query" (chunks are disjoint contiguous ranges, so all-or-nothing)
        o_h, lse_h = flash_attention_lse(
            q, k_c, v_c, scale=scale, interpret=flash_interpret, causal=False
        )
        gate = jnp.max(kp_c, axis=1) <= jnp.min(q_pos, axis=1)  # (B,)
        gate4 = gate[:, None, None, None]
        lse_hg = jnp.where(
            gate4, lse_h.reshape(B, n_groups, q_per_kv, Tq), NEG_INF
        )
        m_new = jnp.maximum(m, lse_hg)
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        # the chunk arrives normalized: its (m, l, o) form is (lse, 1, o_h)
        beta = jnp.exp(jnp.maximum(lse_hg - m_new, -80.0)) * gate4.astype(
            jnp.float32
        )
        l = l * alpha + beta
        o = o * alpha[..., None] + (
            o_h.reshape(B, n_groups, q_per_kv, Tq, hs).astype(jnp.float32)
            * beta[..., None]
        )
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        kp_n = jax.lax.ppermute(kp_c, axis_name, perm)
        return (k_n, v_n, kp_n, m_new, l, o), None

    with jax.named_scope("ring_attention"):
        if use_flash and causal:
            # the diagonal block is already in the carry: start from the
            # neighbors' chunks and walk the remaining P-1 hops fully fused
            k1 = jax.lax.ppermute(k, axis_name, perm)
            v1 = jax.lax.ppermute(v, axis_name, perm)
            kp1 = jax.lax.ppermute(k_pos, axis_name, perm)
            (k_f, v_f, kp_f, m, l, o), _ = jax.lax.scan(
                flash_body, (k1, v1, kp1, m0, l0, o0), None, length=P - 1
            )
        else:
            (k_f, v_f, kp_f, m, l, o), _ = jax.lax.scan(
                body, (k, v, k_pos, m0, l0, o0), None, length=P
            )
        out = o / jnp.maximum(l[..., None], 1e-30)
        return out.reshape(B, n_head, Tq, hs).astype(q.dtype)


def ring_decode(
    q: jnp.ndarray,  # (B, n_head, 1, hs) — the decode token, replicated
    k_cache: jnp.ndarray,  # (B, n_groups, C, hs) LOCAL cache shard
    v_cache: jnp.ndarray,  # (B, n_groups, C, hs)
    k_pos: jnp.ndarray,  # (B, C) absolute position of each local slot
    # (sentinel >= 2^30 marks an empty slot)
    q_pos: jnp.ndarray,  # (B, 1) absolute query position
    axis_name: str,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Decode-step attention over a sequence-sharded KV cache: every device
    computes online-softmax partials (m, l, o) over its local shard, then
    the partials merge across the `axis_name` ring with one psum/pmax —
    the distributed analog of flash-decoding.  No device ever holds the
    full cache; per-step traffic is O(B · heads · hs).

    Returns (B, n_head, 1, hs), replicated across the axis."""
    B, n_head, Tq, hs = q.shape
    _, n_groups, C, _ = k_cache.shape
    if scale is None:
        scale = 1.0 / (hs**0.5)
    q_per_kv = n_head // n_groups
    qg = q.reshape(B, n_groups, q_per_kv, Tq, hs)

    with jax.named_scope("ring_decode"):
        s = jnp.einsum(
            "bgqth,bgsh->bgqts", qg, k_cache, preferred_element_type=jnp.float32
        ) * scale
        valid = k_pos[:, None, :] <= q_pos[:, :, None]  # (B, 1, C); empty
        # slots carry the sentinel position and are never <= a real q_pos
        s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)

        m = jnp.max(s, axis=-1)  # (B, g, q, 1) local max
        p = jnp.exp(jnp.maximum(s - m[..., None], -80.0))
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum("bgqts,bgsh->bgqth", p, v_cache.astype(jnp.float32))

        # cross-device softmax merge
        m_g = jax.lax.pmax(m, axis_name)
        corr = jnp.exp(jnp.maximum(m - m_g, -80.0))
        l_g = jax.lax.psum(l * corr, axis_name)
        o_g = jax.lax.psum(o * corr[..., None], axis_name)
        out = o_g / jnp.maximum(l_g[..., None], 1e-30)
        return out.reshape(B, n_head, Tq, hs).astype(q.dtype)
