"""TPU-native core ops: RoPE, norms, attention, KV cache, sampling."""

from mdi_llm_tpu.ops.rope import build_rope_cache, apply_rope
from mdi_llm_tpu.ops.norms import rms_norm, layer_norm
from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.paged_attention import paged_attention, paged_prefill, paged_update
from mdi_llm_tpu.ops.ragged_paged_attention import ragged_paged_attention
from mdi_llm_tpu.ops.tuning import KernelParams, resolve_kernel_params
from mdi_llm_tpu.ops.sampling import sample, sample_top_p, logits_to_probs

__all__ = [
    "build_rope_cache",
    "apply_rope",
    "rms_norm",
    "layer_norm",
    "multihead_attention",
    "paged_attention",
    "paged_prefill",
    "paged_update",
    "ragged_paged_attention",
    "KernelParams",
    "resolve_kernel_params",
    "sample",
    "sample_top_p",
    "logits_to_probs",
]
