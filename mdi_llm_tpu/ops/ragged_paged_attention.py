"""ONE ragged paged-attention Pallas kernel for every serving shape.

Historically `ops/paged_attention.py` carried three correctness-first
kernels — single-query decode, ragged multi-query decode (capped at
``RAGGED_KERNEL_MAX_TQ=16``), and packed ragged prefill — with hardcoded
grids.  This module replaces all three with a single kernel over the one
layout they all reduce to, the paper's "Ragged Paged Attention" shape
(PAPERS.md, arxiv 2604.15464):

- every query token of every slot packs slot-major into ONE ``(1, n_head,
  T, hs)`` axis; per-slot ``(q_start, q_len)`` spans plus a per-token
  absolute-position vector fully describe the raggedness.  Pure decode is
  spans of width 1, speculative verify is spans of any width (no 16-token
  cap), chunked prefill is wide spans — same kernel, same grid.
- grid ``(n_slots, max_blocks * steps_per_block)``: the slot's block
  table rides in scalar prefetch so the index map DMAs exactly the KV
  (sub-)blocks the slot owns (unneeded steps remap to trash block 0 and
  skip compute); ``kv_step`` tokens stream per iteration with online-
  softmax accumulation in VMEM scratch, one row per (head, packed token).
- int8 pools dequantize INSIDE the loop (``int8_block * scale[group]``
  fused after the block DMA) from the per-block scale refs riding the
  same table-resolved index map — no gathered-fp transient, ever.
- ``q_pack`` folds p KV groups into one block-diagonal matmul so (head,
  query) rows fill full 8x128 sublanes when ``n_head*hs`` underfills a
  lane tile (pythia-14m / tiny-llama class).  Packing is exact: the
  off-diagonal q blocks are zeros (0*k contributes nothing to the QK
  scores) and the PV product keeps only the diagonal blocks, so packed
  and unpacked paths compute the same chain.

The three knobs (``kv_step``, ``q_pack``, ``scratch_width``) come from
`ops/tuning.py` — resolved host-side at trace time, so the choice is
compile-time static and costs zero post-warmup recompiles.  Dispatch
(packing `paged_attention`'s per-sequence batch into the span layout,
auto/fallback routing, the shard_map tp wrapper) stays in
`ops/paged_attention.py`; this module is the kernel and its builder.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from mdi_llm_tpu.ops.attention import NEG_INF
from mdi_llm_tpu.ops.tuning import KernelParams, validate_kernel_params

__all__ = ["ragged_paged_attention"]

# import guarded so a stripped jax build without pallas still serves the
# lax fallback (pallas itself imports fine on plain CPU)
try:  # pragma: no cover - import guard
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _pool_parts(pool):
    """(payload, scale-or-None): fp pools are bare arrays, int8 pools are
    ``{"q": int8 blocks, "scale": f32 (num_blocks, G)}``."""
    if isinstance(pool, dict):
        return pool["q"], pool["scale"]
    return pool, None


def _packed_qk(qg, k, p, scale):
    """Block-diagonal QK scores with p KV groups per matmul.

    qg ``(G, rows_g, hs)``, k ``(kv, G, hs)`` -> ``(G, rows_g, kv)``, the
    exact same scores as the unpacked per-group dot: group g = gp*p + j
    lands in row-block j / col-block j of the (p*rows_g, p*hs) operands,
    and the zero off-diagonal q blocks add exact zeros to each dot.
    """
    G, rows_g, hs = qg.shape
    kv = k.shape[0]
    gp = G // p
    eye = jnp.eye(p, dtype=jnp.float32)
    qbd = (
        qg.reshape(gp, p, rows_g, 1, hs) * eye.reshape(1, p, 1, p, 1)
    ).reshape(gp, p * rows_g, p * hs)
    kp = k.transpose(1, 2, 0).reshape(gp, p * hs, kv)
    s = jax.lax.dot_general(
        qbd, kp, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale
    return s.reshape(G, rows_g, kv)


def _packed_pv(pm, v, p):
    """Block-diagonal PV with p KV groups per matmul.

    pm ``(G, rows_g, kv)`` softmax weights, v ``(kv, G, hs)`` ->
    ``(G, rows_g, hs)``: the packed product computes a (p x p)-block
    result per group pack and keeps only the diagonal blocks — row-block
    j x col-block j is exactly group j's P·V.
    """
    G, rows_g, kv = pm.shape
    hs = v.shape[-1]
    gp = G // p
    eye = jnp.eye(p, dtype=jnp.float32)
    pb = pm.reshape(gp, p * rows_g, kv)
    vp = (
        v.transpose(1, 0, 2)
        .reshape(gp, p, kv, hs)
        .transpose(0, 2, 1, 3)
        .reshape(gp, kv, p * hs)
    )
    pv = jax.lax.dot_general(
        pb, vp, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    pv = (
        pv.reshape(gp, p, rows_g, p, hs) * eye.reshape(1, p, 1, p, 1)
    ).sum(axis=3)
    return pv.reshape(G, rows_g, hs)


def _unified_kernel(
    # scalar prefetch (per SLOT — metadata is O(slots), not O(tokens))
    tables_ref,  # (S, MB) int32
    qstart_ref,  # (S,) int32 — offset of slot s's span in the packed axis
    qlen_ref,  # (S,) int32 — span length (0 = slot absent this step)
    lens_ref,  # (S,) int32 — valid KV length (deepest visible pos + 1)
    # tensor blocks
    qpos_ref,  # (1, T) int32 — absolute position of EVERY packed token
    # (a VMEM vector read; scalar-prefetch refs only serve scalar loads)
    q_ref,  # (1, n_head, T, hs) — the whole packed batch rides every step
    k_ref,  # (1, kv_step, G, hs) — table-resolved KV sub-block
    v_ref,
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref — quantized
    # pools insert the sub-block's (1, G) scales before the output
    kv_step: int,
    n_groups: int,
    n_tokens: int,
    scale: float,
    q_pack: int,
    quantized: bool,
):
    # o_ref (1, n_head, T, hs); scratch: every (head, packed token) pair
    # is one online-softmax row — m/l (n_head*T, scratch_width),
    # acc (n_head*T, hs)
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    s_id = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(jnp.logical_and(s_id == 0, i == 0))
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qstart_ref[s_id]
    q_len = qlen_ref[s_id]
    n_live = lens_ref[s_id]

    @pl.when(jnp.logical_and(q_len > 0, i * kv_step < n_live))
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (n_head, T, hs)
        n_head, T, hs = q.shape
        q_per_kv = n_head // n_groups
        k = k_ref[0].astype(jnp.float32)  # (kv_step, G, hs)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # in-loop dequant: the int8 sub-block just DMA'd scales by its
            # own per-group factor — no fp copy of the pool materializes
            k = k * ks_ref[0][None, :, None]
            v = v * vs_ref[0][None, :, None]
        rows_g = q_per_kv * T
        qg = q.reshape(n_groups, rows_g, hs)
        if q_pack > 1:
            s = _packed_qk(qg, k, q_pack, scale)
        else:
            s = jax.lax.dot_general(
                qg,
                k.transpose(1, 2, 0),  # (G, hs, kv_step)
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale
        s = s.reshape(n_head, T, kv_step)
        # ragged causal mask, the dense op's ONE rule per packed row: key
        # at absolute position j is valid for token t iff j <= q_pos[t]
        # and t lies in this slot's span
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, T, 1), 1)
        in_span = jnp.logical_and(t_idx >= q_start, t_idx < q_start + q_len)
        qpos = qpos_ref[0].reshape(1, T, 1)
        jpos = i * kv_step + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, kv_step), 2
        )
        s = jnp.where(jnp.logical_and(in_span, jpos <= qpos), s, NEG_INF)
        s = s.reshape(n_head * T, kv_step)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # (n_head * T, kv_step)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pg = p.reshape(n_groups, rows_g, kv_step)
        if q_pack > 1:
            pv = _packed_pv(pg, v, q_pack).reshape(n_head * T, hs)
        else:
            pv = jax.lax.dot_general(
                pg,
                v.transpose(1, 0, 2),  # (G, kv_step, hs)
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ).reshape(n_head * T, hs)
        # rows OUTSIDE this slot's span must keep their state untouched:
        # NEG_INF is finite, so a fully-masked untouched row would compute
        # p = exp(NEG_INF - NEG_INF) = 1 and pollute another slot's
        # accumulator with this slot's V blocks — gate the update per row
        row = jnp.broadcast_to(
            in_span.reshape(1, T), (n_head, T)
        ).reshape(n_head * T, 1)
        m_ref[...] = jnp.where(
            row, jnp.broadcast_to(m_new, m_ref.shape), m_ref[...]
        )
        l_ref[...] = jnp.where(
            row, jnp.broadcast_to(l_new, l_ref.shape), l_ref[...]
        )
        acc_ref[...] = jnp.where(row, corr * acc_ref[...] + pv, acc_ref[...])

    @pl.when(jnp.logical_and(
        s_id == pl.num_programs(0) - 1, i == pl.num_programs(1) - 1
    ))
    def _finalize():
        # padding rows no slot owns never accumulate (l == 0): the floor
        # keeps them finite — garbage by contract, discarded by the caller
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        out = acc_ref[...] / denom
        n_head_t, hs = out.shape
        o_ref[0] = out.reshape(
            n_head_t // n_tokens, n_tokens, hs
        ).astype(o_ref.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,  # (1, n_head, T, hs) packed slot-major ragged queries
    k_pool,  # (num_blocks, block_size, G, hs), or int8 {"q", "scale"}
    v_pool,
    block_tables: jnp.ndarray,  # (n_slots, max_blocks) int32
    q_start: jnp.ndarray,  # (n_slots,) span offset per slot
    q_len: jnp.ndarray,  # (n_slots,) span length (0 = slot absent)
    lens: jnp.ndarray,  # (n_slots,) valid KV length (deepest pos + 1)
    q_pos: jnp.ndarray,  # (T,) absolute position per packed token
    *,
    scale: float,
    params: Optional[KernelParams] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Build and run the unified kernel on one packed ragged batch.

    This is the raw kernel entry — dispatch, fallbacks and the tp
    shard_map wrapper live in `ops/paged_attention.py`.  `params=None`
    resolves the conservative defaults for the pool geometry; pass the
    tuned entry from `ops/tuning.resolve_kernel_params` to pick layout.
    Raises `ValueError` (actionably) on unsupported shapes or invalid
    tuning parameters instead of silently degrading.  Returns
    ``(1, n_head, T, hs)``.
    """
    if not _HAS_PALLAS:
        raise ValueError(
            "ragged_paged_attention needs jax.experimental.pallas, which "
            "this jax build lacks — drop use_kernel=True to serve on the "
            "lax fallback"
        )
    B, n_head, T, hs = q.shape
    if B != 1:
        raise ValueError(
            f"ragged_paged_attention packs every slot into one ragged "
            f"batch: q must be (1, n_head, T, hs), got leading dim {B}"
        )
    k_arr, k_sc = _pool_parts(k_pool)
    v_arr, v_sc = _pool_parts(v_pool)
    quantized = k_sc is not None
    NB, BS, G, _ = k_arr.shape
    S, MB = block_tables.shape
    if n_head % G != 0:
        raise ValueError(
            f"n_head={n_head} must be a multiple of the pool's KV groups "
            f"G={G} (GQA grouping)"
        )
    rp = (params or KernelParams()).resolved(BS, G, hs)
    # under the tp shard_map this builder sees the LOCAL group count; a
    # globally-resolved pack factor folds down to what still divides
    rp = KernelParams(
        kv_step=rp.kv_step,
        q_pack=math.gcd(int(rp.q_pack or 1), G),
        scratch_width=rp.scratch_width,
    )
    problems = validate_kernel_params(rp, BS, G, hs)
    if problems:
        raise ValueError(
            "ragged_paged_attention: invalid kernel tuning parameters — "
            + "; ".join(problems)
            + " (fix the tuning-table entry, or pass params=KernelParams(...))"
        )
    kv_step = int(rp.kv_step)
    spb = BS // kv_step  # grid sub-steps per paged block

    tables = block_tables.astype(jnp.int32)
    qstart = q_start.astype(jnp.int32)
    qlen = q_len.astype(jnp.int32)
    lens32 = lens.astype(jnp.int32)
    qpos2d = q_pos.astype(jnp.int32).reshape(1, T)

    def kv_index(sidx, i, tables_ref, qstart_ref, qlen_ref, lens_ref):
        # unneeded grid steps remap to (trash) block 0: the DMA still
        # happens (the grid is static) but never re-reads a far block
        needed = jnp.logical_and(
            qlen_ref[sidx] > 0, i * kv_step < lens_ref[sidx]
        )
        blk = jnp.where(needed, tables_ref[sidx, i // spb], 0)
        return (blk, i % spb, 0, 0)

    def scale_index(sidx, i, tables_ref, qstart_ref, qlen_ref, lens_ref):
        needed = jnp.logical_and(
            qlen_ref[sidx] > 0, i * kv_step < lens_ref[sidx]
        )
        blk = jnp.where(needed, tables_ref[sidx, i // spb], 0)
        return (blk, 0)

    in_specs = [
        pl.BlockSpec((1, T), lambda s, i, *_: (0, 0)),  # q_pos
        pl.BlockSpec((1, n_head, T, hs), lambda s, i, *_: (0, 0, 0, 0)),
        pl.BlockSpec((1, kv_step, G, hs), kv_index),
        pl.BlockSpec((1, kv_step, G, hs), kv_index),
    ]
    operands = [qpos2d, q, k_arr, v_arr]
    if quantized:
        in_specs += [pl.BlockSpec((1, G), scale_index)] * 2
        operands += [k_sc, v_sc]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, MB * spb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, n_head, T, hs), lambda s, i, *_: (0, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((n_head * T, rp.scratch_width), jnp.float32),
            pltpu.VMEM((n_head * T, rp.scratch_width), jnp.float32),
            pltpu.VMEM((n_head * T, hs), jnp.float32),
        ],
    )
    kern = functools.partial(
        _unified_kernel,
        kv_step=kv_step, n_groups=G, n_tokens=T, scale=scale,
        q_pack=int(rp.q_pack), quantized=quantized,
    )
    with jax.named_scope("ragged_paged_attention"):
        return pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((1, n_head, T, hs), q.dtype),
            interpret=interpret,
        )(tables, qstart, qlen, lens32, *operands)
