"""Rotary position embeddings.

Semantics match the reference (`/root/reference/src/sub/model.py:856-891`,
litGPT convention): frequencies over the first `rope_n_elem` channels of each
head, the rotated half is `[-x2, x1]` with the head dim split in two
contiguous halves.  Implemented as pure jnp functions; the cos/sin cache is a
plain array pair that jit treats as ordinary operands, so position offsets are
dynamic (gathered per token) rather than baked into the trace.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax.numpy as jnp


def build_rope_cache(
    seq_len: int,
    n_elem: int,
    base: int = 10000,
    condense_ratio: int = 1,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Return (cos, sin), each of shape (seq_len, n_elem).

    Equivalent computation to reference `build_rope_cache` (model.py:856-878):
    theta_i = 1 / base^(2i/n_elem), positions optionally condensed.

    Computed in NumPy on the host: the tables are static for a config, so
    they must be constants (cacheable, safe to memoize) rather than traced
    values — inside jit they fold into the executable.
    """
    if n_elem <= 0:
        z = np.zeros((seq_len, 0), dtype=dtype)
        return z, z
    theta = 1.0 / (base ** (np.arange(0, n_elem, 2, dtype=np.float32) / n_elem))
    pos = np.arange(seq_len, dtype=np.float32) / condense_ratio
    idx_theta = np.outer(pos, theta)  # (S, n_elem//2)
    # duplicate to full n_elem: [f0..f{k-1}, f0..f{k-1}] — litGPT repeats the
    # half table so cos/sin have shape (S, n_elem)
    idx_theta = np.concatenate([idx_theta, idx_theta], axis=-1)
    return np.cos(idx_theta).astype(dtype), np.sin(idx_theta).astype(dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate the leading `n_elem` channels of each head.

    x: (..., T, head_size_slice) where the last dim == cos.shape[-1] == n_elem.
    cos/sin: broadcastable to x, typically (T, n_elem) or (B, 1, T, n_elem).

    Matches reference `apply_rope` (model.py:881-891): split in two halves,
    rotated = concat(-x2, x1).
    """
    n = x.shape[-1]
    x1 = x[..., : n // 2]
    x2 = x[..., n // 2 :]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos + rotated * sin).astype(x.dtype)


def gather_rope(
    cos: jnp.ndarray, sin: jnp.ndarray, input_pos: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Index the rope cache at dynamic positions.

    input_pos: int array (T,) or (B, T) → returns cos/sin of shape
    input_pos.shape + (n_elem,), ready to broadcast over heads after adding
    a head axis.
    """
    return jnp.take(cos, input_pos, axis=0), jnp.take(sin, input_pos, axis=0)
