"""Offline serving driver for the paged-KV continuous-batching engine.

Feeds a request trace — prompts from a file (one per line), a repeated
``--prompt``, or a mixed-length synthetic trace — through
`ServingEngine` (`serving/engine.py`): request-level scheduling over a
shared block pool, a unified token-budget step (every decode lane's
pending token + prefill chunks in ONE ragged forward per dispatch),
mid-batch retirement, hash-based prefix caching.  Prints each finished
request (decoded when a tokenizer is available) and a one-line JSON stats
summary: tokens/s, KV-block utilization, prefix-cache hits, preemptions,
plus the per-request latency percentile block (TTFT/TPOT/E2E/queue-wait
p50/p95/p99).  `--metrics-out`/`--prom-out` dump the full metrics
registry, `--trace-out` a Perfetto-loadable request/step timeline —
all recorded at existing host-sync boundaries (docs/observability.md).

Examples::

    # 32 mixed-length synthetic requests, 8 decode slots
    python -m mdi_llm_tpu.cli.serve --model NanoLlama --synthetic 32 \
        --max-batch 8 --block-size 16 \
        --metrics-out logs/metrics.json --trace-out logs/trace.json

    # real prompts, one per line, against a converted checkpoint
    python -m mdi_llm_tpu.cli.serve --ckpt checkpoints/TinyLlama/... \
        --prompt-file prompts.txt --n-tokens 256
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from mdi_llm_tpu.cli._common import (
    DTYPES,
    add_common_args,
    load_model,
    resolve_kv_dtype,
    select_device,
    setup_logging,
)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    # serving_kv: --kv-dtype additionally accepts int8 (quantized paged
    # pool — int8 blocks + per-block scales, docs/perf.md)
    add_common_args(ap, serving_kv=True)
    ap.add_argument("--n-tokens", type=int, default=128,
                    help="max new tokens per request")
    ap.add_argument("--prompt", default="Once upon a time,",
                    help="prompt text used when no --prompt-file/--synthetic")
    ap.add_argument("--prompt-file", type=Path, default=None,
                    help="file with one prompt per line")
    ap.add_argument("--n-requests", type=int, default=8,
                    help="requests queued when using --prompt")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="queue N synthetic requests with mixed prompt/"
                    "output lengths (benchmarking without a tokenizer)")
    ap.add_argument("--block-size", type=int, default=16,
                    help="KV pool block width (tokens)")
    ap.add_argument("--max-blocks", type=int, default=None,
                    help="KV pool size in blocks (default: full coverage)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="concurrent decode slots")
    ap.add_argument("--prefill-chunk", type=int, default=128,
                    help="max prompt tokens one sequence feeds per step")
    ap.add_argument("--token-budget", type=int, default=None,
                    help="unified-step token budget: every engine step runs "
                    "ONE ragged forward packing each decode lane's pending "
                    "token plus prefill chunk tokens up to this width "
                    "(prompts longer than the leftover split across steps); "
                    "default max_batch + prefill-chunk")
    ap.add_argument("--decode-chunk", type=int, default=8,
                    help="device decode steps per host sync: K steps run as "
                    "one on-device lax.scan and the host reads tokens once "
                    "per K, amortizing the dispatch round-trip as RTT/K "
                    "(1 = per-step engine)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative serving: draft K tokens per request "
                    "(n-gram prompt lookup, or --draft-model where the "
                    "lookup misses) and verify them in one ragged forward "
                    "over the paged cache, emitting up to K+1 tokens per "
                    "sync.  At temperature 0 the verify is exact-match "
                    "(token-identical to plain decode); at temperature>0 "
                    "it is rejection-sampled (accept w.p. min(1, "
                    "p_verify/p_draft), else resample the residual) and "
                    "preserves the per-step sampling distribution "
                    "(0 disables)")
    ap.add_argument("--draft-model", default=None, metavar="NAME",
                    help="registry name of a small draft model for "
                    "speculative serving (needs --spec-k > 0): drafts "
                    "spec_k tokens in one jitted greedy scan from a "
                    "second paged pool carved out of the block budget "
                    "(ServingConfig.draft_share) wherever the n-gram "
                    "lookup misses.  The engine random-inits the draft "
                    "params — useful accept rates need a draft trained "
                    "on the target's distribution")
    ap.add_argument("--no-double-buffer", action="store_true",
                    help="do not overlap a decode chunk's host read with "
                    "the next chunk's on-device compute")
    ap.add_argument("--scan-unroll", type=int, default=1,
                    help="layer-scan unroll factor for the decode/verify "
                    "steps (transformer.run_blocks(unroll=)): divides the "
                    "per-layer while-loop fixed cost that dominates small "
                    "models (docs/perf.md hypothesis 1)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel serving: shard the model "
                    "(Megatron rules) and the paged KV pool's head "
                    "dimension over a tp-axis mesh of this many devices "
                    "(make_mesh); n_query_groups must divide by it — "
                    "mdi-audit preflights the mesh (bad-serving-mesh). "
                    "1 = single device")
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline-parallel serving: split the layers over "
                    "this many recurrent ring stages (stage_layers "
                    "starter/secondary policy), each holding its own shard "
                    "of the paged KV pool; composes with --tp (tp x pp "
                    "devices).  Decode lanes fill the ring, so keep "
                    "--max-batch >= --pp (mdi-audit warns with the bubble "
                    "fraction otherwise).  1 = no pipelining")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable hash-based prefix block reuse")
    ap.add_argument("--host-pool-mib", type=int, default=0,
                    help="host-RAM KV block tier in MiB (0 = off): "
                    "preemption victims swap their int8/fp blocks to "
                    "pinned host slabs and resume without re-prefill "
                    "(when the swap cost model beats recompute), and "
                    "cold prefix chains spill there instead of being "
                    "dropped (docs/perf.md 'Tiered KV')")
    ap.add_argument("--host-link-gbps", type=float, default=None,
                    help="host<->device link bandwidth (GB/s) for the "
                    "swap-vs-recompute cost model (default: "
                    "per-device-kind table in serving/host_tier.py)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="engine-wide sampling temperature (0 = greedy)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="top-k sampling filter (temperature>0)")
    ap.add_argument("--top-p", type=float, default=None,
                    help="nucleus sampling filter (temperature>0)")
    ap.add_argument("--policy", default="fcfs",
                    choices=("fcfs", "priority", "fair", "deadline"),
                    help="scheduling policy (serving/policy.py): admission "
                    "order and prefill packing order — fcfs (default, the "
                    "historical scheduler), priority (higher "
                    "Request.priority first), fair (per-tenant fair-share "
                    "token accounting), deadline (TTFT-SLO "
                    "earliest-deadline-first admission + least-slack "
                    "prefill packing).  Replay traces carry default "
                    "priority/tenant/SLO attributes, so non-FCFS policies "
                    "mainly matter through mdi-server's HTTP API")
    ap.add_argument("--no-preflight", action="store_true",
                    help="downgrade a failing mdi-audit preflight to a "
                    "warning instead of refusing to launch")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget for the preflight audit")
    # observability (docs/observability.md): request-lifecycle tracing and
    # TTFT/TPOT percentile metrics, recorded only at the engine's existing
    # host-sync boundaries — zero extra syncs, zero recompiles
    ap.add_argument("--metrics-out", type=Path, default=None, metavar="JSON",
                    help="write serving metrics JSON: per-request "
                    "TTFT/TPOT/E2E/queue-wait p50/p95/p99, counter/gauge/"
                    "histogram registry, canonical serving stats "
                    "(docs/observability.md metric catalog)")
    ap.add_argument("--prom-out", type=Path, default=None, metavar="TXT",
                    help="also write the metrics registry in Prometheus "
                    "text exposition format")
    ap.add_argument("--trace-out", type=Path, default=None, metavar="JSON",
                    help="write a Chrome-trace-event timeline of the run "
                    "(request lifecycles + engine steps) — open in "
                    "Perfetto (ui.perfetto.dev) or chrome://tracing")
    ap.add_argument("--trace-ring", type=int, default=65536,
                    help="bounded ring capacity for trace events and the "
                    "completed-request percentile window (memory stays "
                    "O(ring) however long the engine runs)")
    ap.add_argument("--sample-rss", type=float, default=None, nargs="?",
                    const=0.5, metavar="SECONDS",
                    help="sample the host process tree's RSS into a "
                    "host_rss_bytes gauge at most once per this many "
                    "seconds (default 0.5 when given bare), at host-sync "
                    "boundaries only — the in-process successor to the "
                    "standalone mem_monitor wrapper")
    # device-side observability (docs/observability.md "Device-side"):
    # XLA executable introspection is ALWAYS on (one side-band AOT
    # compile per executable at warmup, zero device work); the xprof
    # flags add a bounded deep-profile window
    ap.add_argument("--no-device-obs", action="store_true",
                    help="skip the XLA executable introspection "
                    "(cost/memory analysis per serving executable) and "
                    "the MFU/MBU roofline block in the stats line")
    ap.add_argument("--xprof-steps", type=int, default=None, metavar="N",
                    help="wrap N mid-run engine steps (after --xprof-skip "
                    "warm steps) in a jax.profiler trace, so a "
                    "production-length replay yields a BOUNDED xplane "
                    "capture (utils/profiling.StepWindowProfiler)")
    ap.add_argument("--xprof-dir", type=Path, default=Path("logs/xprof"),
                    metavar="DIR",
                    help="where --xprof-steps writes the trace "
                    "(open with tensorboard --logdir or Perfetto)")
    ap.add_argument("--xprof-skip", type=int, default=8,
                    help="engine steps to let pass before the --xprof-steps "
                    "window opens (past warmup compiles, into steady state)")
    return ap


def synthetic_trace(n: int, vocab: int, max_seq: int, max_new: int, seed=10137):
    """Mixed-length request trace: prompt lengths log-spread across the
    window, output budgets spread across [8, max_new] — the shape that
    makes continuous batching win over static batching."""
    import numpy as np

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(4, max(5, max_seq // 4)))
        new = int(rng.integers(8, max(9, max_new + 1)))
        # clamp into the window but never below the 1-token engine minimum
        new = max(1, min(new, max_seq - plen - 1))
        prompt = rng.integers(1, vocab, plen).tolist()
        reqs.append((f"syn{i}", prompt, new))
    return reqs


def make_serving_config(args, admission_queue=None):
    """The `ServingConfig` the CLI flags describe — shared by the replay
    driver here and the open-system `mdi-server` (`cli/server.py`), so
    both audit and run EXACTLY the same config."""
    from mdi_llm_tpu.config import ServingConfig

    # --kv-dtype int8 selects the QUANTIZED POOL (ServingConfig.kv_dtype:
    # int8 blocks + per-block scales, ~2x resident sequences per HBM byte);
    # the dense-cache cast dtypes keep flowing through cache_dtype below
    return ServingConfig(
        block_size=args.block_size,
        max_blocks=args.max_blocks,
        max_batch=args.max_batch,
        prefill_chunk=args.prefill_chunk,
        token_budget=args.token_budget,
        decode_chunk=args.decode_chunk,
        spec_k=args.spec_k,
        double_buffer=not args.no_double_buffer,
        prefix_caching=not args.no_prefix_cache,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        draft_model=args.draft_model,
        kv_dtype="int8" if args.kv_dtype == "int8" else None,
        admission_queue=admission_queue,
        host_pool_mib=args.host_pool_mib,
        host_link_gbps=args.host_link_gbps,
        # spill needs the prefix hash chains; keep the audit clean when
        # the cache is off by degrading to a swap-only tier
        host_prefix_spill=not args.no_prefix_cache,
    )


def preflight_serving(args, serving_cfg, origin):
    """mdi-audit preflight + the pool-size log line (shared with
    `mdi-server`).  Runs BEFORE the checkpoint load: a refused plan must
    not pay the weight load (docs/analysis.md "Plan audit")."""
    from mdi_llm_tpu.analysis.audit import enforce_preflight, preflight
    from mdi_llm_tpu.cli._common import resolve_config

    report = preflight(
        resolve_config(args),
        tp=args.tp,
        pp=getattr(args, "pp", 1),
        batch=args.max_batch,
        seq_len=args.sequence_length,
        dtype=args.dtype,
        cache_dtype=args.kv_dtype,
        quantize=args.quantize,
        serving=serving_cfg,
        hbm_gb=args.hbm_gb,
        origin=origin,
    )
    enforce_preflight(report, origin, allow=args.no_preflight)
    pool = report.breakdown.get("kv_pool", {})
    if pool:
        axes = " x ".join(
            f"{ax}={pool[ax]}" for ax in ("tp", "pp") if pool.get(ax, 1) > 1
        )
        per_dev = (
            f" ({pool['pool_bytes_per_device'] / 2**20:.1f} MiB/device over "
            f"{axes})" if axes else ""
        )
        q_tag = (
            f" [int8 + {pool['scale_bytes'] / 2**20:.2f} MiB scales]"
            if pool.get("kv_dtype") == "int8" else ""
        )
        print(
            f"{origin}: KV pool {pool['num_blocks']} blocks x "
            f"{pool['block_size']} tokens ~= {pool['pool_bytes'] / 2**20:.1f}"
            f" MiB{q_tag}{per_dev}",
            file=sys.stderr,
        )
        if pool.get("host_blocks"):
            print(
                f"{origin}: host KV tier {pool['host_blocks']} blocks ~= "
                f"{pool['host_pool_bytes'] / 2**20:.1f} MiB pinned host RAM",
                file=sys.stderr,
            )
    return report


def build_generator(args, cfg, params):
    """The serving `Generator` the CLI flags describe (tp mesh, cache
    dtype, quantization) — shared with `mdi-server`."""
    from mdi_llm_tpu.generation import Generator

    dtype = DTYPES[args.dtype]
    pool_int8 = args.kv_dtype == "int8"
    mesh = None
    tp, pp = args.tp, getattr(args, "pp", 1)
    if tp > 1 or pp > 1:
        from mdi_llm_tpu.parallel.mesh import make_mesh

        axes = {}
        if tp > 1:
            axes["tp"] = tp
        if pp > 1:
            axes["pp"] = pp
        mesh = make_mesh(axes)
    return Generator(
        cfg, params,
        max_seq_length=args.sequence_length,
        cache_dtype=(
            dtype if pool_int8
            else resolve_kv_dtype(args.kv_dtype) or dtype
        ),
        quantize=args.quantize,
        mesh=mesh,
        scan_unroll=args.scan_unroll,
    )


def main(argv=None):
    args = build_parser().parse_args(argv)
    setup_logging(args)
    select_device(args)

    import numpy as np

    serving_cfg = make_serving_config(args)
    preflight_serving(args, serving_cfg, "mdi-serve")

    cfg, params, tokenizer, _style = load_model(
        args, need_tokenizer=not args.synthetic
    )
    gen = build_generator(args, cfg, params)
    # observability rides every run (its hooks are host-side appends at
    # sync boundaries the loop already owns — docs/observability.md); the
    # file flags only decide what gets WRITTEN at the end
    from mdi_llm_tpu.obs import ServingObserver
    from mdi_llm_tpu.serving.policy import make_policy

    obs = ServingObserver(ring=args.trace_ring,
                          rss_interval_s=args.sample_rss,
                          device=not args.no_device_obs)
    # the audited config IS the engine config — no second hand-kept copy
    engine = gen.serve(serving=serving_cfg, obs=obs,
                       policy=make_policy(args.policy))

    # trace-level preflight: verify the compile set and the IR invariants
    # on abstract jaxprs of THIS engine's executables (docs/analysis.md)
    from mdi_llm_tpu.analysis.ir import enforce_ir_preflight, ir_preflight

    ir_report = ir_preflight(engine, origin="mdi-serve")
    enforce_ir_preflight(ir_report, "mdi-serve", allow=args.no_preflight)

    # buffer-liveness preflight over the same traced executables: donation
    # aliasing, live-range bloat, static peak-HBM (docs/analysis.md,
    # "Buffer liveness (mdi-flow)")
    from mdi_llm_tpu.analysis.liveness import (
        enforce_flow_preflight,
        flow_preflight,
    )

    flow_report = flow_preflight(engine, origin="mdi-serve")
    enforce_flow_preflight(flow_report, "mdi-serve", allow=args.no_preflight)

    if args.synthetic:
        trace = synthetic_trace(
            args.synthetic, cfg.vocab_size, gen.max_seq_length, args.n_tokens
        )
    else:
        if args.prompt_file:
            texts = [
                ln for ln in args.prompt_file.read_text().splitlines() if ln.strip()
            ]
        else:
            texts = [args.prompt] * args.n_requests
        if tokenizer is None:
            raise SystemExit(
                "text prompts need a tokenizer (--ckpt); use --synthetic "
                "with --model for tokenizer-free runs"
            )
        trace = [
            (f"req{i}", tokenizer.encode(t).tolist(), args.n_tokens)
            for i, t in enumerate(texts)
        ]

    for rid, prompt, new in trace:
        engine.add_request(rid, prompt, new)
    # --xprof-steps: a bounded deep-profile window over N mid-run steps —
    # NOT the whole run, so replay length never bloats the capture
    xprof = None
    if args.xprof_steps:
        from mdi_llm_tpu.utils.profiling import StepWindowProfiler

        args.xprof_dir.mkdir(parents=True, exist_ok=True)
        xprof = StepWindowProfiler(
            args.xprof_dir, args.xprof_steps, skip=args.xprof_skip
        )
    try:
        results, stats = engine.run(
            step_hook=xprof.on_step if xprof is not None else None
        )
    finally:
        if xprof is not None:
            xprof.close()  # short runs / exceptions: never leak a trace
    if xprof is not None and xprof.window is not None:
        print(
            f"mdi-serve: xprof window steps {xprof.window[0]}-"
            f"{xprof.window[1]} -> {args.xprof_dir} "
            "(tensorboard --logdir, or load in Perfetto)",
            file=sys.stderr,
        )

    for rid, prompt, _new in trace:
        out = results.get(rid, [])
        gen_tokens = out[len(prompt):]
        print(f"--- {rid} ({len(gen_tokens)} new tokens) " + "-" * 30)
        if tokenizer is not None:
            print(tokenizer.decode(np.asarray(out)))  # mdi-lint: disable=host-sync -- end-of-run print, not the serving loop
        else:
            print(gen_tokens)

    # canonical stats (ServingStats.to_dict — the same dict bench serve
    # rows embed) + CLI topology extras + the latency percentile block
    n_chips = max(1, args.tp) * max(1, args.pp)
    line = stats.to_dict()
    line.update({
        "kv_dtype": engine.kv_dtype_name,
        "tp": args.tp,
        "pp": args.pp,
        "devices": n_chips,
        "tokens_per_s_per_chip": round(stats.tokens_per_s / n_chips, 2),
        "latency": {
            name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in summ.items()}
            for name, summ in obs.latency_summaries().items()
        },
    })
    if args.pp > 1:
        # ring topology + fill model (serving/pipeline.py): stages, lane
        # fill and the steady-state bubble fraction (docs/perf.md)
        line["pipeline"] = engine.pipeline_fill()
    if not args.no_device_obs:
        # achieved MFU/MBU against the running chip's peak (null off the
        # peak table, e.g. CPU) — docs/observability.md "Device-side";
        # the full per-executable cost sheets land in --metrics-out
        import jax

        from mdi_llm_tpu.obs import roofline as rf

        kind = getattr(jax.devices()[0], "device_kind", None)
        ctxs = [
            len(p) + max(0, len(results.get(rid, [])) - len(p)) / 2
            for rid, p, _new in trace
        ]
        ctx_mean = int(sum(ctxs) / max(1, len(ctxs)))
        eff_batch = (
            max(1, round(stats.mixed_batch_occupancy * args.max_batch))
            if stats.mixed_batch_occupancy else args.max_batch
        )
        roof = rf.serving_roofline(
            cfg, serving_cfg, tokens_per_s=stats.tokens_per_s,
            context=ctx_mean, batch=eff_batch,
            weight_bytes=rf.param_bytes(gen.params),
            device_kind=kind, n_chips=n_chips, dtype=args.dtype,
        )
        line["device"] = {
            "kind": kind,
            "mfu": None if roof["mfu"] is None else round(roof["mfu"], 6),
            "mbu": None if roof["mbu"] is None else round(roof["mbu"], 6),
            "achieved_tflops_per_s": round(roof["achieved_tflops_per_s"], 4),
            "achieved_hbm_gbps": round(roof["achieved_hbm_gbps"], 4),
            "context_mean": ctx_mean,
            "executables": len(obs.device),
        }
    print(json.dumps(line), file=sys.stderr)

    if args.metrics_out:
        args.metrics_out.parent.mkdir(parents=True, exist_ok=True)
        args.metrics_out.write_text(
            json.dumps(obs.metrics_dict(stats), indent=2) + "\n"
        )
        print(f"mdi-serve: metrics -> {args.metrics_out}", file=sys.stderr)
    if args.prom_out:
        args.prom_out.parent.mkdir(parents=True, exist_ok=True)
        args.prom_out.write_text(obs.metrics.render_prometheus())
        print(f"mdi-serve: prometheus -> {args.prom_out}", file=sys.stderr)
    if args.trace_out:
        obs.tracer.write_chrome_trace(args.trace_out)
        print(
            f"mdi-serve: trace -> {args.trace_out} "
            "(open in https://ui.perfetto.dev)",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
