"""Open-system HTTP serving daemon over the paged-KV engine.

Where `mdi-serve` replays a fixed trace and exits, `mdi-server` stands
up the live system (docs/serving.md): the continuous-batching engine
runs in a dedicated thread behind a bounded admission queue, and an
asyncio HTTP front door streams tokens to clients over SSE —

    POST /v1/completions   JSON in; SSE token stream or JSON out
    GET  /healthz          liveness + queue/lane depths
    GET  /v1/stats         canonical ServingStats + latency percentiles
    GET  /metrics          Prometheus text exposition

Backpressure is explicit: arrivals past ``--admission-queue`` get 429 +
Retry-After (shed load is measurable load, not a crash), and SIGINT/
SIGTERM trigger a graceful drain — stop accepting, finish in-flight
streams, stop the engine — bounded by ``--drain-timeout``.

Scheduling is policy-pluggable (``--policy``): priority classes,
per-tenant fair share and TTFT-deadline EDF ride the request body's
``priority`` / ``tenant`` / ``ttft_slo_ms`` fields.

Examples::

    # synthetic-weight dev server on port 8080, fair-share scheduling
    python -m mdi_llm_tpu.cli.server --model NanoLlama --port 8080 \
        --max-batch 8 --policy fair

    # real checkpoint (text prompts + decoded SSE), deadline scheduling
    python -m mdi_llm_tpu.cli.server --ckpt checkpoints/TinyLlama/... \
        --policy deadline --admission-queue 64

    curl -N localhost:8080/v1/completions -d \
        '{"prompt": "Once upon a time,", "max_tokens": 64, "stream": true}'
"""

from __future__ import annotations

import asyncio
import json
import signal
import sys

from mdi_llm_tpu.cli._common import load_model, select_device, setup_logging


def build_parser():
    import argparse

    from mdi_llm_tpu.cli.serve import build_parser as serve_parser

    ap = argparse.ArgumentParser(
        description=__doc__,
        parents=[serve_parser()], conflict_handler="resolve", add_help=True,
    )
    # the replay-trace knobs stay (they size nothing here) but the server
    # adds its own surface on top
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (0.0.0.0 exposes the server beyond "
                    "localhost — it speaks plaintext HTTP with no auth, so "
                    "front it with something that terminates TLS first)")
    ap.add_argument("--port", type=int, default=8000,
                    help="TCP port (0 = ephemeral, printed at startup)")
    ap.add_argument("--admission-queue", type=int, default=None,
                    help="bound on accepted-but-not-yet-scheduled requests; "
                    "arrivals past it get HTTP 429 + Retry-After instead of "
                    "growing an unbounded queue (default 4 x max-batch; "
                    "mdi-audit checks it against the pool's headroom — "
                    "bad-server-config)")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    help="graceful-shutdown bound (s): on SIGINT/SIGTERM "
                    "stop accepting (503), wait this long for in-flight "
                    "requests to finish, then stop the engine thread")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    setup_logging(args)
    select_device(args)

    from mdi_llm_tpu.cli.serve import (
        build_generator,
        make_serving_config,
        preflight_serving,
    )
    from mdi_llm_tpu.obs import ServingObserver
    from mdi_llm_tpu.serving.policy import make_policy

    serving_cfg = make_serving_config(
        args, admission_queue=args.admission_queue
    )
    preflight_serving(args, serving_cfg, "mdi-server")

    # tokenizer is optional here: token-id requests always work, text
    # prompts 400 without one (the HTTP layer explains)
    cfg, params, tokenizer, _style = load_model(args, need_tokenizer=False)
    gen = build_generator(args, cfg, params)
    obs = ServingObserver(ring=args.trace_ring,
                          rss_interval_s=args.sample_rss,
                          device=not args.no_device_obs)
    engine = gen.serve(serving=serving_cfg, obs=obs,
                       policy=make_policy(args.policy))

    from mdi_llm_tpu.server import ServingFrontend
    from mdi_llm_tpu.server.http import ServingHTTPServer

    frontend = ServingFrontend(engine, max_queue=args.admission_queue)
    server = ServingHTTPServer(
        frontend, host=args.host, port=args.port, tokenizer=tokenizer,
        drain_timeout_s=args.drain_timeout,
    )

    async def run():
        await server.start()
        print(
            f"mdi-server: serving {cfg.name} on "
            f"http://{args.host}:{server.port} (policy={args.policy}, "
            f"slots={args.max_batch}, admission queue "
            f"{frontend.max_queue}; POST /v1/completions, GET /healthz)",
            file=sys.stderr,
        )
        loop = asyncio.get_running_loop()
        drain = asyncio.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, drain.set)
            except NotImplementedError:  # non-unix event loops
                pass
        await drain.wait()
        print("mdi-server: draining (new requests get 503) ...",
              file=sys.stderr)
        await server.shutdown()
        # the same canonical stats line mdi-serve prints, so a server
        # session lands in logs exactly like a replay run
        line = engine.stats.to_dict()
        line["latency"] = {
            name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in summ.items()}
            for name, summ in obs.latency_summaries().items()
        }
        print(json.dumps(line), file=sys.stderr)

    asyncio.run(run())


if __name__ == "__main__":
    main()
