"""`mdi-doctor`: staged backend triage that a wedged libtpu cannot hang.

Since r03 the bench suite's TPU probes have been timing out into CPU
fallbacks, and nothing in any artifact said WHY: the probe is a single
subprocess that either answers or doesn't.  This tool decomposes backend
bring-up into ordered stages — import jax → enumerate devices → tiny
compiled matmul → donation round-trip → profiler-trace write → one
collective (when >1 device) — and runs EACH stage in its own subprocess
under its own hard timeout, so a wedge localizes to a stage instead of
eating the whole budget, and the tool itself always returns.

The output is a JSON health snapshot: toolchain versions (read via
importlib.metadata, no jax import in the parent — a hosed install must
not take the doctor down), platform/hostname, the probe-relevant
environment (`JAX_PLATFORMS`, `TPU_*`, `XLA_*`, ...), and per-stage
status/elapsed/error/detail.  Bench embeds the cheap half of this
snapshot (`provenance()`) in every suite artifact, and `bench --doctor`
runs the full `--quick` staged triage as a preflight — so the next
r03-style wedge is diagnosable from the artifact alone
(docs/observability.md "Device-side observability").

Exit status: 0 when every stage is ok/skipped, 1 otherwise.

Examples::

    mdi-doctor                 # full triage, JSON line on stdout
    mdi-doctor --quick         # import/devices/matmul only
    mdi-doctor --json out.json # also write a pretty snapshot file
    mdi-doctor --device cpu    # pin the stages to the CPU backend
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# Environment keys that decide which backend comes up (and how): captured
# verbatim into the snapshot so two artifacts can be diffed.  Values are
# truncated, never redacted — these are platform knobs, not secrets.
_ENV_PREFIXES = ("JAX_", "TPU_", "LIBTPU", "XLA_", "PJRT_")
_ENV_VALUE_CAP = 200

# Each stage is a self-contained python snippet run as `python -c` in a
# FRESH interpreter: stage N's wedge cannot poison stage N+1's process,
# and the parent enforces the timeout with a kill.  A stage prints ONE
# JSON line on stdout (its `detail`); a `skipped` key marks a stage that
# chose not to run (e.g. the collective on a single device).
STAGES: List[Dict[str, Any]] = [
    {
        "name": "import_jax",
        "help": "import jax/jaxlib and report their versions",
        "timeout": 120.0,
        "quick": True,
        "code": (
            "import json, time\n"
            "t0 = time.perf_counter()\n"
            "import jax, jaxlib\n"
            "print(json.dumps({'jax': jax.__version__,"
            " 'jaxlib': jaxlib.__version__,"
            " 'import_s': round(time.perf_counter() - t0, 3)}))\n"
        ),
    },
    {
        "name": "devices",
        "help": "bring up the backend and enumerate devices",
        "timeout": 180.0,
        "quick": True,
        "code": (
            "import json, jax\n"
            "ds = jax.devices()\n"
            "print(json.dumps({'platform': jax.default_backend(),"
            " 'device_count': len(ds),"
            " 'device_kind': ds[0].device_kind,"
            " 'devices': [str(d) for d in ds[:8]]}))\n"
        ),
    },
    {
        "name": "matmul",
        "help": "compile and run one tiny matmul",
        "timeout": 180.0,
        "quick": True,
        "code": (
            "import json, time, jax, jax.numpy as jnp\n"
            "t0 = time.perf_counter()\n"
            "x = jnp.ones((128, 128), jnp.bfloat16)\n"
            "y = (x @ x).block_until_ready()\n"
            "print(json.dumps({'matmul_s':"
            " round(time.perf_counter() - t0, 3),"
            " 'correct': bool(float(y[0, 0]) == 128.0)}))\n"
        ),
    },
    {
        "name": "donation",
        "help": "donated-buffer round-trip (the serving engine's idiom)",
        "timeout": 120.0,
        "quick": False,
        "code": (
            "import json, jax, jax.numpy as jnp\n"
            "f = jax.jit(lambda a: a + 1, donate_argnums=(0,))\n"
            "x = jax.device_put(jnp.zeros((256, 256), jnp.float32))\n"
            "y = f(x).block_until_ready()\n"
            "print(json.dumps({'donated': bool(x.is_deleted()),"
            " 'correct': bool(float(y[0, 0]) == 1.0)}))\n"
        ),
    },
    {
        "name": "profiler_trace",
        "help": "write a jax.profiler trace (the --profile/--xprof path)",
        "timeout": 120.0,
        "quick": False,
        "code": (
            "import json, os, tempfile, jax, jax.numpy as jnp\n"
            "d = tempfile.mkdtemp(prefix='mdi_doctor_xprof_')\n"
            "with jax.profiler.trace(d):\n"
            "    (jnp.ones((64, 64)) @ jnp.ones((64, 64)))"
            ".block_until_ready()\n"
            "files = [f for r, _, fs in os.walk(d) for f in fs]\n"
            "print(json.dumps({'n_files': len(files),"
            " 'wrote_xplane': any(f.endswith('.xplane.pb')"
            " for f in files)}))\n"
        ),
    },
    {
        "name": "collective",
        "help": "one psum across all devices (skipped on 1 device)",
        "timeout": 180.0,
        "quick": False,
        "code": (
            "import json, jax, jax.numpy as jnp\n"
            "n = jax.device_count()\n"
            "if n < 2:\n"
            "    print(json.dumps({'skipped': 'single device'}))\n"
            "else:\n"
            "    out = jax.pmap(lambda x: jax.lax.psum(x, 'i'),"
            " axis_name='i')(jnp.ones((n,)))\n"
            "    print(json.dumps({'devices': n,"
            " 'psum_correct': bool(float(out[0]) == n)}))\n"
        ),
    },
    {
        "name": "threads",
        "help": "seeded schedule-explorer burst on a tiny CPU serving "
                "engine (host concurrency-contract triage)",
        "timeout": 300.0,
        "quick": False,
        "code": (
            "import json\n"
            "from mdi_llm_tpu.server.explorer import doctor_burst\n"
            "print(json.dumps(doctor_burst()))\n"
        ),
    },
]


def _package_versions() -> Dict[str, Optional[str]]:
    """Toolchain versions WITHOUT importing anything heavy: a wedged or
    half-installed jax must not prevent the snapshot from recording what
    is installed (the import itself is stage 1's job)."""
    from importlib import metadata

    out: Dict[str, Optional[str]] = {}
    for pkg in ("jax", "jaxlib", "numpy"):
        try:
            out[pkg] = metadata.version(pkg)
        except Exception:
            out[pkg] = None
    out["libtpu"] = None
    for pkg in ("libtpu", "libtpu-nightly"):
        try:
            out["libtpu"] = metadata.version(pkg)
            break
        except Exception:
            continue
    return out


def _probe_env() -> Dict[str, str]:
    return {
        k: (v if len(v) <= _ENV_VALUE_CAP else v[:_ENV_VALUE_CAP] + "…")
        for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES)
    }


def provenance() -> Dict[str, Any]:
    """The cheap, always-safe environment record (no subprocess, no jax):
    versions + host + probe-relevant env.  Bench embeds this in EVERY
    suite artifact as `detail.provenance` so trajectory JSONs are
    comparable across environments; `collect_snapshot` extends it with
    staged probe results."""
    return {
        "schema": SCHEMA_VERSION,
        "hostname": socket.gethostname(),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "versions": _package_versions(),
        "env": _probe_env(),
    }


def run_stage(stage: Dict[str, Any], timeout: Optional[float] = None,
              env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Run one stage in a fresh interpreter under a hard timeout.  Returns
    {"name", "status": ok|failed|timeout|skipped, "elapsed_s", "timeout_s",
    "error", "detail"} — the record shape the snapshot schema pins."""
    budget = float(timeout if timeout is not None else stage["timeout"])
    rec: Dict[str, Any] = {
        "name": stage["name"],
        "status": "failed",
        "elapsed_s": 0.0,
        "timeout_s": budget,
        "error": None,
        "detail": {},
    }
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", stage["code"]],
            capture_output=True, text=True, timeout=budget,
            env={**os.environ, **(env or {})},
        )
    except subprocess.TimeoutExpired:
        rec["elapsed_s"] = round(time.perf_counter() - t0, 3)
        rec["status"] = "timeout"
        rec["error"] = f"no answer within {budget:g}s (process killed)"
        return rec
    except Exception as exc:  # spawn failure: still a record, never a raise
        rec["elapsed_s"] = round(time.perf_counter() - t0, 3)
        rec["error"] = f"{type(exc).__name__}: {exc}"
        return rec
    rec["elapsed_s"] = round(time.perf_counter() - t0, 3)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
        rec["error"] = " | ".join(tail) or f"exit code {proc.returncode}"
        return rec
    payload: Dict[str, Any] = {}
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                payload = json.loads(line)
            except json.JSONDecodeError:
                pass
            break
    rec["detail"] = payload
    rec["status"] = "skipped" if "skipped" in payload else "ok"
    return rec


def collect_snapshot(quick: bool = False,
                     stage_timeout: Optional[float] = None,
                     stages: Optional[List[Dict[str, Any]]] = None,
                     env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Provenance + staged probe results.  `quick` keeps the first three
    stages (import/devices/matmul — the is-the-backend-alive question);
    `stage_timeout` overrides every stage's own budget; `stages` swaps in
    a custom stage list (tests inject a wedged stage to pin the timeout
    machinery).  `ok` is True iff every stage ended ok/skipped."""
    chosen = stages if stages is not None else [
        s for s in STAGES if not quick or s.get("quick")
    ]
    records = [run_stage(s, timeout=stage_timeout, env=env) for s in chosen]
    snap = provenance()
    snap["quick"] = bool(quick)
    snap["stages"] = records
    snap["ok"] = all(r["status"] in ("ok", "skipped") for r in records)
    for r in records:  # surface the device identity at the top level
        d = r.get("detail") or {}
        if "device_kind" in d:
            snap["backend"] = d.get("platform")
            snap["device_kind"] = d.get("device_kind")
            snap["device_count"] = d.get("device_count")
            break
    return snap


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-doctor",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--quick", action="store_true",
                    help="run only the bring-up stages (import_jax, "
                    "devices, matmul) — the bench --doctor preflight")
    ap.add_argument("--stage-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="override every stage's own hard timeout "
                    "(defaults are per stage, 120-180 s)")
    ap.add_argument("--json", type=str, default=None, metavar="PATH",
                    help="also write the snapshot as pretty JSON to PATH "
                    "(stdout always carries the one-line snapshot)")
    ap.add_argument("--device", default=None, metavar="PLATFORM",
                    help="pin the stage subprocesses to a jax platform "
                    "(sets JAX_PLATFORMS for them, e.g. cpu)")
    ap.add_argument("--list-stages", action="store_true",
                    help="print the stage list and exit")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_stages:
        for s in STAGES:
            tag = " [quick]" if s.get("quick") else ""
            print(f"{s['name']:<16} {s['timeout']:>5.0f}s{tag}  {s['help']}")
        return 0
    env = {"JAX_PLATFORMS": args.device} if args.device else None
    snap = collect_snapshot(
        quick=args.quick, stage_timeout=args.stage_timeout, env=env
    )
    for r in snap["stages"]:
        mark = {"ok": "ok ", "skipped": "-- ", "timeout": "T/O",
                "failed": "ERR"}[r["status"]]
        line = f"mdi-doctor: [{mark}] {r['name']:<16} {r['elapsed_s']:.1f}s"
        if r["error"]:
            line += f"  {r['error']}"
        print(line, file=sys.stderr)
    v = snap["versions"]
    print(
        f"mdi-doctor: jax={v.get('jax')} jaxlib={v.get('jaxlib')} "
        f"libtpu={v.get('libtpu')} backend={snap.get('backend')} "
        f"device_kind={snap.get('device_kind')} "
        f"-> {'HEALTHY' if snap['ok'] else 'UNHEALTHY'}",
        file=sys.stderr,
    )
    print(json.dumps(snap), flush=True)
    if args.json:
        from pathlib import Path

        p = Path(args.json)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(snap, indent=2) + "\n")
        print(f"mdi-doctor: snapshot -> {p}", file=sys.stderr)
    return 0 if snap["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
