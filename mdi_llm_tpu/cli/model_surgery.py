"""Checkpoint surgery: repair or patch saved checkpoints in place.

≡ reference `old/GPT2/model_surgery.py` (fixes stale/broken fields in
training checkpoints so they load again).  Operations:

- `--set key=value`: patch a `model_config.yaml` field (e.g. a wrong
  `block_size`, a missing `name`); values parse as YAML scalars.
- `--rename old=new`: rename a top-level parameter entry.
- `--drop key`: delete a top-level parameter entry (e.g. a stale optimizer
  moment accidentally saved into the model tree).

Examples:
    python -m mdi_llm_tpu.cli.model_surgery --ckpt <dir> --set block_size=2048
    python -m mdi_llm_tpu.cli.model_surgery --ckpt <dir> --drop lm_head --dry-run
"""

from __future__ import annotations

import argparse
from pathlib import Path


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", type=Path, required=True)
    ap.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    ap.add_argument("--rename", action="append", default=[], metavar="OLD=NEW")
    ap.add_argument("--drop", action="append", default=[], metavar="KEY")
    ap.add_argument("--dry-run", action="store_true")
    return ap


def _parse_scalar(v: str):
    import yaml

    return yaml.safe_load(v)


def main(argv=None):
    args = build_parser().parse_args(argv)
    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    cfg, params = load_checkpoint(args.ckpt)
    conf = cfg.asdict()
    changed = []

    for item in args.set:
        k, _, v = item.partition("=")
        if k not in conf:
            raise SystemExit(f"unknown config field {k!r}")
        old = conf[k]
        conf[k] = _parse_scalar(v)
        changed.append(f"config {k}: {old!r} -> {conf[k]!r}")
    for item in args.rename:
        old, _, new = item.partition("=")
        if old not in params:
            raise SystemExit(f"no parameter entry {old!r} (have {sorted(params)})")
        params[new] = params.pop(old)
        changed.append(f"param rename {old} -> {new}")
    for k in args.drop:
        if k not in params:
            raise SystemExit(f"no parameter entry {k!r} (have {sorted(params)})")
        params.pop(k)
        changed.append(f"param drop {k}")

    for line in changed or ["(no changes requested)"]:
        print(line)
    if args.dry_run or not changed:
        return
    # reconstruct through __post_init__ so invariants re-validate
    new_cfg = Config(
        **{k: v for k, v in conf.items() if k in Config.__dataclass_fields__}
    )
    save_checkpoint(params, new_cfg, args.ckpt)
    print(f"rewrote {args.ckpt}")


if __name__ == "__main__":
    main()
