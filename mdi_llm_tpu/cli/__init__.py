"""Command-line entry points (≡ reference `src/*.py` CLIs)."""
