"""Starter-node entry point for multi-process pipeline generation.

Reference-parity CLI (`/root/reference/src/starter.py`): reads a nodes-config
topology file, brings the node group up, runs recurrent-pipeline generation,
prints the samples and writes tokens/time CSVs + plots + run-stats CSV with
the reference's file naming.

TPU-native semantics: instead of POSTing pickled init messages to CherryPy
servers on each secondary (`model_dist.py:402-497`), the starter is process 0
of a `jax.distributed` job; secondaries join with `cli/secondary.py` and the
whole group executes one SPMD ring program (parallel/pipeline.py) whose
stage-to-stage hop is `jax.lax.ppermute` over ICI/DCN.  Run parameters ship
starter→secondaries via a device broadcast (parallel/nodes.py).

Examples:
    # 1 host, all local chips (standalone.json analog — no secondaries):
    python -m mdi_llm_tpu.cli.starter --ckpt <dir> --nodes-config standalone.json

    # 3-node job (run cli/secondary.py on the other two hosts):
    python -m mdi_llm_tpu.cli.starter --ckpt <dir> --nodes-config cfg.json \
        --n-samples 3 --n-tokens 200 --plots --time-run stats.csv
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path

import jax
import numpy as np

from mdi_llm_tpu.cli._common import (
    add_common_args,
    add_run_args,
    load_model,
    report_run,
    resolve_kv_dtype,
    select_device,
    setup_logging,
)
from mdi_llm_tpu.parallel.nodes import (
    NodesConfig,
    broadcast_run_spec,
    check_params_consistency,
    init_distributed,
    parse_nodes_config,
)
from mdi_llm_tpu.utils.prompts import get_user_prompt


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    add_run_args(ap)
    ap.add_argument(
        "--nodes-config",
        type=Path,
        required=True,
        help="topology JSON (reference settings_distr schema or mesh schema)",
    )
    ap.add_argument(
        "--pipeline-stages",
        type=int,
        default=None,
        help="stages to split over (default: one per chip in the job)",
    )
    ap.add_argument(
        "--stream",
        action="store_true",
        help="print sample 0's text live as its tokens come back around "
        "the ring (≡ the reference starter surfacing tokens as they "
        "arrive, gptserver.py:904-956)",
    )
    ap.add_argument(
        "--samples-per-slot",
        type=int,
        default=1,
        help="samples batched per ring slot (M): full utilization serves "
        "stages×M concurrent samples",
    )
    ap.add_argument(
        "--chunk",
        type=int,
        default=16,
        help="steady-state ring rotations per jit dispatch",
    )
    ap.add_argument(
        "--tp-devices",
        type=int,
        default=None,  # None → config file value → 1 (an explicit 1 must
        # be able to override a config's tp_devices)
        help="tensor-parallel devices per pipeline stage (pipe x tp mesh)",
    )
    ap.add_argument(
        "--overlap-chunks",
        action="store_true",
        help="dispatch the next decode chunk before fetching the previous "
        "one (directly-attached TPUs only; stalls on remote tunnels)",
    )
    ap.add_argument(
        "--no-preflight",
        action="store_true",
        help="downgrade a failing mdi-audit plan preflight to a warning "
        "instead of refusing to launch the ring",
    )
    ap.add_argument(
        "--hbm-gb",
        type=float,
        default=None,
        help="per-device HBM budget for the preflight audit",
    )
    return ap


def run_node(args, nodes_cfg: NodesConfig, process_id: int):
    """Shared starter/secondary body: join the job, load the model, receive
    (or originate) the run spec, and execute the SPMD pipeline ring."""
    log = setup_logging(
        args, role="starter" if process_id == 0 else f"secondary{process_id - 1}"
    )
    # device priority: CLI > node JSON > auto (≡ gptserver.py:601-617)
    node = nodes_cfg.starter if process_id == 0 else nodes_cfg.secondary[process_id - 1]
    if not args.device and node.device:
        args.device = node.device
    select_device(args)
    init_distributed(nodes_cfg, process_id)
    is_starter = process_id == 0

    if is_starter:
        # CLI beats config file, config beats the default of 1 (same
        # precedence as the device override, gptserver.py:601-617)
        eff_tp = (
            args.tp_devices if args.tp_devices is not None
            else nodes_cfg.tp_devices
        )
        n_stages = (
            args.pipeline_stages
            or nodes_cfg.pipeline_stages
            or jax.device_count() // max(1, eff_tp)
        )
        raw_prompts = get_user_prompt(args.prompt, args.n_samples)

        # static plan audit BEFORE the checkpoint load and BEFORE committing
        # the job to this spec: sharding divisibility, stage split, ring-
        # schedule sanity, the paper's n_samples >= n_stages utilization
        # invariant (reported with the bubble fraction), optional --hbm-gb
        # budget.  Pure host analysis over the config alone — refusing here
        # costs nothing; a bad plan discovered at compile time costs minutes
        # on a pod (docs/analysis.md, "Plan audit").
        from mdi_llm_tpu.analysis.audit import (
            enforce_preflight,
            preflight,
            refusal_text,
        )
        from mdi_llm_tpu.cli._common import resolve_config

        report = preflight(
            resolve_config(args),
            n_stages=n_stages,
            pipeline=True,
            tp=max(1, eff_tp),
            samples_per_slot=args.samples_per_slot,
            n_samples=len(raw_prompts),
            batch=len(raw_prompts),
            seq_len=args.sequence_length,
            dtype=args.dtype,
            cache_dtype=args.kv_dtype,
            quantize=args.quantize,
            hbm_gb=getattr(args, "hbm_gb", None),
            origin="mdi-starter",
        )
        ok = enforce_preflight(
            report, "mdi-starter",
            allow=getattr(args, "no_preflight", False),
            emit=lambda line: log.warning("%s", line),
            exit_=False,
        )
        if not ok:
            # a refusal is this feature's EXPECTED outcome, so it must not
            # strand the secondaries inside their blocking broadcast: ship
            # an abort sentinel through the same channel so every process
            # exits cleanly instead of deadlocking the pod
            msg = refusal_text("mdi-starter") + "\n" + "\n".join(
                report.render_findings()
            )
            broadcast_run_spec({"abort": msg})
            raise SystemExit(msg)

        cfg, params, tokenizer, prompt_style = load_model(args, need_tokenizer=True)
        if tokenizer is not None:
            styled = [prompt_style.apply(p) for p in raw_prompts]
            prompt_ids = [tokenizer.encode(p).tolist() for p in styled]
            stop_seqs = tuple(prompt_style.stop_tokens(tokenizer))
        else:
            rng = np.random.default_rng(args.seed)
            prompt_ids = [
                rng.integers(1, cfg.vocab_size, 8).tolist() for _ in raw_prompts
            ]
            stop_seqs = ()
        spec = dict(
            prompt_ids=prompt_ids,
            n_tokens=args.n_tokens,
            temperature=0.0 if args.greedy else args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            stop_seqs=stop_seqs,
            seed=args.seed,
            dtype=args.dtype,
            quantize=args.quantize,
            kv_dtype=args.kv_dtype,
            seq_len=args.sequence_length,
            # shape-critical: every process must build the identical SPMD
            # ring (n_stages/eff_tp computed above, before the preflight)
            n_stages=n_stages,
            samples_per_slot=args.samples_per_slot,
            rotations_per_call=args.chunk,
            tp=max(1, eff_tp),
            overlap_chunks=args.overlap_chunks,
        )
        spec = broadcast_run_spec(spec)
    else:
        spec = broadcast_run_spec(None)
        if "abort" in spec:
            log.warning("starter aborted the job: %s", spec["abort"])
            raise SystemExit(1)
        # weights load AFTER the spec so random-init mode (--model, no
        # --ckpt) uses the starter's seed/dtype, not this node's defaults
        args.seed, args.dtype = spec["seed"], spec["dtype"]
        cfg, params, tokenizer, prompt_style = load_model(args, need_tokenizer=False)
    check_params_consistency(params)

    from mdi_llm_tpu.parallel.pipeline import PipelineEngine

    n_stages = spec["n_stages"]
    engine = PipelineEngine(
        cfg,
        params,
        n_stages=n_stages,
        max_seq_length=spec["seq_len"],
        rng_seed=spec["seed"],
        quantize=spec["quantize"],
        cache_dtype=resolve_kv_dtype(spec["kv_dtype"]),
        samples_per_slot=spec.get("samples_per_slot", 1),
        rotations_per_call=spec.get("rotations_per_call", 16),
        tp=spec.get("tp", 1),
        overlap_chunks=spec.get("overlap_chunks", False),
    )
    # live console stream of sample 0 (host-side only: the callback never
    # enters the traced ring program, so secondaries' SPMD step matches)
    stream_cb = printer = None
    if is_starter and getattr(args, "stream", False):
        if tokenizer is None:
            log.warning(
                "--stream needs a checkpoint with a tokenizer (--ckpt); "
                "running without live output"
            )
        else:
            from mdi_llm_tpu.generation import StreamPrinter

            printer = StreamPrinter(tokenizer, spec["stop_seqs"])

            def stream_cb(j: int, tok: int):
                if j == 0:
                    printer.push(tok)

    t0 = time.perf_counter()
    outs, stats = engine.generate(
        spec["prompt_ids"],
        spec["n_tokens"],
        temperature=spec["temperature"],
        top_k=spec["top_k"],
        top_p=spec["top_p"],
        stop_sequences=spec["stop_seqs"],
        stream_cb=stream_cb,
    )
    gen_time = time.perf_counter() - t0
    if printer is not None:
        # reconcile with the trimmed result (flushes the held-back tail)
        printer.finish(outs[0][len(spec["prompt_ids"][0]) :])
        print()

    if not is_starter:
        log.info("secondary %d done (%d tokens)", process_id, stats.tokens_generated)
        return outs, stats, gen_time, engine

    args.sequence_length = spec["seq_len"]
    report_run(
        args, cfg, tokenizer, spec["prompt_ids"], outs, stats, gen_time,
        nodes_cfg.n_nodes, f"{nodes_cfg.n_nodes} node(s) / {n_stages} stage(s)",
    )
    if stats.interrupted:
        raise SystemExit(130)  # conventional SIGINT exit code
    return outs, stats, gen_time, engine


def main(argv=None):
    args = build_parser().parse_args(argv)
    nodes_cfg = parse_nodes_config(args.nodes_config)
    outs, _, _, _ = run_node(args, nodes_cfg, process_id=0)
    return outs


def cli() -> int:
    """Console-script entry (exit code 0, not the samples list)."""
    main()
    return 0


if __name__ == "__main__":
    main()
