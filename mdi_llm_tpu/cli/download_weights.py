"""Weights download CLI (thin wrapper, ≡ reference `src/download_weights.py`)."""

from __future__ import annotations

import argparse
from pathlib import Path

from mdi_llm_tpu.utils.download import download_from_hub


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo_id", help="HF repo id, e.g. TinyLlama/TinyLlama-1.1B-Chat-v1.0")
    ap.add_argument("--checkpoints-dir", type=Path, default=Path("checkpoints"))
    ap.add_argument("--access-token", default=None)
    ap.add_argument("--tokenizer-only", action="store_true")
    ap.add_argument("--no-convert", action="store_true")
    args = ap.parse_args(argv)
    out = download_from_hub(
        args.repo_id,
        args.checkpoints_dir,
        access_token=args.access_token,
        tokenizer_only=args.tokenizer_only,
        convert=not args.no_convert,
    )
    print(out)


if __name__ == "__main__":
    main()
