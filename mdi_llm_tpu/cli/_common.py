"""Shared CLI plumbing: checkpoint/tokenizer loading, device/dtype selection.

≡ reference `GPTServer._select_device`/`_init_model`/`_load_tokenizer`
(`gptserver.py:601-749`) and `sample.py`'s auto-convert (`sample.py:66-76`).
"""

from __future__ import annotations

import argparse
import logging
from pathlib import Path
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.utils import checkpoint as ckpt_utils
from mdi_llm_tpu.utils.prompts import (
    PromptStyle,
    has_prompt_style,
    load_prompt_style,
    style_for_model,
)
from mdi_llm_tpu.utils.tokenizer import Tokenizer

DTYPES = {"bfloat16": jnp.bfloat16, "float16": jnp.float16, "float32": jnp.float32}
# KV-cache dtypes: the cache is written with a cast and upcast at the read
# (ops/attention.py), so it may be narrower than the compute dtype
KV_DTYPES = {**DTYPES, "float8": jnp.float8_e4m3fn}


def resolve_kv_dtype(name: str):
    """Map --kv-dtype to a jnp dtype; "auto" → None (follow the weights).
    "int8" is NOT a dense-cache dtype — it selects the quantized paged
    pool via ServingConfig.kv_dtype, and serving entry points route it
    there before calling this."""
    if name == "int8":
        raise ValueError(
            "--kv-dtype int8 quantizes the paged serving pool "
            "(ServingConfig.kv_dtype), not the dense KV cache"
        )
    return None if name == "auto" else KV_DTYPES[name]


def make_tp_mesh(tp_devices: int, quantize: str):
    """Shared --tp-devices handling for the Generator entry points (sample,
    chat): validate, then build a 1-D tp mesh over the first N devices.
    Composes with --quantize: quantized storage layouts shard under the
    adapted Megatron specs (parallel/sharding.adapt_specs_to_tree)."""
    del quantize  # accepted everywhere since r5; kept for call compatibility
    if tp_devices < 1:
        raise SystemExit("--tp-devices must be a positive device count")
    import jax

    from mdi_llm_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < tp_devices:
        raise SystemExit(
            f"--tp-devices {tp_devices} exceeds the {len(jax.devices())} "
            "available devices"
        )
    return make_mesh({"tp": tp_devices}, jax.devices()[:tp_devices])


def make_ep_mesh(ep_devices: int, cfg: Config):
    """Shared --ep-devices handling: validate (MoE config, >=2 devices,
    enough devices) and build a 1-D ep mesh over the first N devices."""
    if ep_devices < 2:
        raise SystemExit(
            "--ep-devices needs at least 2 devices (expert dispatch over an "
            "ep mesh; a single device is just the dense MoE path)"
        )
    if cfg.mlp_class_name != "LLaMAMoE":
        raise SystemExit(
            f"--ep-devices needs a MoE config; {cfg.name} has "
            f"mlp_class_name={cfg.mlp_class_name}"
        )
    import jax

    from mdi_llm_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < ep_devices:
        raise SystemExit(
            f"--ep-devices {ep_devices} exceeds the {len(jax.devices())} "
            "available devices"
        )
    return make_mesh({"ep": ep_devices}, jax.devices()[:ep_devices])


def add_common_args(ap: argparse.ArgumentParser, serving_kv: bool = False) -> None:
    """`serving_kv=True` (mdi-serve) additionally accepts --kv-dtype int8:
    the paged pool stores int8 blocks with per-block-per-group scales
    (ServingConfig.kv_dtype) — a serving-engine feature, so the dense-cache
    entry points keep refusing it at the parser."""
    ap.add_argument("--ckpt", type=Path, default=None, help="checkpoint directory")
    ap.add_argument(
        "--model", default=None, help="registry model name (random init if no --ckpt)"
    )
    ap.add_argument("--dtype", choices=list(DTYPES), default="bfloat16")
    ap.add_argument("--seed", type=int, default=10137)
    ap.add_argument(
        "--sequence-length", type=int, default=None, help="truncate max context"
    )
    ap.add_argument("--device", default=None, help="jax platform override (tpu/cpu)")
    ap.add_argument(
        "--quantize",
        choices=("none", "int8", "w8a8", "int4"),
        default="none",
        help="int8: weight-only (halves weight HBM traffic; fastest decode "
        "measured); w8a8: also dynamically quantizes activations for full "
        "int8 MXU matmuls (wins on compute-bound prefill/large tiles, the "
        "per-token requantize makes it SLOWER than int8 for decode); int4: "
        "group-wise weight-only nibble packing (quarters weight footprint, "
        "coarser numerics)",
    )
    ap.add_argument(
        "--kv-dtype",
        choices=("auto", *KV_DTYPES) + (("int8",) if serving_kv else ()),
        default="auto",
        help="KV-cache storage dtype (float8 halves cache HBM traffic; "
        "reads upcast to the compute dtype)"
        + (
            "; int8 quantizes the paged pool — int8 blocks with "
            "per-block-per-head scales dequantized inside the attention "
            "kernels, ~2x resident sequences per HBM byte "
            "(docs/perf.md 'Quantized paged KV')"
            if serving_kv else ""
        ),
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--debug", action="store_true")


def add_run_args(ap: argparse.ArgumentParser) -> None:
    """Generation-run flags shared by cli/sample.py, cli/starter.py and
    cli/secondary.py (≡ reference starter.py/sample.py flag set)."""
    from mdi_llm_tpu.config import TEMPERATURE, TOP_K

    ap.add_argument("--n-samples", type=int, default=1)
    ap.add_argument("--n-tokens", type=int, default=300, help="tokens per sample")
    ap.add_argument("--prompt", default="Once upon a time,", help='text or "FILE:<path>"')
    ap.add_argument("--temperature", type=float, default=TEMPERATURE)
    ap.add_argument("--top-k", type=int, default=TOP_K)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--greedy", action="store_true", help="temperature 0 (parity mode)")
    ap.add_argument("--plots", action="store_true")
    ap.add_argument("--time-run", type=Path, default=None, help="append run stats CSV")
    ap.add_argument("--logs-dir", type=Path, default=Path("logs"))


def report_run(args, cfg, tokenizer, prompt_ids, outs, stats, gen_time, n_nodes, label):
    """Print the samples + throughput line and write the tokens/time CSV,
    plot, and run-stats CSV with the reference's file naming
    (≡ starter.py:70-105 / sample.py:203-245).  Shared by cli/sample.py and
    cli/starter.py."""
    import sys

    import numpy as np

    from mdi_llm_tpu.utils import plots

    for i, (ids, plen) in enumerate(zip(outs, (len(p) for p in prompt_ids))):
        print(f"--- sample {i} ({len(ids) - plen} new tokens) " + "-" * 30)
        if tokenizer is not None:
            print(tokenizer.decode(np.asarray(ids)))  # mdi-lint: disable=host-sync -- end-of-run print, not the decode loop
        else:
            print(ids)
    print(
        f"[{label}] {stats.tokens_generated} tokens in {gen_time:.2f}s — "
        f"{stats.tokens_per_s:.2f} tok/s decode (prefill {stats.prefill_s:.2f}s)",
        file=sys.stderr,
    )
    if stats.interrupted:
        print("WARNING: generation interrupted — output is partial", file=sys.stderr)
    if args.plots or args.time_run:
        csv_path = plots.tok_time_csv_path(
            args.logs_dir, n_nodes, cfg.name, args.n_samples
        )
        plots.write_tok_time_csv(csv_path, stats.tok_time)
        if args.plots:
            plots.plot_tokens_per_time(
                stats.tok_time,
                csv_path.with_suffix(".png"),
                label=f"{cfg.name} {n_nodes} node(s)",
            )
        if args.time_run:
            plots.append_run_stats(
                args.time_run,
                args.n_samples,
                cfg.n_layer,
                args.sequence_length or cfg.block_size,
                gen_time,
            )


def setup_logging(args, role: str = None) -> logging.Logger:
    """Console logging; with --debug and a node role, also a per-role file
    under logs/ (≡ reference `logs/logs_{starter,finisher}.log`,
    starter.py:35-44 / secondary.py:29-38)."""
    level = (
        logging.DEBUG if args.debug else logging.INFO if args.verbose else logging.WARNING
    )
    logging.basicConfig(level=level, format="%(asctime)s %(name)s %(message)s")
    log = logging.getLogger("mdi_llm_tpu")
    if args.debug and role:
        logs_dir = Path(getattr(args, "logs_dir", None) or "logs")
        logs_dir.mkdir(parents=True, exist_ok=True)
        import os

        path = logs_dir / f"logs_{role}.log"
        # idempotent: repeat calls (retries, tests) must not stack handlers.
        # FileHandler stores os.path.abspath (symlinks unresolved) — compare
        # apples to apples.
        for h in list(log.handlers):
            if isinstance(h, logging.FileHandler) and h.baseFilename == os.path.abspath(
                path
            ):
                h.close()
                log.removeHandler(h)
        fh = logging.FileHandler(path)
        fh.setLevel(logging.DEBUG)
        fh.setFormatter(logging.Formatter("%(asctime)s %(name)s %(message)s"))
        log.addHandler(fh)
    return log


def select_device(args) -> None:
    """Device priority CLI > default (≡ gptserver.py:601-617)."""
    if args.device:
        jax.config.update("jax_platforms", args.device)


def resolve_config(args) -> Config:
    """The architecture alone, WITHOUT loading weights — the mdi-audit
    preflight runs on this so a refused plan never pays the checkpoint
    load.  Mirrors `load_model`'s --ckpt/--model resolution order."""
    if args.ckpt:
        return Config.from_checkpoint(Path(args.ckpt))
    if args.model:
        return Config.from_name(args.model)
    raise SystemExit("one of --ckpt or --model is required")


def load_model(
    args, need_tokenizer: bool = True
) -> Tuple[Config, dict, Optional[Tokenizer], Optional[PromptStyle]]:
    """Resolve (config, params, tokenizer, prompt_style) from --ckpt or
    --model.  A --ckpt dir holding raw HF weights is converted in place
    (≡ sample.py:66-76)."""
    dtype = DTYPES[args.dtype]
    tokenizer = prompt_style = None
    if args.ckpt:
        ckpt_dir = Path(args.ckpt)
        if not ckpt_utils.has_checkpoint(ckpt_dir):
            ckpt_utils.convert_hf_checkpoint(ckpt_dir, model_name=args.model, dtype=dtype)
        cfg, params = ckpt_utils.load_checkpoint(ckpt_dir, dtype=dtype)
        if need_tokenizer:
            tokenizer = Tokenizer(ckpt_dir)
            prompt_style = (
                load_prompt_style(ckpt_dir)
                if has_prompt_style(ckpt_dir)
                else style_for_model(cfg.name)
            )
    elif args.model:
        cfg = Config.from_name(args.model)
        params = transformer.init_params(
            cfg, jax.random.PRNGKey(args.seed), dtype=dtype
        )
        prompt_style = style_for_model(cfg.name)
    else:
        raise SystemExit("one of --ckpt or --model is required")
    return cfg, params, tokenizer, prompt_style
