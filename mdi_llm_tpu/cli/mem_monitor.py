"""Memory monitor: sample host RSS + TPU HBM while a command runs.

≡ reference `src/mem_monitor.py` (psutil RSS + GPUtil VRAM + jtop): spawns
the target command, samples the process tree's RSS and (when a TPU backend
is live in-process) `device.memory_stats()`, writes CSV + optional plot.

`sample_rss` doubles as the in-process sampler behind
`mdi-serve --sample-rss`: the serving observer calls it (rate-limited, at
host-sync boundaries only) to expose a `host_rss_bytes` gauge
(docs/observability.md).

Example:
    python -m mdi_llm_tpu.cli.mem_monitor -o mem.csv -- \
        python -m mdi_llm_tpu.cli.sample --model NanoLlama --n-tokens 50
"""

from __future__ import annotations

import argparse
import csv
import subprocess
import sys
import time
from pathlib import Path


def sample_rss(pid: int = None) -> int:
    """Resident-set bytes of a process TREE (pid + recursive children);
    defaults to the calling process so in-process samplers — the serving
    observer's `--sample-rss` host-memory gauge (`obs.ServingObserver`)
    — share one implementation with the standalone monitor below."""
    import os

    import psutil

    try:
        p = psutil.Process(os.getpid() if pid is None else pid)
        total = p.memory_info().rss
        for child in p.children(recursive=True):
            try:
                total += child.memory_info().rss
            except psutil.NoSuchProcess:
                pass
        return total
    except psutil.NoSuchProcess:
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--out", type=Path, default=Path("logs/mem_monitor.csv"))
    ap.add_argument("--interval", type=float, default=0.5)
    ap.add_argument("--plot", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER, help="command to run (after --)")
    args = ap.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        raise SystemExit("no command given; usage: mem_monitor -o out.csv -- <cmd> ...")

    args.out.parent.mkdir(parents=True, exist_ok=True)
    proc = subprocess.Popen(cmd)
    rows = []
    t0 = time.perf_counter()
    try:
        while proc.poll() is None:
            rows.append((time.perf_counter() - t0, sample_rss(proc.pid)))
            time.sleep(args.interval)
    except KeyboardInterrupt:
        proc.terminate()
    with args.out.open("w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["time_s", "rss_bytes"])
        w.writerows(rows)
    print(f"wrote {len(rows)} samples → {args.out}", file=sys.stderr)

    if args.plot and rows:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        ax.plot([r[0] for r in rows], [r[1] / 2**20 for r in rows])
        ax.set_xlabel("time (s)")
        ax.set_ylabel("RSS (MiB)")
        fig.savefig(args.out.with_suffix(".png"), dpi=120)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
