"""Dataset preparation CLI: raw text → tokenized train.bin/val.bin.

≡ reference `src/prepare_data.py` (Shakespeare et al.): tokenize with the
checkpoint's tokenizer, 90/10 split, uint16 bins readable by np.memmap.
"""

from __future__ import annotations

import argparse
from pathlib import Path

from mdi_llm_tpu.utils.data_loader import prepare_bin
from mdi_llm_tpu.utils.tokenizer import Tokenizer


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", type=Path, required=True, help="input .txt file")
    ap.add_argument("--ckpt", type=Path, required=True, help="tokenizer source dir")
    ap.add_argument("--out", type=Path, default=None, help="output dir (default: alongside input)")
    ap.add_argument("--frac-train", type=float, default=0.9)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    out = args.out or args.dataset.parent
    tok = Tokenizer(args.ckpt)
    train_p, val_p = prepare_bin(args.dataset, out, tok, args.frac_train)
    print(f"wrote {train_p} and {val_p}")


if __name__ == "__main__":
    main()
