"""OpenWebText (or any HF dataset) preparation CLI: streaming multiprocess
tokenization into memmapped token bins.

≡ reference `src/prepare_owt.py` (HF `datasets` load → multiproc `.map`
tokenize → concatenate into uint16 `train.bin`/`val.bin` memmaps).  Same
output format as cli/prepare_data.py, so the trainer and the native C++
loader read either.

Works with any dataset id / local dataset dir exposing a text column:
    python -m mdi_llm_tpu.cli.prepare_owt --ckpt <tokenizer-dir> --out data/owt
    python -m mdi_llm_tpu.cli.prepare_owt --dataset wikitext \
        --dataset-config wikitext-2-raw-v1 --ckpt <dir> --out data/wt2
"""

from __future__ import annotations

import argparse
from pathlib import Path

import numpy as np


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", default="openwebtext", help="HF dataset id or local dir")
    ap.add_argument("--dataset-config", default=None)
    ap.add_argument("--ckpt", type=Path, required=True, help="tokenizer source dir")
    ap.add_argument("--out", type=Path, required=True, help="output directory")
    ap.add_argument("--text-column", default="text")
    ap.add_argument("--num-proc", type=int, default=4)
    ap.add_argument("--val-frac", type=float, default=0.0005)
    ap.add_argument("--seed", type=int, default=2357)
    return ap


def _tokenize_split(ds, tok, text_column, num_proc, eos_id):
    def enc(batch):
        outs = [np.asarray(tok.encode(t), np.uint32) for t in batch[text_column]]
        if eos_id is not None:  # document separator (≡ append eot per doc)
            outs = [np.concatenate([o, [eos_id]]) for o in outs]
        return {"ids": [o.tolist() for o in outs], "len": [len(o) for o in outs]}

    return ds.map(
        enc,
        batched=True,
        num_proc=num_proc,
        remove_columns=ds.column_names,
        desc="tokenizing",
    )


def _write_bin(ds, path: Path, dtype) -> int:
    """Concatenate all docs into one memmapped bin (constant RAM)."""
    total = int(np.sum(ds["len"], dtype=np.int64))
    arr = np.memmap(path, dtype=dtype, mode="w+", shape=(total,))
    n_shards = min(1024, max(1, len(ds)))
    idx = 0
    for shard in range(n_shards):
        batch = ds.shard(num_shards=n_shards, index=shard, contiguous=True)
        if len(batch) == 0:
            continue
        ids = np.concatenate([np.asarray(d, dtype) for d in batch["ids"]])
        arr[idx : idx + len(ids)] = ids
        idx += len(ids)
    arr.flush()
    return total


def main(argv=None):
    args = build_parser().parse_args(argv)
    import datasets  # HF datasets (baked in); heavy import kept out of module scope

    from mdi_llm_tpu.utils.tokenizer import Tokenizer

    tok = Tokenizer(args.ckpt)
    eos_id = getattr(tok, "eos_id", None)
    vocab = getattr(tok, "vocab_size", 2**17) or 2**17
    dtype = np.uint16 if vocab < 2**16 else np.uint32

    local = Path(args.dataset)
    if local.exists():
        ds = datasets.load_from_disk(str(local))
        if isinstance(ds, datasets.DatasetDict):
            ds = datasets.concatenate_datasets(list(ds.values()))
    else:
        ds = datasets.load_dataset(
            args.dataset, args.dataset_config, split="train", num_proc=args.num_proc
        )

    split = ds.train_test_split(test_size=args.val_frac, seed=args.seed, shuffle=True)
    args.out.mkdir(parents=True, exist_ok=True)
    for name, part in (("train", split["train"]), ("val", split["test"])):
        tokked = _tokenize_split(part, tok, args.text_column, args.num_proc, eos_id)
        n = _write_bin(tokked, args.out / f"{name}.bin", dtype)
        print(f"{name}.bin: {n} tokens ({dtype.__name__})")


if __name__ == "__main__":
    main()
