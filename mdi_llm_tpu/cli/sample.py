"""Text generation CLI — single-device or pipeline-parallel.

Merges the reference's `src/sample.py` (single device) and `src/starter.py`
(distributed run with plots/timing) into one entry point: pass
`--pipeline-stages N` to lay the model over an N-stage mesh ring (the
reference's `--nodes-config` topology file becomes a mesh axis; multi-host
meshes initialize via `--coordinator`/`--process-id`/`--num-processes`,
replacing the HTTP init handshake, model_dist.py:402-497).

Examples:
    python -m mdi_llm_tpu.cli.sample --ckpt checkpoints/TinyLlama... \
        --n-samples 3 --n-tokens 200 --prompt "FILE:prompts.txt" --plots
    python -m mdi_llm_tpu.cli.sample --model NanoLlama --pipeline-stages 4
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path

import jax
import numpy as np

from mdi_llm_tpu.cli._common import (
    add_common_args,
    add_run_args,
    load_model,
    report_run,
    resolve_kv_dtype,
    select_device,
    setup_logging,
)
from mdi_llm_tpu.utils.prompts import get_user_prompt


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    add_run_args(ap)
    ap.add_argument("--chunk", type=int, default=16, help="decode steps per dispatch")
    ap.add_argument(
        "--scan-unroll", type=int, default=1,
        help="layer-scan unroll factor for decode steps "
        "(transformer.run_blocks(unroll=)): divides the per-layer "
        "while-loop fixed cost that dominates small models "
        "(docs/perf.md hypothesis 1; single-device engine only)",
    )
    ap.add_argument(
        "--speculative", type=int, default=0, metavar="K",
        help="greedy speculative decoding with K-token n-gram drafts "
        "(single sample, temperature 0; exact)",
    )
    ap.add_argument("--pipeline-stages", type=int, default=0)
    ap.add_argument(
        "--samples-per-slot",
        type=int,
        default=1,
        help="pipeline mode: samples batched per ring slot (M)",
    )
    ap.add_argument(
        "--sp-devices",
        type=int,
        default=0,
        help="sequence-parallel inference over N devices: ring-attention "
        "prefill + sequence-sharded KV cache (context scales with N)",
    )
    ap.add_argument(
        "--sp-flash",
        action="store_true",
        help="run the sp prefill ring through the Pallas flash kernel "
        "(TPU opt-in; engages when the local chunk is >= 2048)",
    )
    ap.add_argument(
        "--ep-devices",
        type=int,
        default=0,
        help="expert-parallel inference over N>=2 devices (MoE configs "
        "only): GShard token dispatch, experts sharded over the ep mesh "
        "axis; composes with --quantize int8/w8a8/int4",
    )
    ap.add_argument(
        "--moe-capacity-factor",
        type=float,
        default=None,
        help="expert-parallel dispatch capacity factor: bounds the per-"
        "device dispatch buffers at cf*k/E of the no-drop worst case "
        "(Switch-style drops past capacity); default exact/no-drop — "
        "long-prompt MoE prefill may want ~1.25 to cap activation memory",
    )
    ap.add_argument(
        "--tp-devices",
        type=int,
        default=0,
        help="tensor-parallel inference over N devices (GSPMD Megatron "
        "sharding; weights and KV heads split across chips); combines with "
        "--pipeline-stages S into an S x N pipe-by-tp mesh",
    )
    ap.add_argument(
        "--overlap-chunks",
        action="store_true",
        help="pipeline mode: dispatch the next decode chunk before fetching "
        "the previous one (hides transfer + host work under compute on "
        "directly-attached TPUs; known to stall on remote-tunnel backends)",
    )
    # multi-host mesh bootstrap (≡ HTTP /init, model_dist.py:402-497)
    ap.add_argument("--coordinator", default=None, help="host:port of process 0")
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument(
        "--profile-dir", type=Path, default=None,
        help="capture a jax.profiler device trace here (TensorBoard/Perfetto)",
    )
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    log = setup_logging(args)
    select_device(args)
    if args.coordinator:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    cfg, params, tokenizer, prompt_style = load_model(args)
    log.info("model %s: %d layers, %d params", cfg.name, cfg.n_layer, -1)

    raw_prompts = get_user_prompt(args.prompt, args.n_samples)
    if tokenizer is not None:
        styled = [prompt_style.apply(p) for p in raw_prompts]
        prompt_ids = [tokenizer.encode(p).tolist() for p in styled]
        stop_seqs = prompt_style.stop_tokens(tokenizer)
    else:
        rng = np.random.default_rng(args.seed)
        prompt_ids = [rng.integers(1, cfg.vocab_size, 8).tolist() for _ in raw_prompts]
        stop_seqs = ()

    temperature = 0.0 if args.greedy else args.temperature
    if args.speculative:
        if args.pipeline_stages:
            raise SystemExit(
                "--speculative applies to single-device decode only "
                "(drop --pipeline-stages)"
            )
        if temperature != 0.0 or args.n_samples != 1:
            raise SystemExit(
                "--speculative requires --greedy (or --temperature 0) and "
                "--n-samples 1"
            )
    if args.tp_devices and args.sp_devices:
        raise SystemExit("--tp-devices is exclusive with --sp-devices")
    if args.tp_devices < 0:
        raise SystemExit("--tp-devices must be a positive device count")
    if args.ep_devices and (args.tp_devices or args.sp_devices or args.pipeline_stages):
        raise SystemExit(
            "--ep-devices is a standalone expert-parallel mesh; drop the "
            "other parallelism flags"
        )
    seq_len = args.sequence_length

    from mdi_llm_tpu.utils.profiling import profile

    host_prof = (
        args.logs_dir / "sample_profile.prof" if args.debug else None
    )  # ≡ reference sample.py:34-37
    t_load = time.perf_counter()
    with profile(logdir=args.profile_dir, host_profile_path=host_prof):
        if args.sp_devices:
            if args.pipeline_stages:
                raise SystemExit("--sp-devices and --pipeline-stages are exclusive")
            if args.speculative:
                raise SystemExit("--speculative applies to single-device decode only")
            from mdi_llm_tpu.parallel.sp_inference import SPGenerator

            engine = SPGenerator(
                cfg, params, n_devices=args.sp_devices, max_seq_length=seq_len,
                rng_seed=args.seed, cache_dtype=resolve_kv_dtype(args.kv_dtype),
                use_flash=args.sp_flash, quantize=args.quantize,
            )
            n_nodes = args.sp_devices
            outs, stats = engine.generate(
                prompt_ids, args.n_tokens, temperature=temperature,
                top_k=args.top_k, top_p=args.top_p, stop_sequences=stop_seqs,
            )
        elif args.pipeline_stages:
            from mdi_llm_tpu.parallel.pipeline import PipelineEngine

            engine = PipelineEngine(
                cfg, params, n_stages=args.pipeline_stages, max_seq_length=seq_len,
                rng_seed=args.seed, quantize=args.quantize,
                cache_dtype=resolve_kv_dtype(args.kv_dtype),
                samples_per_slot=args.samples_per_slot,
                rotations_per_call=args.chunk,
                tp=max(1, args.tp_devices),
                overlap_chunks=args.overlap_chunks,
            )
            n_nodes = args.pipeline_stages * max(1, args.tp_devices)
            outs, stats = engine.generate(
                prompt_ids, args.n_tokens, temperature=temperature,
                top_k=args.top_k, top_p=args.top_p, stop_sequences=stop_seqs,
            )
        else:
            from mdi_llm_tpu.generation import Generator

            mesh = None
            n_nodes = 1
            if args.tp_devices:
                from mdi_llm_tpu.cli._common import make_tp_mesh

                mesh = make_tp_mesh(args.tp_devices, args.quantize)
                n_nodes = args.tp_devices
            elif args.ep_devices:
                from mdi_llm_tpu.cli._common import make_ep_mesh

                mesh = make_ep_mesh(args.ep_devices, cfg)
                n_nodes = args.ep_devices
            engine = Generator(
                cfg, params, max_seq_length=seq_len, rng_seed=args.seed,
                quantize=args.quantize, cache_dtype=resolve_kv_dtype(args.kv_dtype),
                mesh=mesh, moe_capacity_factor=args.moe_capacity_factor,
                scan_unroll=args.scan_unroll,
            )
            outs, stats = engine.generate(
                prompt_ids, args.n_tokens, temperature=temperature,
                top_k=args.top_k, top_p=args.top_p, stop_sequences=stop_seqs,
                chunk_size=args.chunk,
                speculative=args.speculative or None,
            )
    gen_time = time.perf_counter() - t_load

    report_run(
        args, cfg, tokenizer, prompt_ids, outs, stats, gen_time,
        n_nodes, f"{n_nodes} node(s)",
    )
    if stats.interrupted:
        raise SystemExit(130)  # conventional SIGINT exit code
    return outs


def cli() -> int:
    """Console-script entry (exit code 0, not the samples list)."""
    main()
    return 0


if __name__ == "__main__":
    main()
