"""Model preparation CLI: download → convert → partition into stages.

≡ reference `src/prepare_model.py`: fetch an HF checkpoint (or use a local
directory), convert to the framework layout, and pre-carve per-stage
checkpoints (`chunks/<n>stages/stage_<i>/`) + `stage_map.json` so multi-host
pipeline deployments load only their own stage (≡ chunk files
`chunks/<n>nodes/model_*.pth`, utils.py:388-438).

Example:
    python -m mdi_llm_tpu.cli.prepare_model TinyLlama/TinyLlama-1.1B-Chat-v1.0 --n-stages 3
"""

from __future__ import annotations

import argparse
from pathlib import Path

import jax.numpy as jnp

from mdi_llm_tpu.parallel.partition import save_stage_manifest, split_params
from mdi_llm_tpu.utils.checkpoint import (
    convert_hf_checkpoint,
    has_checkpoint,
    load_checkpoint,
    save_checkpoint,
)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("model", help="HF repo id (org/name) or local checkpoint dir")
    ap.add_argument("--checkpoints-dir", type=Path, default=Path("checkpoints"))
    ap.add_argument("--n-stages", "--n-nodes", type=int, default=0, dest="n_stages")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--access-token", default=None)
    ap.add_argument(
        "--quantize",
        choices=("none", "int8", "int4"),
        default="none",
        help="additionally write a pre-quantized checkpoint "
        "(<ckpt>-<mode>/) that engines load with no further flags — "
        "quantize once at prepare time instead of per process at load",
    )
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[args.dtype]

    local = Path(args.model)
    if local.exists():
        ckpt_dir = local
        if not has_checkpoint(ckpt_dir):
            convert_hf_checkpoint(ckpt_dir, dtype=dtype)
    else:
        from mdi_llm_tpu.utils.download import download_from_hub

        ckpt_dir = download_from_hub(
            args.model, args.checkpoints_dir, access_token=args.access_token, dtype=dtype
        )

    cfg = params = None
    if args.n_stages > 1 or args.quantize != "none":
        cfg, params = load_checkpoint(ckpt_dir)

    def write_stages(base_dir, cfg_, params_, quantize="none"):
        stages = split_params(cfg_, params_, args.n_stages)
        chunk_dir = base_dir / "chunks" / f"{args.n_stages}stages"
        for i, st in enumerate(stages):
            save_checkpoint(st, cfg_, chunk_dir / f"stage_{i}")
        save_stage_manifest(chunk_dir, cfg_, args.n_stages, quantize=quantize)
        print(f"wrote {args.n_stages} stage checkpoints → {chunk_dir}")

    if args.n_stages > 1:
        write_stages(ckpt_dir, cfg, params)

    if args.quantize != "none":
        import shutil

        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, quantize_params
        from mdi_llm_tpu.utils.checkpoint import TOKENIZER_FILES

        qp = quantize_params(params, mode=FLAG_TO_MODE[args.quantize])
        q_dir = ckpt_dir.parent / f"{ckpt_dir.name}-{args.quantize}"
        save_checkpoint(qp, cfg, q_dir)
        # tokenizer files travel with the quantized copy so it is a
        # self-contained --ckpt target
        for name in TOKENIZER_FILES:
            src = ckpt_dir / name
            if src.exists():
                shutil.copy(src, q_dir / name)
        if args.n_stages > 1:
            # pipeline deployments get pre-quantized stage chunks too
            write_stages(q_dir, cfg, qp, quantize=args.quantize)
        print(f"wrote {args.quantize}-quantized checkpoint → {q_dir}")
    print(f"checkpoint ready: {ckpt_dir}")
    return ckpt_dir


if __name__ == "__main__":
    main()
