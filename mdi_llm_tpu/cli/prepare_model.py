"""Model preparation CLI: download → convert → partition into stages.

≡ reference `src/prepare_model.py`: fetch an HF checkpoint (or use a local
directory), convert to the framework layout, and pre-carve per-stage
checkpoints (`chunks/<n>stages/stage_<i>/`) + `stage_map.json` so multi-host
pipeline deployments load only their own stage (≡ chunk files
`chunks/<n>nodes/model_*.pth`, utils.py:388-438).

Example:
    python -m mdi_llm_tpu.cli.prepare_model TinyLlama/TinyLlama-1.1B-Chat-v1.0 --n-stages 3
"""

from __future__ import annotations

import argparse
from pathlib import Path

import jax.numpy as jnp

from mdi_llm_tpu.parallel.partition import save_stage_manifest, split_params
from mdi_llm_tpu.utils.checkpoint import (
    convert_hf_checkpoint,
    has_checkpoint,
    load_checkpoint,
    save_checkpoint,
)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("model", help="HF repo id (org/name) or local checkpoint dir")
    ap.add_argument("--checkpoints-dir", type=Path, default=Path("checkpoints"))
    ap.add_argument("--n-stages", "--n-nodes", type=int, default=0, dest="n_stages")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--access-token", default=None)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[args.dtype]

    local = Path(args.model)
    if local.exists():
        ckpt_dir = local
        if not has_checkpoint(ckpt_dir):
            convert_hf_checkpoint(ckpt_dir, dtype=dtype)
    else:
        from mdi_llm_tpu.utils.download import download_from_hub

        ckpt_dir = download_from_hub(
            args.model, args.checkpoints_dir, access_token=args.access_token, dtype=dtype
        )

    if args.n_stages > 1:
        cfg, params = load_checkpoint(ckpt_dir)
        stages = split_params(cfg, params, args.n_stages)
        chunk_dir = ckpt_dir / "chunks" / f"{args.n_stages}stages"
        for i, st in enumerate(stages):
            save_checkpoint(st, cfg, chunk_dir / f"stage_{i}")
        save_stage_manifest(chunk_dir, cfg, args.n_stages)
        print(f"wrote {args.n_stages} stage checkpoints → {chunk_dir}")
    print(f"checkpoint ready: {ckpt_dir}")
    return ckpt_dir


if __name__ == "__main__":
    main()
