"""Secondary-node entry point for multi-process pipeline generation.

Reference-parity CLI (`/root/reference/src/secondary.py`, which takes
`--nodes-config CONFIG IDX` and blocks in `GPTDistributed.start()` waiting
for the starter's HTTP `/init`).  TPU-native semantics: the secondary joins
the `jax.distributed` job as process IDX+1, receives the run spec over the
device fabric (parallel/nodes.py:broadcast_run_spec — the analog of the
pickled `/init`+inference messages), and executes the same SPMD ring program
as the starter; its chips host the middle/last pipeline stages.

Weights: loaded from (shared) storage via --ckpt / --model rather than
shipped through a Python control plane (see parallel/nodes.py docstring).

Example:
    python -m mdi_llm_tpu.cli.secondary --ckpt <dir> --nodes-config cfg.json 0
"""

from __future__ import annotations

import argparse
from pathlib import Path

from mdi_llm_tpu.cli._common import add_common_args
from mdi_llm_tpu.cli.starter import add_run_args, run_node
from mdi_llm_tpu.parallel.nodes import parse_nodes_config


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    add_run_args(ap)
    # ≡ reference secondary.py:76-84: one flag, two values (config path, index)
    ap.add_argument(
        "--nodes-config",
        nargs=2,
        metavar=("CONFIG", "IDX"),
        required=True,
        help="topology JSON and this node's secondary index (0-based)",
    )
    # accepted for launch-script symmetry with cli/starter.py; the effective
    # values always come from the starter's broadcast run spec
    ap.add_argument("--pipeline-stages", type=int, default=None)
    ap.add_argument("--samples-per-slot", type=int, default=1)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--tp-devices", type=int, default=None)
    ap.add_argument("--overlap-chunks", action="store_true")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    config_path, idx = Path(args.nodes_config[0]), int(args.nodes_config[1])
    nodes_cfg = parse_nodes_config(config_path)
    if not 0 <= idx < len(nodes_cfg.secondary):
        raise SystemExit(
            f"secondary index {idx} out of range (config lists "
            f"{len(nodes_cfg.secondary)} secondaries)"
        )
    args.nodes_config = config_path
    run_node(args, nodes_cfg, process_id=idx + 1)


if __name__ == "__main__":
    main()
