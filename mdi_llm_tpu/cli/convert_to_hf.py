"""Inverse checkpoint conversion: framework pytree → HF-layout state dict.

≡ reference `src/sub/utils/convert_lit_checkpoint.py` (lit→HF weight maps,
QKV un-interleaving).  Writes `pytorch_model.bin` (torch.save) or
`model.safetensors` next to the source checkpoint so the weights round-trip
back into `transformers`.

Example:
    python -m mdi_llm_tpu.cli.convert_to_hf --ckpt checkpoints/custom/NanoLlama --out export/
"""

from __future__ import annotations

import argparse
from pathlib import Path


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", type=Path, required=True)
    ap.add_argument("--out", type=Path, default=None, help="default: <ckpt>/hf_export")
    ap.add_argument(
        "--format", choices=("safetensors", "bin"), default="safetensors"
    )
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    from mdi_llm_tpu.utils.checkpoint import convert_to_hf_state_dict, load_checkpoint

    cfg, params = load_checkpoint(args.ckpt)
    sd = convert_to_hf_state_dict(cfg, params)
    out = args.out or (args.ckpt / "hf_export")
    out.mkdir(parents=True, exist_ok=True)

    if args.format == "safetensors":
        try:
            from safetensors.numpy import save_file
        except ImportError:  # fall back to torch.save
            args.format = "bin"
        else:
            save_file(dict(sd), str(out / "model.safetensors"))
    if args.format == "bin":
        import torch

        torch.save(
            {k: torch.from_numpy(v.copy()) for k, v in sd.items()},
            out / "pytorch_model.bin",
        )
    print(f"wrote {len(sd)} tensors to {out} ({args.format})")


if __name__ == "__main__":
    main()
