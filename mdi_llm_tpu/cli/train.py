"""Training CLI.

≡ reference `src/train.py` argparse surface: init from scratch / resume /
converted-HF weights, token-bin dataset dir, gradient accumulation, periodic
eval + checkpoint with patience.  The DDP/torchrun path becomes `--mesh`
("dp=8" or "dp=4,tp=2") on one host, plus `--coordinator/--process-id/
--num-processes` for multi-host `jax.distributed`.

Example:
    python -m mdi_llm_tpu.cli.train --ckpt checkpoints/custom/NanoLlama \
        --dataset data/shakespeare --batch-size 8 --grad-acc-steps 4 \
        --max-iters 5000 --mesh dp=4
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

import jax
import numpy as np

from mdi_llm_tpu.cli._common import add_common_args, select_device, setup_logging
from mdi_llm_tpu.config import Config
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.training import Trainer, TrainingConfig
from mdi_llm_tpu.utils import data_loader
from mdi_llm_tpu.utils.checkpoint import has_checkpoint, load_checkpoint


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    ap.add_argument("--dataset", type=Path, required=True, help="dir with train.bin/val.bin")
    ap.add_argument("--init", choices=["scratch", "resume", "hf"], default="scratch")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--grad-acc-steps", type=int, default=1)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--max-iters", type=int, default=600000)
    ap.add_argument("--learning-rate", type=float, default=3e-4)
    ap.add_argument("--warmup-iters", type=int, default=2000)
    ap.add_argument("--lr-decay-iters", type=int, default=600000)
    ap.add_argument("--min-lr", type=float, default=6e-5)
    ap.add_argument("--weight-decay", type=float, default=1e-1)
    ap.add_argument("--grad-clip", type=float, default=1.0)
    ap.add_argument("--ckpt-interval", type=int, default=1000)
    ap.add_argument("--eval-iters", type=int, default=20)
    ap.add_argument("--log-interval", type=int, default=10)
    ap.add_argument("--patience", type=int, default=5)
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument(
        "--use-flash",
        choices=("auto", "on", "off"),
        default="auto",
        help="attention via the Pallas flash kernel (fwd + FA-2 backward); "
        "auto = TPU backend only",
    )
    ap.add_argument(
        "--mesh",
        default=None,
        help='e.g. "dp=8", "dp=4,tp=2", "dp=2,sp=4" (ring attention), '
        '"dp=1,pp=4" (GPipe pipeline stages), or "dp=2,ep=4" (MoE '
        'configs: token-dispatch expert parallelism)',
    )
    ap.add_argument(
        "--moe-aux-weight",
        type=float,
        default=0.01,
        help="MoE configs: weight on the load-balancing auxiliary loss "
        "(0 disables; ignored with a warning on sp/pp meshes, where MoE "
        "trains dense pure-CE)",
    )
    ap.add_argument(
        "--moe-capacity-factor",
        type=float,
        default=None,
        help="ep-mesh MoE training: dispatch capacity factor (bounds the "
        "per-device buffers, Switch-style drops past capacity); default "
        "exact/no-drop — gradients then match the dense formulation",
    )
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    return ap


def parse_mesh(spec):
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    return make_mesh(axes)


def main(argv=None):
    args = build_parser().parse_args(argv)
    log = setup_logging(args)
    select_device(args)
    if args.coordinator:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    tc = TrainingConfig(
        batch_size=args.batch_size,
        block_size=args.block_size,
        grad_acc_steps=args.grad_acc_steps,
        learning_rate=args.learning_rate,
        warmup_iters=args.warmup_iters,
        lr_decay_iters=args.lr_decay_iters,
        min_lr=args.min_lr,
        weight_decay=args.weight_decay,
        grad_clip=args.grad_clip,
        max_iters=args.max_iters,
        eval_iters=args.eval_iters,
        ckpt_interval=args.ckpt_interval,
        log_interval=args.log_interval,
        patience=args.patience,
        seed=args.seed,
        dtype=args.dtype if args.dtype != "float16" else "bfloat16",
        remat=not args.no_remat,
        use_flash={"auto": None, "on": True, "off": False}[args.use_flash],
        moe_aux_weight=args.moe_aux_weight,
        moe_capacity_factor=args.moe_capacity_factor,
    )
    mesh = parse_mesh(args.mesh)
    out_dir = Path(args.ckpt) if args.ckpt else Path("out")

    if args.init == "resume":
        trainer = Trainer.resume(out_dir, mesh=mesh)
        log.info("resumed at iter %d", trainer.iter_num)
    else:
        if args.init == "hf" or (args.ckpt and has_checkpoint(out_dir)):
            cfg, params = load_checkpoint(out_dir)
        else:
            cfg = (
                Config.from_checkpoint(out_dir)
                if (out_dir / "model_config.yaml").exists()
                else Config.from_name(args.model or out_dir.name)
            )
            params = None
        trainer = Trainer(cfg, tc, mesh=mesh, params=params, out_dir=out_dir)

    # prefer the native C++ loader when the toolchain is present
    try:
        from mdi_llm_tpu.utils import native_loader

        use_native = native_loader.is_available()
    except Exception:
        use_native = False
    train_p, val_p = args.dataset / "train.bin", args.dataset / "val.bin"
    if use_native:
        train = native_loader.NativeBinDataset(train_p, seed=args.seed)
        val = native_loader.NativeBinDataset(val_p, seed=args.seed + 1) if val_p.exists() else None
        log.info("using native C++ data loader")
    else:
        train = data_loader.open_bin(train_p)
        val = data_loader.open_bin(val_p) if val_p.exists() else None

    def log_cb(entry):
        print(json.dumps(entry))

    result = trainer.fit(train, val, max_iters=args.max_iters, log_cb=log_cb)
    trainer.save(out_dir)
    print(
        f"finished at iter {result['iter_num']}, best val loss "
        f"{result['best_val_loss']:.4f} → {out_dir}"
    )
    return result


if __name__ == "__main__":
    main()
