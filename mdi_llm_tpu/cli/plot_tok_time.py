"""Overlay tokens-vs-time CSVs across node counts for one model.

≡ reference `src/plot_tok_time.py:28-66`: finds
`logs/tokens_time_samples_<k>nodes_<model>_<n>samples.csv` for k in 1..5 and
overlays the curves.
"""

from __future__ import annotations

import argparse
from pathlib import Path

from mdi_llm_tpu.utils.plots import plot_overlay


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--n-samples", type=int, default=None)
    ap.add_argument("--logs-dir", type=Path, default=Path("logs"))
    ap.add_argument("--out", type=Path, default=None)
    args = ap.parse_args(argv)

    pat = f"tokens_time_samples_*nodes_{args.model}_*samples.csv"
    paths = sorted(args.logs_dir.glob(pat))
    if args.n_samples is not None:
        paths = [p for p in paths if p.stem.endswith(f"_{args.n_samples}samples")]
    if not paths:
        raise SystemExit(f"no CSVs matching {pat} under {args.logs_dir}")
    out = args.out or args.logs_dir / f"tok_time_overlay_{args.model}.png"
    plot_overlay(paths, out)
    print(out)


if __name__ == "__main__":
    main()
