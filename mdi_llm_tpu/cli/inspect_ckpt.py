"""Checkpoint inspector: dump config, parameter keys/shapes/dtypes, sizes,
and (optionally) the stage partition.

≡ reference `src/scripts/inspect_lit.py` (litGPT checkpoint key/shape dump)
and `old/nanoGPT/test_checkpoint.py` (split-correctness inspector: exercises
`split_parameters` and reports per-chunk sizes).

Examples:
    python -m mdi_llm_tpu.cli.inspect_ckpt --ckpt checkpoints/custom/NanoLlama
    python -m mdi_llm_tpu.cli.inspect_ckpt --ckpt <dir> --n-stages 3
"""

from __future__ import annotations

import argparse
from pathlib import Path

import numpy as np


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", type=Path, required=True)
    ap.add_argument("--n-stages", type=int, default=0, help="also show the stage split")
    ap.add_argument("--keys-only", action="store_true")
    return ap


def _flatten(tree, prefix=""):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flatten(tree[k], f"{prefix}{k}." if prefix or True else k)
    else:
        yield prefix.rstrip("."), np.asarray(tree)


def _dump(params, keys_only=False) -> int:
    total = 0
    for name, arr in _flatten(params):
        total += arr.nbytes
        if keys_only:
            print(name)
        else:
            print(f"{name:60s} {str(arr.dtype):10s} {tuple(arr.shape)}")
    return total


def main(argv=None):
    args = build_parser().parse_args(argv)
    from mdi_llm_tpu.utils.checkpoint import load_checkpoint

    cfg, params = load_checkpoint(args.ckpt)
    n_params = sum(int(np.asarray(a).size) for _, a in _flatten(params))
    print(f"# {cfg.name}: n_layer={cfg.n_layer} n_head={cfg.n_head} "
          f"n_embd={cfg.n_embd} n_query_groups={cfg.n_query_groups} "
          f"block_size={cfg.block_size} padded_vocab={cfg.padded_vocab_size}")
    print(f"# params: {n_params:,} ({n_params/1e6:.1f}M)")
    total = _dump(params, args.keys_only)
    print(f"# total bytes: {total:,} ({total/2**20:.1f} MiB)")

    if args.n_stages > 1:
        from mdi_llm_tpu.parallel.partition import split_params, stage_layers

        counts = stage_layers(cfg.n_layer, args.n_stages)
        stages = split_params(cfg, params, args.n_stages)
        print(f"\n# stage split over {args.n_stages} stages: layers {counts}")
        for i, st in enumerate(stages):
            sz = sum(a.nbytes for _, a in _flatten(st))
            keys = [k for k in st if k != "blocks"]
            print(f"  stage {i}: {counts[i]} layers, {sz/2**20:.1f} MiB, extras={keys}")


if __name__ == "__main__":
    main()
