"""Interactive chat REPL with streaming token output.

≡ reference `src/chat.py`: apply the model's prompt style per turn, stream
tokens as they decode (incremental re-decode so multi-byte/merged tokens
print correctly, chat.py:36-54), keep the conversation in the KV window by
accumulating turn tokens, stop on the style's stop sequences.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from mdi_llm_tpu.cli._common import (
    add_common_args,
    load_model,
    resolve_kv_dtype,
    select_device,
    setup_logging,
)
from mdi_llm_tpu.config import TEMPERATURE, TOP_K
from mdi_llm_tpu.generation import Generator


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    ap.add_argument("--n-tokens", type=int, default=512, help="max tokens per reply")
    ap.add_argument("--temperature", type=float, default=TEMPERATURE)
    ap.add_argument("--top-k", type=int, default=TOP_K)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument(
        "--tp-devices",
        type=int,
        default=0,
        help="tensor-parallel streaming over N devices (GSPMD Megatron "
        "sharding; cuts per-token latency for models too big for one chip)",
    )
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    setup_logging(args)
    select_device(args)
    cfg, params, tokenizer, prompt_style = load_model(args)
    if tokenizer is None:
        raise SystemExit("chat needs a checkpoint with a tokenizer (--ckpt)")
    stop_seqs = prompt_style.stop_tokens(tokenizer)
    mesh = None
    if args.tp_devices:
        from mdi_llm_tpu.cli._common import make_tp_mesh

        mesh = make_tp_mesh(args.tp_devices, args.quantize)
    gen = Generator(
        cfg, params, max_seq_length=args.sequence_length, rng_seed=args.seed,
        quantize=args.quantize, cache_dtype=resolve_kv_dtype(args.kv_dtype),
        mesh=mesh,
    )

    print(f"Chatting with {cfg.name} — empty line or Ctrl-D to exit.")
    history: list[int] = []
    while True:
        try:
            user = input(">> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            break
        if not user:
            break
        turn = tokenizer.encode(prompt_style.apply(user)).tolist()
        context = history + turn
        limit = gen.max_seq_length - args.n_tokens - 1
        if len(context) > limit > 0:
            context = context[-limit:]  # slide the window

        reply_ids: list[int] = []
        printed = ""
        try:
            for tok in gen.generate_chat(
                context,
                args.n_tokens,
                temperature=args.temperature,
                top_k=args.top_k,
                top_p=args.top_p,
                stop_sequences=stop_seqs,
            ):
                reply_ids.append(tok)
                # incremental re-decode (≡ chat.py:174-200): print only the
                # newly stabilized suffix
                text = tokenizer.decode(np.asarray(reply_ids))
                if text.startswith(printed):
                    sys.stdout.write(text[len(printed) :])
                    sys.stdout.flush()
                    printed = text
        except KeyboardInterrupt:
            print("\n[interrupted]")
        print()
        history = context + reply_ids
    return 0


if __name__ == "__main__":
    main()
