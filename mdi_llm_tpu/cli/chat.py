"""Interactive chat REPL with streaming token output.

≡ reference `src/chat.py`: apply the model's prompt style per turn, stream
tokens as they decode (incremental re-decode so multi-byte/merged tokens
print correctly, chat.py:36-54), keep the conversation in the KV window by
accumulating turn tokens, stop on the style's stop sequences.

Streaming backends: single device (default), tensor-parallel
(`--tp-devices N`), expert-parallel for MoE configs (`--ep-devices N`,
GShard token dispatch), sequence-parallel (`--sp-devices N`, ring-attention
prefill + sequence-sharded KV so the conversation window scales with N
chips; composes with `--quantize` for long-context 8B-class serving), or
the recurrent pipeline ring (`--pipeline-stages N`) — the last matching
the reference's distributed chat experience where the starter surfaces
tokens as they come back around the ring (gptserver.py:904-956).
"""

from __future__ import annotations

import argparse

from mdi_llm_tpu.cli._common import (
    add_common_args,
    load_model,
    resolve_kv_dtype,
    select_device,
    setup_logging,
)
from mdi_llm_tpu.config import TEMPERATURE, TOP_K
from mdi_llm_tpu.generation import Generator, StreamPrinter


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    add_common_args(ap)
    ap.add_argument("--n-tokens", type=int, default=512, help="max tokens per reply")
    ap.add_argument("--temperature", type=float, default=TEMPERATURE)
    ap.add_argument("--top-k", type=int, default=TOP_K)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument(
        "--speculative", type=int, default=0, metavar="K",
        help="greedy speculative chat: draft K tokens by n-gram lookup over "
        "the whole conversation, verify in one forward (requires "
        "--temperature 0; single-device/tp/ep/sp backends)",
    )
    ap.add_argument(
        "--tp-devices",
        type=int,
        default=0,
        help="tensor-parallel streaming over N devices (GSPMD Megatron "
        "sharding; cuts per-token latency for models too big for one chip)",
    )
    ap.add_argument(
        "--pipeline-stages",
        type=int,
        default=0,
        help="stream over an N-stage recurrent pipeline ring (layer-sharded "
        "stages; tokens surface as stage 0 collects them)",
    )
    ap.add_argument(
        "--ep-devices",
        type=int,
        default=0,
        help="expert-parallel streaming for MoE configs (N>=2 devices; "
        "GShard token dispatch over an ep mesh)",
    )
    ap.add_argument(
        "--sp-devices",
        type=int,
        default=0,
        help="sequence-parallel streaming over N devices: ring-attention "
        "prefill + sequence-sharded KV cache, so the conversation window "
        "scales with N chips (composes with --quantize)",
    )
    ap.add_argument(
        "--sp-flash",
        action="store_true",
        help="run the sp prefill ring through the Pallas flash kernel "
        "(TPU opt-in; engages when the local chunk is >= 2048)",
    )
    ap.add_argument(
        "--sp-chunk",
        type=int,
        default=8,
        help="sp streaming: decode steps batched per dispatch — smaller = "
        "lower time-to-first-byte, larger = higher throughput",
    )
    ap.add_argument(
        "--moe-capacity-factor",
        type=float,
        default=None,
        help="expert-parallel dispatch capacity factor (see cli/sample.py); "
        "default exact/no-drop",
    )
    ap.add_argument(
        "--rotations-per-call",
        type=int,
        default=2,
        help="pipeline streaming: ring rotations batched per dispatch — "
        "smaller = lower time-to-first-byte, larger = higher throughput",
    )
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    setup_logging(args)
    select_device(args)
    backends = (
        args.tp_devices, args.pipeline_stages, args.ep_devices, args.sp_devices
    )
    if sum(bool(f) for f in backends) > 1:
        raise SystemExit(
            "--tp-devices, --pipeline-stages, --ep-devices and --sp-devices "
            "are separate streaming backends; pick one (for a pipe x tp "
            "mesh use cli/starter.py)"
        )
    if args.speculative:
        if args.temperature != 0.0:
            raise SystemExit("--speculative requires --temperature 0 (greedy)")
        if args.pipeline_stages:
            raise SystemExit(
                "--speculative applies to session backends "
                "(single-device/tp/ep/sp); drop --pipeline-stages"
            )
    cfg, params, tokenizer, prompt_style = load_model(args)
    if tokenizer is None:
        raise SystemExit("chat needs a checkpoint with a tokenizer (--ckpt)")
    stop_seqs = prompt_style.stop_tokens(tokenizer)

    if args.pipeline_stages:
        from mdi_llm_tpu.parallel.pipeline import PipelineEngine

        eng = PipelineEngine(
            cfg,
            params,
            n_stages=args.pipeline_stages,
            max_seq_length=args.sequence_length,
            rng_seed=args.seed,
            quantize=args.quantize,
            cache_dtype=resolve_kv_dtype(args.kv_dtype),
            rotations_per_call=args.rotations_per_call,
        )
    elif args.sp_devices:
        from mdi_llm_tpu.parallel.sp_inference import SPGenerator

        eng = SPGenerator(
            cfg, params, n_devices=args.sp_devices,
            max_seq_length=args.sequence_length, rng_seed=args.seed,
            cache_dtype=resolve_kv_dtype(args.kv_dtype),
            decode_chunk=args.sp_chunk, use_flash=args.sp_flash,
            quantize=args.quantize,
        )
    else:
        mesh = None
        if args.tp_devices:
            from mdi_llm_tpu.cli._common import make_tp_mesh

            mesh = make_tp_mesh(args.tp_devices, args.quantize)
        elif args.ep_devices:
            from mdi_llm_tpu.cli._common import make_ep_mesh

            mesh = make_ep_mesh(args.ep_devices, cfg)
        eng = Generator(
            cfg, params, max_seq_length=args.sequence_length, rng_seed=args.seed,
            quantize=args.quantize, cache_dtype=resolve_kv_dtype(args.kv_dtype),
            mesh=mesh, moe_capacity_factor=args.moe_capacity_factor,
        )

    print(f"Chatting with {cfg.name} — empty line or Ctrl-D to exit.")
    # Generator and sp backends get cross-turn KV reuse: each turn
    # prefills (or, on sp, round-robin-appends) only its new tokens, so
    # turn latency tracks the turn length rather than the conversation
    # length.  The pipeline engine re-prefills the window every turn
    # (the reference's behavior for every backend).
    session = eng.chat_session() if hasattr(eng, "chat_session") else None
    history: list[int] = []
    while True:
        try:
            user = input(">> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            break
        if not user:
            break
        turn = tokenizer.encode(prompt_style.apply(user)).tolist()
        pre_turn = session.history[:] if session is not None else None

        printer = StreamPrinter(tokenizer, stop_seqs)
        try:
            if session is not None:
                # stream is already stop-filtered: raw emit; the session
                # slides its own window and owns the history
                for tok in session.send(
                    turn,
                    args.n_tokens,
                    temperature=args.temperature,
                    top_k=args.top_k,
                    top_p=args.top_p,
                    stop_sequences=stop_seqs,
                    speculative=args.speculative or None,
                ):
                    printer.emit(tok)
                print()
                continue

            context = history + turn
            limit = eng.max_seq_length - args.n_tokens - 1
            if len(context) > limit > 0:
                context = context[-limit:]  # slide the window

            if args.pipeline_stages:
                # stream via the ring's collect callback; the engine's
                # returned (trimmed) list is authoritative — finish()
                # flushes any held-back remainder
                outs, _ = eng.generate(
                    [context],
                    args.n_tokens,
                    temperature=args.temperature,
                    top_k=args.top_k,
                    top_p=args.top_p,
                    stop_sequences=stop_seqs,
                    stream_cb=lambda _j, tok: printer.push(tok),
                )
                printer.finish(outs[0][len(context) :])
            else:
                # generate_chat already filters stop sequences: raw emit
                for tok in eng.generate_chat(
                    context,
                    args.n_tokens,
                    temperature=args.temperature,
                    top_k=args.top_k,
                    top_p=args.top_p,
                    stop_sequences=stop_seqs,
                ):
                    printer.emit(tok)
        except KeyboardInterrupt:
            print("\n[interrupted]")
            if session is not None:
                # mid-stream interrupt skipped the generator's reconcile
                # step, so cache and history are desynced; keep the
                # conversation (pre-turn history + this turn + the partial
                # reply, matching the stateless path) and let the next send
                # rebuild the cache with one full prefill
                session.rollback(pre_turn + turn + printer.reply)
                print()
                continue
        print()
        history = context + printer.reply
    return 0


if __name__ == "__main__":
    main()
