"""Evaluation CLI: mean cross-entropy loss + perplexity on a token bin.

Standalone version of the reference's in-training `estimate_loss`
(`src/sub/utils/utils.py:61-107`, invoked at checkpoint intervals,
`train.py:280-311`) so a checkpoint can be scored without running the
trainer.  Prints one JSON line.

Example:
    python -m mdi_llm_tpu.cli.evaluate --ckpt checkpoints/custom/NanoLlama \
        --dataset data/shakespeare --split val --eval-iters 50
"""

from __future__ import annotations

import argparse
import json
import math
from pathlib import Path

import numpy as np


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt", type=Path, required=True)
    ap.add_argument("--dataset", type=Path, required=True, help="dir with <split>.bin")
    ap.add_argument("--split", default="val", choices=("train", "val"))
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--eval-iters", type=int, default=20)
    ap.add_argument("--seed", type=int, default=10137)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--device", default=None)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    import jax

    from mdi_llm_tpu.cli._common import DTYPES, select_device

    select_device(args)
    import jax.numpy as jnp

    from mdi_llm_tpu.training import cross_entropy_loss
    from mdi_llm_tpu.utils import data_loader
    from mdi_llm_tpu.utils.checkpoint import load_checkpoint

    dtype = DTYPES[args.dtype]
    cfg, params = load_checkpoint(args.ckpt, dtype=dtype)
    block_size = int(args.block_size or cfg.block_size)

    # eval-only: no optimizer state, no train-step compile (a Trainer would
    # allocate 2x param memory in AdamW moments it never uses)
    eval_fn = jax.jit(
        lambda p, x, y: cross_entropy_loss(cfg, p, x, y, remat=False)
    )
    bin_path = args.dataset / f"{args.split}.bin"
    data = data_loader.open_bin(bin_path)
    if len(data) <= block_size + 1:
        raise SystemExit(
            f"{bin_path} holds {len(data)} tokens — need more than "
            f"block_size+1 = {block_size + 1} (pass a smaller --block-size)"
        )
    rng = np.random.default_rng(args.seed)
    losses = []
    for _ in range(args.eval_iters):
        x, y = data_loader.get_batch(data, args.batch_size, block_size, rng)
        losses.append(float(eval_fn(params, jnp.asarray(x), jnp.asarray(y))))
    loss = float(np.mean(losses))
    print(
        json.dumps(
            {
                "ckpt": str(args.ckpt),
                "split": args.split,
                "tokens": int(len(data)),
                "eval_iters": args.eval_iters,
                "loss": round(loss, 4),
                "perplexity": round(math.exp(min(loss, 20.0)), 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    main()
