"""Test configuration: force an 8-device virtual CPU platform BEFORE any jax
computation, so mesh/pipeline tests run anywhere (SURVEY.md §4 note: the
reference's localhost-loopback trick maps to
--xla_force_host_platform_device_count here).

Note: this image boots an `axon` TPU backend via sitecustomize and pins
JAX_PLATFORMS=axon, so the env-var route is overridden; updating the
`jax_platforms` config before first backend use is what actually works.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    assert jax.devices()[0].platform == "cpu" and len(jax.devices()) == 8
    return jax.devices()
