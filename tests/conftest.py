"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
imports, so mesh/pipeline tests run anywhere (SURVEY.md §4 note: the
reference's localhost-loopback trick maps to
--xla_force_host_platform_device_count here)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
