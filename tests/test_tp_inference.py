"""Tensor-parallel (GSPMD) inference: Megatron-sharded weights + KV heads
split across chips must reproduce single-device generation token-for-token.

Beyond reference parity: the reference has no tensor parallelism at all
(SURVEY.md §2.4 "Tensor parallelism: Absent"); on TPU it is a declarative
layout over a mesh (parallel/sharding.py) with XLA inserting the
all-gather/psum collectives over ICI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models.transformer import init_params
from mdi_llm_tpu.parallel.mesh import make_mesh
from tests.test_model import CONFIG_VARIANTS, tiny_config

PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7], [2, 7, 1]]


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=128, n_layer=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def single(model):
    cfg, params = model
    return Generator(cfg, params, cache_dtype=jnp.float32)


@pytest.mark.smoke
def test_tp_matches_single_device(model, single, devices):
    cfg, params = model
    want, _ = single.generate(PROMPTS, 12, temperature=0.0)
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )
    got, _ = eng.generate(PROMPTS, 12, temperature=0.0)
    assert got == want


def test_dp_tp_matches_single_device(model, single, devices):
    cfg, params = model
    want, _ = single.generate(PROMPTS, 10, temperature=0.0)
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"dp": 2, "tp": 2}, devices[:4]),
    )
    got, _ = eng.generate(PROMPTS, 10, temperature=0.0)
    assert got == want


def test_tp_gqa_with_stop_sequences(single, devices):
    """GQA KV-group sharding (G=2 over tp=2) + host-side stop detection."""
    cfg = tiny_config(block_size=128, n_layer=3, **CONFIG_VARIANTS["gqa"])
    params = init_params(cfg, jax.random.PRNGKey(1))
    ref = Generator(cfg, params, cache_dtype=jnp.float32)
    free, _ = ref.generate(PROMPTS[:2], 10, temperature=0.0)
    stop = [free[0][len(PROMPTS[0]) + 2]]
    want, _ = ref.generate(PROMPTS[:2], 10, temperature=0.0, stop_sequences=[stop])
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )
    got, _ = eng.generate(PROMPTS[:2], 10, temperature=0.0, stop_sequences=[stop])
    assert got == want


def test_tp_rejects_indivisible_heads(devices):
    cfg = tiny_config(n_head=3, n_query_groups=3, n_embd=48)
    params = init_params(cfg, jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="n_head"):
        Generator(
            cfg, params, cache_dtype=jnp.float32,
            mesh=make_mesh({"tp": 2}, devices[:2]),
        )


@pytest.mark.parametrize("mode,wkey", [
    ("int8", "weight_q"), ("w8a8", "weight_q8"), ("int4", "weight_q4"),
])
def test_tp_quantized_decode_parity(model, devices, mode, wkey):
    """Quantized weights over a tp mesh (pre-r5 this raised): the standard
    Megatron specs adapt to every storage layout (weight_q* inherits the
    weight's spec, scale its leading dims — sharding.adapt_specs_to_tree),
    reproducing single-device quantized decode token-for-token."""
    cfg, params = model
    want, _ = Generator(
        cfg, params, cache_dtype=jnp.float32, quantize=mode
    ).generate(PROMPTS, 10, temperature=0.0)
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32, quantize=mode,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )
    got, _ = eng.generate(PROMPTS, 10, temperature=0.0)
    assert got == want
    # column-parallel qkv: quantized weight AND its per-out-channel scale
    # shard over tp; row-parallel proj keeps its scale replicated (int8 —
    # the int4 group scale instead shards its group axis with the input)
    qkv = eng.params["blocks"]["attn"]["qkv"]
    assert "tp" in str(qkv[wkey].sharding.spec)
    if mode in ("int8", "w8a8"):
        assert "tp" in str(qkv["scale"].sharding.spec)
        proj_scale = eng.params["blocks"]["attn"]["proj"]["scale"]
        assert "tp" not in str(proj_scale.sharding.spec)


def test_dp_tp_quantized_parity(model, devices):
    """Quantized + the full dp x tp serving mesh."""
    cfg, params = model
    want, _ = Generator(
        cfg, params, cache_dtype=jnp.float32, quantize="int8"
    ).generate(PROMPTS, 8, temperature=0.0)
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32, quantize="int8",
        mesh=make_mesh({"dp": 2, "tp": 2}, devices[:4]),
    )
    got, _ = eng.generate(PROMPTS, 8, temperature=0.0)
    assert got == want


def test_dp_rejects_ragged_batch(model, devices):
    cfg, params = model
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"dp": 2}, devices[:2]),
    )
    with pytest.raises(ValueError, match="divisible"):
        eng.generate(PROMPTS[:3], 4, temperature=0.0)


def test_cli_tp_flag_exclusions():
    from mdi_llm_tpu.cli.sample import main

    # pipeline-stages x tp-devices is a supported combination (pipe x tp
    # mesh); sequence parallelism is the remaining exclusion
    with pytest.raises(SystemExit, match="exclusive"):
        main(
            [
                "--model", "pythia-14m", "--tp-devices", "2",
                "--sp-devices", "2", "--n-samples", "1", "--n-tokens", "4",
            ]
        )


def test_dp_streaming_rejected_at_call_time(model, devices):
    """generate_chat must raise when constructed over a dp mesh BEFORE the
    caller starts iterating (a raise inside the generator body would only
    surface on the first next(), after streaming has begun)."""
    cfg, params = model
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"dp": 2}, devices[:2]),
    )
    with pytest.raises(ValueError, match="tp-only"):
        eng.generate_chat([3, 1, 4], 4, temperature=0.0)


def test_tp_moe_experts_sharded(devices):
    """MoE inference over tp: the expert axis is the sharded dimension
    (sharding.py P(None, e, ...)), token-identical to single device."""
    cfg = tiny_config(
        block_size=64, n_layer=3, mlp_class_name="LLaMAMoE",
        n_expert=4, n_expert_per_token=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(3))
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate(PROMPTS[:2], 8, temperature=0.0)
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )
    got, _ = eng.generate(PROMPTS[:2], 8, temperature=0.0)
    assert got == want


def test_chat_session_on_tp_mesh(model, single, devices):
    """ChatSession cross-turn KV reuse over a tp=2 mesh: token-identical to
    the single-device stateless baseline across turns (the sharded cache
    persists and grows across sends)."""
    cfg, params = model
    eng = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )
    sess = eng.chat_session()
    history: list[int] = []
    for turn in ([3, 1, 4], [9, 2]):
        want = list(single.generate_chat(history + turn, 8, temperature=0.0))
        got = list(sess.send(turn, 8, temperature=0.0))
        assert got == want
        history += turn + want
