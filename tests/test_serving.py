"""Serving subsystem tests: block pool accounting + prefix reuse,
scheduler admission/retirement/preemption, and the decisive end-to-end
contract — `ServingEngine` greedy outputs are token-identical to
sequential `Generator.generate` calls, whatever the scheduling order,
block placement, chunking or preemptions did in between."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.scheduler import Request, Scheduler
from tests.test_model import tiny_config


# ---------------------------------------------------------------------------
# KVPool
# ---------------------------------------------------------------------------


def test_pool_alloc_free_roundtrip():
    pool = KVPool(num_blocks=9, block_size=4)
    assert pool.available == 8  # block 0 reserved
    a = pool.alloc(3)
    b = pool.alloc(5)
    assert a is not None and b is not None
    assert 0 not in a + b and len(set(a + b)) == 8
    assert pool.alloc(1) is None  # exhausted, all-or-nothing
    assert pool.used == 8 and pool.utilization == 1.0
    pool.release(a)
    assert pool.available == 3 and pool.used == 5
    c = pool.alloc(3)
    assert c is not None and set(c) == set(a)  # blocks actually recycled


def test_pool_prefix_reuse_and_refcounts():
    pool = KVPool(num_blocks=17, block_size=4)
    prompt = list(range(100, 111))  # 11 tokens -> 2 full blocks
    blocks = pool.alloc(pool.blocks_needed(len(prompt)))
    pool.register_prefix(blocks, prompt)

    # same prompt matches both full blocks, copy-free, refcounted
    m, n_cached = pool.match_prefix(prompt)
    assert m == blocks[:2] and n_cached == 8
    assert pool.prefix_hits == 2
    # a longer prompt sharing the head matches the same chain
    m2, n2 = pool.match_prefix(prompt + [1, 2, 3])
    assert m2 == blocks[:2] and n2 == 8
    # a diverging prompt matches only the first block
    div = prompt[:4] + [9] * 7
    m3, n3 = pool.match_prefix(div)
    assert m3 == blocks[:1] and n3 == 4
    # the last prompt token is never covered (recompute guarantee)
    aligned = list(range(200, 208))  # exactly 2 blocks
    ab = pool.alloc(2)
    pool.register_prefix(ab, aligned)
    m4, n4 = pool.match_prefix(aligned)
    assert len(m4) == 1 and n4 == 4

    # release everything: registered blocks stay warm (evictable), not free
    pool.release(blocks)  # original owner
    for blks in (m, m2, m3, m4, ab):
        pool.release(blks)
    assert pool.used == 0
    # still matchable after full release — copy-free reuse survives owners
    m5, n5 = pool.match_prefix(prompt)
    assert m5 == blocks[:2] and n5 == 8
    pool.release(m5)


def test_pool_eviction_reclaims_cached_blocks():
    pool = KVPool(num_blocks=5, block_size=2)  # 4 usable
    prompt = [1, 2, 3, 4, 5]
    blocks = pool.alloc(3)
    pool.register_prefix(blocks, prompt)
    pool.release(blocks)
    # free list empty contribution: 1 never-used + 3 evictable
    got = pool.alloc(4)
    assert got is not None and len(got) == 4
    # evicted hashes are gone: nothing matches anymore
    m, n = pool.match_prefix(prompt)
    assert m == [] and n == 0


def test_pool_validation():
    with pytest.raises(ValueError):
        KVPool(1, 4)
    with pytest.raises(ValueError):
        KVPool(4, 0)


# ---------------------------------------------------------------------------
# Scheduler (policy only — no device work)
# ---------------------------------------------------------------------------


def _sched(num_blocks=33, block_size=4, max_batch=2, prefill_chunk=8,
           max_seq_length=64):
    pool = KVPool(num_blocks, block_size)
    return Scheduler(pool, max_batch, prefill_chunk, max_seq_length), pool


def test_scheduler_admission_and_slots():
    sched, pool = _sched()
    for i in range(3):
        sched.add(Request(f"r{i}", [1, 2, 3, 4, 5], 4))
    kind, entries = sched.next_batch(token_budget=32)
    # both prompts fit the budget in ONE mixed batch, FCFS order
    assert kind == "mixed"
    assert [(s.req.rid, n) for s, n in entries] == [("r0", 5), ("r1", 5)]
    # both slots filled FCFS; third request waits
    rids = {s.req.rid for s in sched.running()}
    assert rids == {"r0", "r1"} and len(sched.waiting) == 1
    # retiring r0 frees the slot; r2 admits on the next batch
    sched.retire(sched.running()[0])
    sched.next_batch(token_budget=32)
    assert {s.req.rid for s in sched.running()} == {"r1", "r2"}


def test_scheduler_rejects_impossible_requests():
    sched, _ = _sched(max_seq_length=16)
    with pytest.raises(ValueError, match="exceeds max_seq_length"):
        sched.add(Request("big", [1] * 10, 10))
    with pytest.raises(ValueError, match="empty prompt"):
        sched.add(Request("empty", [], 4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.add(Request("zero", [1, 2], 0))
    sched2, _ = _sched(num_blocks=3, block_size=2, max_seq_length=64)
    with pytest.raises(ValueError, match="blocks"):
        sched2.add(Request("huge", [1] * 20, 10))


def _complete_prefill(entries):
    """Simulate the engine crediting a mixed batch's prefill feeds."""
    for seq, n in entries:
        if seq.needs_prefill:
            seq.fed += n
            if seq.fed >= seq.prefill_target and seq.next_tok is None:
                seq.next_tok = 7
                seq.tokens.append(7)


def test_scheduler_token_budget_composition():
    """The mixed batch packs decode lanes FIRST, then prefill chunks split
    to fit the remaining token budget — a prompt longer than the leftover
    feeds across several steps, and a decode lane rides EVERY one of those
    steps (no starvation behind a long prefill)."""
    sched, _ = _sched(prefill_chunk=32, max_seq_length=64)
    sched.add(Request("a", [1, 2, 3], 8))
    kind, entries = sched.next_batch(token_budget=10)
    assert kind == "mixed" and [n for _, n in entries] == [3]
    _complete_prefill(entries)  # "a" is now decode-ready
    sched.add(Request("b", [1] * 20, 4))
    steps = []
    while True:
        action = sched.next_batch(token_budget=10)
        if action[0] != "mixed":
            break
        kind, entries = action
        steps.append([(s.req.rid, n, s.needs_prefill) for s, n in entries])
        _complete_prefill(entries)
    # budget 10 - 1 decode lane = 9 prefill tokens/step: 20-token prompt
    # splits 9 + 9 + 2, and "a"'s decode token leads every mixed batch
    assert steps == [
        [("a", 1, False), ("b", 9, True)],
        [("a", 1, False), ("b", 9, True)],
        [("a", 1, False), ("b", 2, True)],
    ]
    # with no prefill work left the engine's decode paths take over
    kind, seqs = sched.next_batch(token_budget=10)
    assert kind == "decode" and {s.req.rid for s in seqs} == {"a", "b"}


def test_scheduler_budget_packs_multiple_prefills():
    """Several prefilling prompts share one mixed batch in admission order,
    each capped at prefill_chunk, until the budget runs out."""
    sched, _ = _sched(max_batch=3, prefill_chunk=4, max_seq_length=64)
    for i, plen in enumerate((6, 3, 9)):
        sched.add(Request(f"r{i}", [1] * plen, 4))
    kind, entries = sched.next_batch(token_budget=8)
    assert kind == "mixed"
    # chunk cap 4 for r0, then 3 for r1, then the 1 leftover for r2
    assert [(s.req.rid, n) for s, n in entries] == \
        [("r0", 4), ("r1", 3), ("r2", 1)]


# ---------------------------------------------------------------------------
# End-to-end engine parity (the acceptance contract)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _sequential_greedy(cfg, params, prompts, max_news, stops=None):
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    outs = []
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        st = stops[i] if stops else ()
        outs.append(gen.generate([p], m, temperature=0.0,
                                 stop_sequences=st)[0][0])
    return outs


def test_engine_matches_sequential_generate(served_model):
    """Mixed-length trace through the continuous-batching loop: every
    request's greedy tokens equal its solo `generate()` run, with block
    tables spanning multiple blocks and ragged last blocks."""
    cfg, params = served_model
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in (3, 9, 17, 5, 33)]
    max_news = [8, 12, 6, 10, 7]
    want = _sequential_greedy(cfg, params, prompts, max_news)

    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=3, prefill_chunk=8
    )
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    streamed = {}
    results, stats = engine.run(
        stream_cb=lambda rid, tok: streamed.setdefault(rid, []).append(tok)
    )
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i], f"request r{i} diverged"
        # the stream saw exactly the generated suffix, in order
        assert streamed[f"r{i}"] == want[i][len(prompts[i]):]
    assert stats.requests_finished == len(prompts)
    assert stats.decode_steps > 0 and stats.prefill_chunks > 0
    assert 0.0 < stats.kv_utilization_peak <= 1.0
    # every request retired mid-batch released its blocks
    assert engine.pool.used == 0


def test_engine_stop_sequences_match_generate(served_model):
    cfg, params = served_model
    prompt = [9, 9, 4]
    free = _sequential_greedy(cfg, params, [prompt], [10])[0]
    stop = [[free[3 + 3]]]  # 4th generated token stops the stream
    want = _sequential_greedy(cfg, params, [prompt, prompt], [10, 10],
                              stops=[stop, ()])
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2
    )
    engine.add_request("stopped", prompt, 10, stop_sequences=stop)
    engine.add_request("free", prompt, 10)
    results, _ = engine.run()
    assert results["stopped"] == want[0]
    assert results["free"] == want[1]


def test_engine_long_prompt_splits_across_budget_steps(served_model):
    """A prompt longer than the unified step's token budget must feed
    across several mixed steps with outputs still token-identical — and
    the whole run stays inside the static (1, token_budget) dispatch
    (padded_token_frac strictly below 1, occupancy sane)."""
    cfg, params = served_model
    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in (41, 5)]
    max_news = [6, 10]
    want = _sequential_greedy(cfg, params, prompts, max_news)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, prefill_chunk=64, token_budget=12,
    )
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i], f"r{i} diverged across splits"
    # 41 prompt tokens through a <=12-token budget: several mixed steps
    assert stats.mixed_steps >= 4
    assert stats.prefill_chunks >= 4
    assert stats.tokens_useful > 0
    assert 0.0 <= stats.padded_token_frac < 1.0
    assert 0.0 < stats.mixed_batch_occupancy <= 1.0


def test_engine_decode_lanes_not_starved_by_long_prefill(served_model):
    """While a long prompt is still prefilling, every unified step must
    also advance the live decode lanes: a short request that goes
    decode-ready before a long prompt arrives finishes BEFORE that
    prompt's prefill completes."""
    cfg, params = served_model
    rng = np.random.default_rng(17)
    short = rng.integers(1, cfg.vocab_size, 4).tolist()
    long = rng.integers(1, cfg.vocab_size, 60).tolist()
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, prefill_chunk=64, token_budget=8,
    )
    engine.add_request("short", short, 3)
    assert engine.step()  # short's prompt fits one step: now decode-ready
    engine.add_request("long", long, 4)
    # 60 prompt tokens at <=7/step (1 budget slot goes to short's decode
    # lane) need >= 9 mixed steps; short needs only 2 more tokens
    for _ in range(4):
        assert engine.step()
    long_seq = [s for s in engine.scheduler.running()
                if s.req.rid == "long"][0]
    assert long_seq.needs_prefill, "budget sized so long is still prefilling"
    assert "short" in engine._results, \
        "decode lanes starved behind the long prefill"
    results, stats = engine.run()
    want = _sequential_greedy(cfg, params, [short, long], [3, 4])
    assert results["short"] == want[0] and results["long"] == want[1]


def test_engine_kernel_info_reports_route_and_tuning(served_model):
    """The bench serve rows' `detail.kernel` provenance: on this CPU
    backend the auto route is the lax fallback, the tuning resolution is
    the conservative entry, and the params are fully resolved ints."""
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    info = gen.serve(block_size=4, max_batch=2).kernel_info()
    assert info["variant"] == "fallback"  # no Pallas/TPU here
    assert info["tuned"] is False
    assert info["table_source"] == "conservative"
    assert info["params"]["kv_step"] == 4  # whole-block default, resolved


def test_engine_rejects_token_budget_at_or_below_max_batch(served_model):
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="token_budget"):
        gen.serve(max_batch=4, token_budget=4)
    with pytest.raises(ValueError, match="token_budget"):
        gen.serve(max_batch=4, token_budget=2)


def test_preemption_mid_prefill_resumes_with_correct_fed(served_model):
    """A sequence preempted while still PREFILLING (the older lane's decode
    growth drains the pool mid-way through the newer prompt's budget-split
    feed) must resume from the queue and re-feed to the exact `fed`
    contract — outputs token-identical, blocks fully rolled back."""
    cfg, params = served_model
    rng = np.random.default_rng(21)
    short = rng.integers(1, cfg.vocab_size, 4).tolist()
    long = rng.integers(1, cfg.vocab_size, 36).tolist()
    # both admit (2 + 10 of 12 usable blocks), but short's decode growth
    # past 8 tokens needs a 3rd block with 0 free — the newer, still-
    # prefilling long prompt is the preemption victim (5 tokens/step over
    # a 6-token budget means its 36-token feed is mid-flight at that point)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, max_blocks=1 + 12, prefix_caching=False,
        token_budget=6, decode_chunk=1,
    )
    engine.add_request("short", short, 28)
    engine.add_request("long", long, 4)
    results, stats = engine.run()
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    want = _sequential_greedy(cfg, params, [short, long], [28, 4])
    assert results["short"] == want[0], "short diverged"
    assert results["long"] == want[1], "long diverged across its preemption"
    assert engine.pool.used == 0


def test_engine_prefix_cache_reuses_blocks(served_model):
    """A later request sharing a prompt head must reuse the registered
    blocks copy-free AND still produce the exact sequential output."""
    cfg, params = served_model
    rng = np.random.default_rng(7)
    head = rng.integers(1, cfg.vocab_size, 21).tolist()
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2
    )
    engine.add_request("first", head, 6)
    engine.run()
    tail = head + [7, 8]
    engine.add_request("second", tail, 6)
    results, stats = engine.run()
    assert stats.prefix_cache_hits >= 5  # 21-token head -> 5 full blocks
    want = _sequential_greedy(cfg, params, [tail], [6])[0]
    assert results["second"] == want


def test_engine_preemption_preserves_parity(served_model):
    """A pool too small for the whole batch forces recompute preemption;
    outputs must still be token-identical to solo runs.  decode_chunk=1
    pins the per-step engine (this pool size forces its one-block-at-a-time
    growth dry); the chunked engine's preemption twin is below."""
    cfg, params = served_model
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in (9, 13, 11)]
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=3, max_blocks=1 + 14, prefix_caching=False,
        decode_chunk=1,
    )
    for i, p in enumerate(prompts):
        engine.add_request(f"p{i}", p, 10)
    results, stats = engine.run()
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    want = _sequential_greedy(cfg, params, prompts, [10, 10, 10])
    for i in range(len(prompts)):
        assert results[f"p{i}"] == want[i], f"p{i} diverged across preemption"


@pytest.mark.parametrize("chunk,buffered", [(4, True), (8, False)])
def test_chunked_preemption_preserves_parity(served_model, chunk, buffered):
    """The chunked engine's K-step block reservation under a dry pool:
    admission succeeds (per-request footprints fit) but chunk reservations
    exhaust the pool mid-decode, forcing preemption — and the unused
    speculative blocks of preempted/retired sequences roll back (pool
    drains to 0 at the end).  Outputs stay token-identical to solo runs."""
    cfg, params = served_model
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in (9, 13, 11)]
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=3, max_blocks=1 + 10, prefix_caching=False,
        decode_chunk=chunk, double_buffer=buffered,
    )
    for i, p in enumerate(prompts):
        engine.add_request(f"p{i}", p, 10)
    results, stats = engine.run()
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    want = _sequential_greedy(cfg, params, prompts, [10, 10, 10])
    for i in range(len(prompts)):
        assert results[f"p{i}"] == want[i], f"p{i} diverged across preemption"
    assert engine.pool.used == 0  # speculative reservations rolled back


# ---------------------------------------------------------------------------
# Multi-token serving steps: chunked decode + batched speculative verify
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk,buffered", [(1, True), (3, True), (8, True),
                                            (8, False)],
                         ids=["k1", "k3", "k8", "k8-nobuf"])
def test_chunked_serving_token_identical(served_model, chunk, buffered):
    """Greedy chunked serving (any K, double-buffered or not) is
    token-identical to the per-step engine and to sequential `generate()`
    on a mixed-length trace — the acceptance contract for the multi-token
    serving step.  The host syncs once per chunk, not per token."""
    cfg, params = served_model
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in (3, 9, 17, 5, 33)]
    max_news = [8, 12, 6, 10, 7]
    want = _sequential_greedy(cfg, params, prompts, max_news)

    def run(k, buf):
        engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
            block_size=4, max_batch=3, prefill_chunk=8,
            decode_chunk=k, double_buffer=buf,
        )
        for i, (p, m) in enumerate(zip(prompts, max_news)):
            engine.add_request(f"r{i}", p, m)
        return engine.run()

    results, stats = run(chunk, buffered)
    per_step, ps_stats = run(1, False)
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i], f"r{i} diverged from generate()"
        assert results[f"r{i}"] == per_step[f"r{i}"]
    if chunk > 1:
        # the amortization is real: strictly fewer host reads than the
        # per-step engine for the same token count
        assert stats.host_syncs < ps_stats.host_syncs
        assert stats.tokens_per_sync > ps_stats.tokens_per_sync


def test_chunked_stop_sequence_mid_chunk(served_model):
    """Stops landing mid-chunk must truncate exactly where the per-step
    engine stops — single-token stops (masked on device) and multi-token
    stops (detected host-side between chunks) alike; the extra computed
    tokens are discarded without perturbing any other slot."""
    cfg, params = served_model
    prompt = [9, 9, 4]
    free = _sequential_greedy(cfg, params, [prompt], [16])[0]
    gen_tail = free[len(prompt):]
    stop1 = [[gen_tail[4]]]           # 5th generated token, single-token stop
    stop2 = [gen_tail[6:8]]           # multi-token stop spanning positions 7-8
    want = _sequential_greedy(
        cfg, params, [prompt, prompt, prompt], [16, 16, 16],
        stops=[stop1, stop2, ()],
    )
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=3, decode_chunk=8
    )
    engine.add_request("s1", prompt, 16, stop_sequences=stop1)
    engine.add_request("s2", prompt, 16, stop_sequences=stop2)
    engine.add_request("free", prompt, 16)
    results, _ = engine.run()
    assert results["s1"] == want[0]
    assert results["s2"] == want[1]
    assert results["free"] == want[2]
    assert engine.pool.used == 0  # mid-chunk retirement released everything


def _cycling_prompts(cfg, seeds):
    """Prompts whose greedy continuation echoes earlier context (the tiny
    random model falls into cycles), so n-gram drafting genuinely fires."""
    return [np.random.default_rng(s).integers(1, cfg.vocab_size, 5).tolist()
            for s in seeds]


@pytest.mark.parametrize("spec_k,chunk", [(4, 1), (4, 4), (8, 8)])
def test_speculative_serving_token_identical(served_model, spec_k, chunk):
    """Batched speculative serving (per-slot n-gram drafts, ONE ragged
    verify forward over the paged cache) is token-identical to sequential
    greedy `generate()` — and actually accepts drafts (the prompts cycle,
    the regime prompt-lookup targets)."""
    cfg, params = served_model
    prompts = _cycling_prompts(cfg, (5, 7, 0))
    max_news = [40, 35, 30]
    want = _sequential_greedy(cfg, params, prompts, max_news)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=3, decode_chunk=chunk, spec_k=spec_k,
    )
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i], f"r{i} diverged under spec_k"
    assert stats.spec_drafted > 0, "trace was built to draft"
    assert stats.spec_accepted > 0, "cycling continuations must accept"
    assert 0.0 < stats.spec_accept_rate <= 1.0
    assert engine.pool.used == 0


def test_speculative_mixed_batch_with_non_drafting_slot(served_model):
    """A slot whose context never echoes rides the same ragged verify with
    one valid token (q_len 1) while its neighbors verify K+1 — per-slot
    raggedness end to end, outputs all exact."""
    cfg, params = served_model
    rng = np.random.default_rng(11)
    prompts = _cycling_prompts(cfg, (5,)) + [
        rng.integers(1, cfg.vocab_size, 23).tolist()  # non-echoing
    ]
    max_news = [30, 12]
    want = _sequential_greedy(cfg, params, prompts, max_news)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, decode_chunk=4, spec_k=4,
    )
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i]
    assert stats.spec_drafted > 0


def test_spec_draft_capped_by_budget_on_tight_pool(served_model):
    """A near-budget slot must trim its draft so the verify reservation
    never exceeds the blocks_needed(prompt+max_new) worst case admission
    guaranteed: on a pool sized exactly to that worst case, an uncapped
    K=8 draft would demand coverage no preemption can free and the lone
    sequence would self-preempt/resume forever."""
    cfg, params = served_model
    rng = np.random.default_rng(2)
    rep = rng.integers(1, cfg.vocab_size, 6).tolist()
    prompt = rep * 4  # the prompt itself echoes, so drafting fires at once
    total = len(prompt) + 2  # remaining budget 2 -> draft capped to 1
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=1, max_blocks=1 + -(-total // 4),
        prefix_caching=False, decode_chunk=1, spec_k=8,
    )
    engine.add_request("t", prompt, 2)
    for _ in range(64):  # bounded: a reservation livelock must FAIL, not hang
        if not engine.scheduler.has_work or not engine.step():
            break
    else:
        pytest.fail("engine made no progress (speculative reservation livelock)")
    want = _sequential_greedy(cfg, params, [prompt], [2])[0]
    assert engine._results["t"] == want
    assert engine.scheduler.preemptions == 0  # fit without self-preempting


def test_spec_config_gates(served_model):
    """temperature>0 + spec_k is legal (rejection verify); the refusal now
    guards only the pinned exact-match path and the draft-model knob."""
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    # spec_sampled=False pins the exact-match verify: greedy-only.
    with pytest.raises(ValueError, match="temperature"):
        gen.serve(spec_k=4, temperature=0.7, spec_sampled=False)
    # default (spec_sampled=None) auto-selects the rejection verify.
    engine = gen.serve(spec_k=4, temperature=0.7)
    assert engine.cfg.spec_verify_sampled()
    # a draft model without spec_k has nothing to draft for
    with pytest.raises(ValueError, match="spec_k"):
        gen.serve(draft_model="test-tiny")
    with pytest.raises(ValueError, match="decode_chunk"):
        gen.serve(decode_chunk=0)


# ---------------------------------------------------------------------------
# Rejection-sampled speculative decoding (temperature > 0)
# ---------------------------------------------------------------------------


def test_speculative_verify_greedy_is_exact_match():
    """mode='greedy' keeps the old contract exactly: accept the longest
    prefix matching the argmax successors, emit the argmax bonus."""
    from mdi_llm_tpu.ops.sampling import sampling_operands, speculative_verify

    rng = np.random.default_rng(0)
    B, K, V = 2, 3, 16
    logits = jnp.asarray(rng.normal(size=(B, K + 1, V)), jnp.float32)
    g = np.argmax(np.asarray(logits), axis=-1)
    draft = np.stack([
        [g[0, 0], g[0, 1], (g[0, 2] + 1) % V],   # matches 2, diverges at 2
        [(g[1, 0] + 1) % V, g[1, 1], g[1, 2]],   # diverges immediately
    ]).astype(np.int32)
    t_op, p_op = sampling_operands(0.0, None)
    out, n = speculative_verify(
        logits, jnp.asarray(draft), jnp.asarray([3, 3], jnp.int32),
        jax.random.PRNGKey(0), t_op, p_op, mode="greedy",
    )
    out, n = np.asarray(out), np.asarray(n)
    assert list(n) == [3, 1]
    np.testing.assert_array_equal(out[0, :3], g[0, :3])
    assert out[1, 0] == g[1, 0]


@pytest.mark.parametrize("mode,top_k,top_p", [
    ("top_k", None, None),   # plain temperature
    ("top_k", 4, None),      # top-k filter
    ("top_p", None, 0.9),    # nucleus filter
])
def test_speculative_verify_preserves_distribution(mode, top_k, top_p):
    """The tentpole's statistical acceptance pin: at every position the
    verify reaches, the emitted token is marginally a draw from the SAME
    filtered softmax the per-step sampler uses — accepted draft or
    resampled residual, the total law is p (Leviathan/Chen rejection rule
    with a one-hot draft distribution)."""
    from mdi_llm_tpu.ops.sampling import (
        filtered_logits, sampling_operands, speculative_verify)

    rng = np.random.default_rng(42)
    K, V, N = 2, 8, 10000
    logits = jnp.asarray(rng.normal(size=(1, K + 1, V)) * 1.5, jnp.float32)
    # draft each position's argmax so later positions are reached often
    draft = jnp.argmax(logits[:, :K, :], axis=-1).astype(jnp.int32)
    dlen = jnp.asarray([K], jnp.int32)
    t_op, p_op = sampling_operands(0.7, top_p)

    def one(key):
        return speculative_verify(logits, draft, dlen, key, t_op, p_op,
                                  mode=mode, top_k=top_k)

    keys = jax.random.split(jax.random.PRNGKey(7), N)
    outs, nems = jax.jit(jax.vmap(one))(keys)
    outs = np.asarray(outs)[:, 0, :]
    nems = np.asarray(nems)[:, 0]
    f = np.asarray(filtered_logits(logits, t_op, p_op,
                                   mode=mode, top_k=top_k))[0]
    p = np.exp(f - f.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    for j in range(K + 1):
        reach = nems > j
        n_j = int(reach.sum())
        assert n_j > N // 20, f"position {j} starved ({n_j} trials)"
        freq = np.bincount(outs[reach, j], minlength=V) / n_j
        se = np.sqrt(p[j] * (1.0 - p[j]) / n_j)
        assert np.all(np.abs(freq - p[j]) < 5.0 * se + 1e-3), (
            f"pos {j}: emitted law diverges from the filtered softmax\n"
            f"freq={freq}\np   ={p[j]}"
        )
        # filtered-out tokens must never be emitted
        assert np.all(freq[p[j] == 0.0] == 0.0)


@pytest.fixture(scope="module")
def spec_greedy_ref(served_model):
    """One shared greedy reference for the sampled-spec tests: cycling
    prompts (so drafting genuinely fires) and their sequential streams."""
    cfg, params = served_model
    prompts = _cycling_prompts(cfg, (5, 7, 0))
    max_news = [24, 20, 16]
    return prompts, max_news, _sequential_greedy(cfg, params, prompts,
                                                 max_news)


def test_sampled_spec_identity_and_zero_recompiles(served_model,
                                                   spec_greedy_ref):
    """Two acceptance pins in one warm/timed pair: (1) temperature>0 with
    top_k=1 makes every filtered distribution one-hot, so the rejection
    verify must reproduce the greedy stream bit-for-bit while drafting
    and accepting; (2) temperature stays a traced operand through the
    verify, so the post-warmup temperature sweep builds no new executable
    (`prime()` dispatches the draft-hit-gated verify at warmup)."""
    from mdi_llm_tpu.utils.profiling import CompileGuard

    cfg, params = served_model
    prompts, max_news, want = spec_greedy_ref
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    knobs = dict(block_size=4, max_batch=3, decode_chunk=4, spec_k=4,
                 top_k=1)
    guard = CompileGuard(label="spec-temp-sweep")
    with guard:
        engine = gen.serve(temperature=0.7, **knobs)
        assert engine.cfg.spec_verify_sampled()
        engine.prime()
        for i, (p, m) in enumerate(zip(prompts, max_news)):
            engine.add_request(f"r{i}", p, m)
        results, stats = engine.run()
        guard.mark_warm()
        for t in (0.5, 1.3):
            e2 = gen.serve(temperature=t, **knobs)
            for i, p in enumerate(prompts):
                e2.add_request(f"s{i}", p, 10)
            e2.run()
    assert guard.traces_after_warmup == 0
    assert guard.backend_compiles_after_warmup == 0
    guard.expect_clean()
    for i in range(len(prompts)):
        assert results[f"r{i}"] == want[i], f"r{i} diverged under sampled verify"
    assert stats.spec_drafted > 0 and stats.spec_accepted > 0
    assert engine.pool.used == 0


def test_draft_model_serving_token_identical(served_model, spec_greedy_ref):
    """The optional draft model mirrors the target's paged layout in its
    own carved-out pool.  Greedy spec with model drafts stays exactly the
    sequential greedy stream; the sampled verify at top_k=1 (one-hot
    distributions) reproduces it bit-for-bit too, splitting the drafted
    counters by source — and both pools drain after every run."""
    cfg, params = served_model
    dcfg = tiny_config(name="test-tiny-draft", n_layer=1,
                       block_size=cfg.block_size)
    dparams = init_params(dcfg, jax.random.PRNGKey(1))
    prompts, max_news, want = spec_greedy_ref
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    dgen = Generator(dcfg, dparams, cache_dtype=jnp.float32)
    # the sampled pass replays a greedy-PREFIX workload (deterministic
    # argmax streams truncate cleanly), halving its runtime
    sampled_news = [m // 2 for m in max_news]
    for sampling, news in ((dict(), max_news),
                           (dict(temperature=0.7, top_k=1), sampled_news)):
        engine = gen.serve(block_size=4, max_batch=3, decode_chunk=4,
                           spec_k=4, draft_model="test-tiny-draft",
                           draft_gen=dgen, **sampling)
        assert engine.cfg.spec_verify_sampled() == bool(sampling)
        for i, (p, m) in enumerate(zip(prompts, news)):
            engine.add_request(f"r{i}", p, m)
        results, stats = engine.run()
        for i, m in enumerate(news):
            assert results[f"r{i}"] == want[i][:len(prompts[i]) + m], \
                f"r{i} diverged with draft model ({sampling or 'greedy'})"
        assert stats.spec_drafted_model > 0, "draft model never drafted"
        assert stats.spec_drafted == (
            stats.spec_drafted_ngram + stats.spec_drafted_model)
        if news is max_news:  # the short sampled replay may accept none
            assert stats.spec_accepted > 0
        assert engine.pool.used == 0
        assert engine.draft_pool.used == 0, "draft blocks leaked"


@pytest.mark.parametrize("spec_k,chunk", [(4, 4)])
def test_post_warmup_steps_pass_transfer_guard(served_model, spec_k, chunk):
    """Steady-state serving must do only EXPLICIT transfers: a warmed
    engine's steps run clean under ``jax.transfer_guard("disallow")``.
    An implicit host->device transfer here means a step is re-baking a
    host constant per dispatch; an implicit device->host means a hidden
    sync the chunked loop was built to amortize."""
    cfg, params = served_model
    rng = np.random.default_rng(11)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(block_size=4, max_batch=2, decode_chunk=chunk,
                       spec_k=spec_k)
    mk = lambda: rng.integers(1, cfg.vocab_size, 9).tolist()
    engine.add_request("warm0", mk(), 6)
    engine.add_request("warm1", mk(), 6)
    engine.run()  # warmup traces every reachable executable
    engine.add_request("a", mk(), 6)
    engine.add_request("b", mk(), 6)
    with jax.transfer_guard("disallow"):
        while engine.step():
            pass
    assert set(engine._results) >= {"a", "b"}


def test_shared_fn_cache_does_not_pin_dead_engines(served_model):
    """Compiled serving fns live on the Generator (so a warmup engine and
    its timed twin share one jit cache — zero re-traces), but the closures
    must not capture the engine: a pinned engine keeps its ENTIRE paged
    pool alive for the Generator's lifetime."""
    import gc
    import weakref

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    warm = gen.serve(block_size=4, max_batch=2, decode_chunk=4)
    warm.add_request("w", [5, 6, 7], 4)
    warm.run()
    ref = weakref.ref(warm)
    timed = gen.serve(block_size=4, max_batch=2, decode_chunk=4)
    assert timed._fns is warm._fns  # one cache, no re-trace for the twin
    del warm
    gc.collect()
    assert ref() is None, "serving fn cache pinned the dead engine (and pool)"


def test_persistent_table_zeroes_released_slots(served_model):
    """The incrementally-maintained block table must zero a retired slot's
    row before the next dispatch: a stale row would route a dead lane's
    position-0 write into a released (possibly prefix-cached) block."""
    cfg, params = served_model
    rng = np.random.default_rng(3)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, decode_chunk=4
    )
    engine.add_request("a", rng.integers(1, cfg.vocab_size, 9).tolist(), 6)
    engine.add_request("b", rng.integers(1, cfg.vocab_size, 9).tolist(), 14)
    results, _ = engine.run()
    assert set(results) == {"a", "b"}
    # after the run every slot is empty; a fresh sync must be all-trash
    tables = engine._sync_tables([])
    assert not tables.any(), "released slots left stale block ids in the table"


def test_resumed_prefill_registers_only_fed_blocks(served_model):
    """A resumed (preempted) sequence's prefill stops one token short of
    its prompt; with a block-aligned prompt the final block's last slot is
    unwritten at registration time — the prefix cache must NOT publish it
    (a match would let another request attend garbage KV)."""
    cfg, params = served_model
    bs = 4
    prompt = list(range(40, 48))  # exactly 2 blocks of 4
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=bs, max_batch=1
    )
    # inject a preempted entry the way preempt_latest does (mid-prompt
    # preemption: no generated tokens yet, no pending token)
    from mdi_llm_tpu.serving.scheduler import Request

    engine.scheduler.preempted.appendleft(
        (Request("resumed", prompt, 4), list(prompt))
    )
    # drive single steps until the resume-prefill completes, stopping
    # BEFORE the first decode writes the pending position
    for _ in range(50):
        running = engine.scheduler.running()
        if running and not running[0].needs_prefill:
            break
        assert engine.step()
    seq = engine.scheduler.running()[0]
    assert seq.fed == len(prompt) - 1  # resume fed all but the pending token
    # only the fully-written first block may be matchable
    m, n_cached = engine.pool.match_prefix(prompt + [1, 2, 3, 4, 5])
    assert n_cached <= seq.fed // bs * bs == 4
    engine.pool.release(m)
    results, _ = engine.run()
    want = _sequential_greedy(cfg, params, [prompt], [4])[0]
    assert results["resumed"] == want


def test_preemption_picks_latest_admitted_not_highest_slot():
    """Victim selection follows admission recency even after slot churn."""
    from mdi_llm_tpu.serving.scheduler import Request

    pool = KVPool(num_blocks=33, block_size=4)
    sched = Scheduler(pool, max_batch=3, prefill_chunk=8, max_seq_length=64)
    for i in range(3):
        sched.add(Request(f"r{i}", [1, 2, 3], 4))
    sched.admit()
    old_slot2 = sched.slots[2]
    # slot 0 churns: r0 retires, r3 admits into the freed LOWEST slot
    sched.retire(sched.slots[0])
    sched.add(Request("r3", [1, 2, 3], 4))
    sched.admit()
    assert sched.slots[0].req.rid == "r3"
    assert sched.preempt_latest()
    # r3 (newest) was evicted, not the slot-2 veteran
    assert sched.slots[0] is None and sched.slots[2] is old_slot2
    assert sched.preempted[0][0].rid == "r3"


def test_engine_rejects_dp_mesh_at_serve_time(served_model, devices):
    """Tensor-parallel meshes serve (tests/test_tp_serving.py); dp>1 is
    the remaining exclusion and must be named at serve() time."""
    from mdi_llm_tpu.parallel.mesh import make_mesh
    from mdi_llm_tpu.serving.engine import ServingEngine

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"dp": 2}, jax.devices()[:2]))
    with pytest.raises(ValueError, match="dp"):
        gen.serve()
    # direct constructions hit the same wall before the pool allocates
    from mdi_llm_tpu.config import ServingConfig

    with pytest.raises(ValueError, match="dp"):
        ServingEngine(gen, ServingConfig())


@pytest.mark.slow
def test_bench_serving_row_cpu_fallback():
    """The `serving-cb` bench row end-to-end on the CPU backend (through
    run_direct, so the CompileGuard wraps it): must report tokens/s,
    KV-block utilization, tokens_per_sync >= decode_chunk on a loaded
    batch, and ZERO post-warmup recompiles — the acceptance criteria for
    the suite row."""
    import bench

    ap = bench.build_parser()
    args = ap.parse_args(
        ["--direct", "--mode", "serve", "--model", "pythia-14m",
         "--batch", "4", "--seq-len", "128", "--new-tokens", "24",
         "--serve-requests", "8", "--serve-block-size", "8",
         "--serve-chunk", "8"]
    )
    out = bench.run_direct(args)
    assert out["unit"] == "tokens/s/chip"
    assert out["value"] > 0
    d = out["detail"]
    assert d["requests"] == 8
    assert 0.0 < d["kv_block_utilization_peak"] <= 1.0
    assert d["tokens_generated"] > 0
    assert d["host_syncs"] > 0
    assert d["tokens_per_sync"] >= 8, "chunked serving must amortize syncs"
    assert d["compiles"]["traces_after_warmup"] == 0
    assert d["compiles"]["backend_compiles_after_warmup"] == 0
    # the percentile block rides every serve row (the production metrics
    # tokens/s alone hides — docs/observability.md); per-request count ==
    # finished requests, ordering sane
    for name in ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s"):
        blk = d["latency"][name]
        assert blk["count"] == 8, name
        assert blk["p99"] >= blk["p95"] >= blk["p50"] >= 0.0
