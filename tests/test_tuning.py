"""ops/tuning.py: the unified ragged-kernel tuning tables — resolution
precedence (explicit > user table > committed per-device defaults >
conservative), validation/VMEM estimates (the mdi-audit substrate), the
JSON artifact roundtrip, and the mdi-tune CLI itself (CPU interpret
sweep).  The resolution path is pure host computation, so these run
everywhere the package imports.
"""

import json

import pytest

from mdi_llm_tpu.ops.tuning import (
    BUILTIN_TUNING_TABLES,
    DEFAULT_PARAMS,
    TUNE_TABLE_ENV,
    KernelParams,
    autotune,
    candidate_params,
    default_q_pack,
    estimate_kernel_vmem,
    geometry_key,
    load_tuning_table,
    main,
    resolve_kernel_params,
    save_tuning_table,
    validate_kernel_params,
)

GEOM = dict(n_head=4, n_groups=2, head_size=16, block_size=8)


# ---------------------------------------------------------------------------
# resolution precedence
# ---------------------------------------------------------------------------


def test_conservative_default_when_nothing_known():
    params, meta = resolve_kernel_params(**GEOM)
    assert meta == {
        "tuned": False,
        "table_source": "conservative",
        "key": "4h2g16hs/fp/bs8",
    }
    # fully resolved ints: whole-block kv step, auto packing
    assert params.kv_step == 8
    assert params.q_pack == default_q_pack(2, 16) == 2
    assert params.scratch_width == 128


def test_unknown_device_kind_is_never_a_guess():
    params, meta = resolve_kernel_params(**GEOM, device_kind="TPU v9x")
    assert meta["table_source"] == "conservative"
    assert not meta["tuned"]
    assert params == DEFAULT_PARAMS.resolved(8, 2, 16)


@pytest.mark.parametrize(
    "kind,norm",
    [
        ("TPU v4", "v4"),
        ("TPU v5 lite", "v5e"),
        ("TPU v5p", "v5p"),
        ("TPU v6e", "v6e"),
    ],
)
def test_builtin_tables_cover_all_generations(kind, norm):
    params, meta = resolve_kernel_params(**GEOM, device_kind=kind)
    assert meta["table_source"] == f"builtin:{norm}"
    assert meta["tuned"] is False  # committed defaults are not "tuned"
    assert params == KernelParams.from_dict(
        BUILTIN_TUNING_TABLES[norm]["*"]
    ).resolved(8, 2, 16)


def test_user_table_wins_over_builtin(tmp_path, monkeypatch):
    key = geometry_key(4, 2, 16, None, 8)
    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {key: {"kv_step": 4, "q_pack": 1}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    params, meta = resolve_kernel_params(**GEOM, device_kind="TPU v5 lite")
    assert meta["tuned"] is True
    assert meta["table_source"] == f"file:{path}"
    assert (params.kv_step, params.q_pack) == (4, 1)


def test_user_table_misses_fall_through(tmp_path, monkeypatch):
    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {"32h8g64hs/fp/bs16": {"kv_step": 8}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    _, meta = resolve_kernel_params(**GEOM)  # geometry not in the table
    assert meta["tuned"] is False
    assert meta["table_source"] == "conservative"


def test_explicit_params_beat_everything(tmp_path, monkeypatch):
    key = geometry_key(4, 2, 16, None, 8)
    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {key: {"kv_step": 4}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    params, meta = resolve_kernel_params(
        **GEOM, params=KernelParams(kv_step=2, q_pack=1, scratch_width=64)
    )
    assert meta["table_source"] == "explicit"
    assert (params.kv_step, params.q_pack, params.scratch_width) == (2, 1, 64)


def test_kv_dtype_keys_separate_rows(tmp_path):
    key8 = geometry_key(4, 2, 16, "int8", 8)
    assert key8 == "4h2g16hs/int8/bs8"
    path = tmp_path / "t.json"
    save_tuning_table(str(path), None, {key8: {"kv_step": 4}})
    p8, m8 = resolve_kernel_params(**GEOM, kv_dtype="int8",
                                   table_path=str(path))
    pf, mf = resolve_kernel_params(**GEOM, table_path=str(path))
    assert m8["tuned"] and p8.kv_step == 4
    assert not mf["tuned"] and pf.kv_step == 8  # fp row absent


def test_bad_table_path_is_loud(tmp_path):
    with pytest.raises(OSError):
        resolve_kernel_params(
            **GEOM, table_path=str(tmp_path / "missing.json")
        )


# ---------------------------------------------------------------------------
# resolution helpers / validation / VMEM estimate
# ---------------------------------------------------------------------------


def test_default_q_pack_geometry_table():
    assert default_q_pack(4, 32) == 4   # pythia-14m: 4*32 = 128 exactly
    assert default_q_pack(4, 64) == 2   # tiny-llama: 2*64 = 128
    assert default_q_pack(1, 64) == 1   # MQA cannot pack
    assert default_q_pack(8, 128) == 1  # full lane already
    assert default_q_pack(8, 16) == 8


def test_validate_catches_each_problem():
    ok = KernelParams(kv_step=8, q_pack=2, scratch_width=128)
    assert validate_kernel_params(ok, 16, 4, 32) == []
    bad_kv = validate_kernel_params(
        KernelParams(kv_step=5, q_pack=1, scratch_width=128), 16, 4, 32
    )
    assert len(bad_kv) == 1 and "kv_step=5" in bad_kv[0]
    bad_qp = validate_kernel_params(
        KernelParams(kv_step=8, q_pack=3, scratch_width=128), 16, 4, 32
    )
    assert len(bad_qp) == 1 and "q_pack=3" in bad_qp[0]
    bad_sw = validate_kernel_params(
        KernelParams(kv_step=8, q_pack=1, scratch_width=0), 16, 4, 32
    )
    assert len(bad_sw) == 1 and "scratch_width=0" in bad_sw[0]


def test_vmem_estimate_scales_with_knobs():
    base = estimate_kernel_vmem(
        4, 2, 16, 64, 8, KernelParams(kv_step=8, q_pack=2, scratch_width=128)
    )
    wider = estimate_kernel_vmem(
        4, 2, 16, 64, 8, KernelParams(kv_step=8, q_pack=2, scratch_width=512)
    )
    assert wider > base  # scratch width is paid in VMEM
    int8 = estimate_kernel_vmem(
        4, 2, 16, 64, 8,
        KernelParams(kv_step=8, q_pack=2, scratch_width=128),
        kv_dtype="int8",
    )
    assert int8 < base  # 1-byte KV sub-blocks (scales cost less than payload)
    assert base > 0


def test_candidate_grid_shape():
    cands = candidate_params(block_size=16, n_groups=4, head_size=32)
    kv_steps = {c.kv_step for c in cands}
    q_packs = {c.q_pack for c in cands}
    assert kv_steps == {8, 16}          # divisors >= 8 (or the full block)
    assert q_packs == {1, 2, 4}         # divisors of G fitting a lane tile
    assert all(c.scratch_width == 128 for c in cands)


# ---------------------------------------------------------------------------
# artifact roundtrip
# ---------------------------------------------------------------------------


def test_save_load_roundtrip(tmp_path):
    path = tmp_path / "table.json"
    entries = {"4h2g16hs/fp/bs8": {"kv_step": 4, "q_pack": 2,
                                   "scratch_width": 128}}
    save_tuning_table(str(path), "v6e", entries,
                      timings_us={"4h2g16hs/fp/bs8": [{"us": 1.0}]})
    table = load_tuning_table(str(path))
    assert table["device_kind"] == "v6e"
    assert table["entries"] == entries
    assert "timings_us" in table


def test_load_bare_mapping(tmp_path):
    path = tmp_path / "bare.json"
    path.write_text(json.dumps({"*": {"kv_step": 8}}))
    table = load_tuning_table(str(path))
    assert table["entries"] == {"*": {"kv_step": 8}}
    assert table["device_kind"] is None


# ---------------------------------------------------------------------------
# the sweep + CLI (CPU interpret: exercises every candidate, ranks nothing)
# ---------------------------------------------------------------------------


def test_autotune_smoke_interpret():
    best, results = autotune(
        n_head=4, n_groups=2, head_size=8, block_size=8, max_blocks=2,
        n_tokens=8, n_slots=2, reps=1,
    )
    assert len(results) == len(candidate_params(8, 2, 8))
    assert best.to_dict() in [r["params"] for r in results]
    assert all(r["us"] > 0 for r in results)


def test_cli_writes_artifact_resolvable_by_serving(tmp_path, capsys):
    out = tmp_path / "tuned.json"
    rc = main([
        "--n-head", "4", "--n-kv-heads", "2", "--head-size", "8",
        "--block-size", "8", "--tokens", "8", "--slots", "2",
        "--max-blocks", "2", "--reps", "1", "--out", str(out),
    ])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "<-- best" in printed and "wrote" in printed
    table = load_tuning_table(str(out))
    key = geometry_key(4, 2, 8, None, 8)
    assert key in table["entries"]
    # the artifact feeds straight back into resolution as a user table
    params, meta = resolve_kernel_params(
        n_head=4, n_groups=2, head_size=8, block_size=8,
        table_path=str(out),
    )
    assert meta["tuned"] and meta["table_source"] == f"file:{out}"
    assert validate_kernel_params(params, 8, 2, 8) == []


def test_cli_model_name_and_missing_geometry():
    with pytest.raises(SystemExit):  # no model, incomplete geometry
        main(["--n-head", "4"])


def test_cli_help_covers_tuning_flags(capsys):
    with pytest.raises(SystemExit):
        main(["--help"])
    help_text = capsys.readouterr().out
    for flag in ("--model", "--n-head", "--n-kv-heads", "--head-size",
                 "--block-size", "--kv-dtype", "--tokens", "--slots",
                 "--reps", "--out", "--interpret"):
        assert flag in help_text, f"{flag} missing from mdi-tune --help"
    assert "MDI_TUNE_TABLE" in help_text


# ---------------------------------------------------------------------------
# candidate preflight (bad-kernel-tuning BEFORE timing) + serve-trace cases
# ---------------------------------------------------------------------------


def test_autotune_rejects_invalid_candidate_before_timing():
    from mdi_llm_tpu.ops.tuning import SERVE_TRACE_CASES  # noqa: F401

    bad = KernelParams(kv_step=3, q_pack=1, scratch_width=128)
    good = KernelParams(kv_step=8, q_pack=1, scratch_width=128)
    best, results = autotune(
        n_head=4, n_groups=2, head_size=8, block_size=8, max_blocks=2,
        n_tokens=8, n_slots=2, reps=1, candidates=[bad, good],
    )
    # every candidate keeps a row (the artifact records WHY one is absent)
    assert len(results) == 2
    rej = [r for r in results if "rejected" in r]
    assert len(rej) == 1
    assert rej[0]["params"]["kv_step"] == 3
    assert "divisor" in rej[0]["rejected"]
    assert "us" not in rej[0]  # never timed
    assert best == KernelParams.from_dict(good.to_dict())


def test_autotune_all_rejected_raises():
    bad = KernelParams(kv_step=3, q_pack=1, scratch_width=128)
    with pytest.raises(ValueError, match="bad-kernel-tuning"):
        autotune(
            n_head=4, n_groups=2, head_size=8, block_size=8, max_blocks=2,
            n_tokens=8, n_slots=2, reps=1, candidates=[bad],
        )


def test_autotune_rejected_rows_persist_in_artifact(tmp_path):
    bad = KernelParams(kv_step=3, q_pack=1, scratch_width=128)
    good = KernelParams(kv_step=8, q_pack=1, scratch_width=128)
    _, results = autotune(
        n_head=4, n_groups=2, head_size=8, block_size=8, max_blocks=2,
        n_tokens=8, n_slots=2, reps=1, candidates=[bad, good],
    )
    out = tmp_path / "tuned.json"
    key = geometry_key(4, 2, 8, None, 8)
    save_tuning_table(str(out), "cpu", {key: good.to_dict()},
                      timings_us={key: results})
    table = json.loads(out.read_text())
    rows = table["timings_us"][key]
    assert any("rejected" in r for r in rows)


def test_autotune_multi_case_sums_timings():
    cases = [
        {"n_tokens": 8, "n_slots": 2, "max_blocks": 2},
        {"n_tokens": 10, "n_slots": 2, "max_blocks": 2},
    ]
    good = KernelParams(kv_step=8, q_pack=1, scratch_width=128)
    _, results = autotune(
        n_head=4, n_groups=2, head_size=8, block_size=8, max_blocks=2,
        reps=1, candidates=[good], cases=cases,
    )
    assert len(results) == 1 and results[0]["us"] > 0


def test_serve_trace_cases_cover_token_budget_geometry():
    from mdi_llm_tpu.ops.tuning import SERVE_TRACE_CASES

    # the default ServingConfig packs max_batch(8)+prefill_chunk(128)
    # tokens; the span must fit the case's block window
    geo = {(c["n_tokens"], c["n_slots"]) for c in SERVE_TRACE_CASES}
    assert (136, 8) in geo and (8, 8) in geo
    for c in SERVE_TRACE_CASES:
        assert c["n_tokens"] - (c["n_slots"] - 1) <= c["max_blocks"] * 16
