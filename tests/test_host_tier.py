"""Host-RAM KV tier tests (serving/host_tier.py + the engine/scheduler/
analysis wiring behind `ServingConfig.host_pool_mib`).

Four layers under test:

1. the pure host-side pieces — `HostBlockStore` slab round-trips are
   bit-exact (fp and int8 payload+scale layouts), allocation is
   all-or-nothing, `SwapCostModel` decisions are deterministic under a
   fake clock/BW and EWMA-correct toward measurements, and `HostTier`
   capacity lets swaps evict spilled prefix blocks but never the
   reverse;
2. the engine device paths — swap-out gather / restore scatter
   round-trip a victim's blocks byte-identically (fp32, int8, tp=2),
   a preemption-heavy trace resolved by SWAP stays greedy
   token-identical to sequential `generate` (the same contract the
   recompute path ships under), a spilled prefix chain restores from
   host and counts `prefix_hits_host`, and the steady state stays
   clean under `jax.transfer_guard("disallow")` with zero post-warmup
   recompiles;
3. the scheduler seam — swap records ride preempted entries, a
   swapped resume re-enters with ZERO re-prefill, and the cancel path
   releases host slots through `drop_swap_record`;
4. the analysis/CLI surface — mdi-audit's `bad-host-tier` fixture
   pairs, the byte-exact `host_pool_bytes` contract against the live
   slabs, mdi-flow's hbm-over-budget host credit (both directions),
   and `--host-pool-mib` on every entry point's --help.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.analysis.audit import preflight
from mdi_llm_tpu.analysis.ir import trace_serving
from mdi_llm_tpu.analysis.liveness import flow_preflight
from mdi_llm_tpu.config import Config, ServingConfig
from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.serving.host_tier import (
    DEFAULT_HOST_LINK_GBPS,
    HOST_LINK_GBPS,
    HostBlockStore,
    HostTier,
    SwapCostModel,
    SwapRecord,
    lookup_host_link_gbps,
)
from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.scheduler import Request, Scheduler
from mdi_llm_tpu.utils.profiling import CompileGuard
from tests.test_model import tiny_config
from tests.test_serving import _sequential_greedy


@pytest.fixture(scope="module")
def served_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# ---------------------------------------------------------------------------
# HostBlockStore
# ---------------------------------------------------------------------------


def _fill(rng, shape, dtype):
    dt = np.dtype(dtype)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return rng.integers(info.min, info.max, size=shape, dtype=dt,
                            endpoint=True)
    return rng.standard_normal(shape).astype(dt)


def test_store_roundtrip_is_bit_exact_fp():
    # two leaves mirroring the flat pool's k/v with blocks on axis 1
    shapes = [((2, 6, 4, 3, 5), np.float32), ((2, 6, 4, 3, 5), np.float16)]
    store = HostBlockStore(shapes, block_axis=1, num_slots=4)
    rng = np.random.default_rng(0)
    slots = store.alloc(3)
    assert slots is not None and len(slots) == 3
    # write takes block-axis-LEADING payloads: row k is block k
    payload = [_fill(rng, (3, 2, 4, 3, 5), d) for _, d in shapes]
    store.write(slots, payload)
    back = store.read(slots)
    for want, got, (_, d) in zip(payload, back, shapes):
        assert got.dtype == np.dtype(d)
        assert np.array_equal(
            want.view(np.uint8), got.view(np.uint8)
        ), "host slab round-trip must be bit-exact"
    # reads are copies: mutating the result must not touch the slabs
    back[0][...] = 0
    again = store.read(slots)
    assert np.array_equal(payload[0], again[0])


def test_store_roundtrip_is_bit_exact_int8_payload_and_scale():
    # int8 pool layout: quantized payload + f32 scales (no block-size axis)
    shapes = [((2, 5, 4, 3), np.int8), ((2, 5, 3), np.float32)]
    store = HostBlockStore(shapes, block_axis=1, num_slots=5)
    rng = np.random.default_rng(1)
    slots = store.alloc(2)
    payload = [_fill(rng, (2, 2, 4, 3), np.int8),
               _fill(rng, (2, 2, 3), np.float32)]
    store.write(slots, payload)
    for want, got in zip(payload, store.read(slots)):
        assert np.array_equal(want, got) and want.dtype == got.dtype


def test_store_write_drops_transfer_padding_rows():
    shapes = [((1, 4, 2), np.float32)]
    store = HostBlockStore(shapes, block_axis=1, num_slots=4)
    rng = np.random.default_rng(2)
    slots = store.alloc(2)
    # fixed-width transfer quantum: rows past len(slots) are padding
    padded = _fill(rng, (4, 1, 2), np.float32)
    store.write(slots, [padded])
    assert np.array_equal(store.read(slots)[0], padded[:2])


def test_store_alloc_all_or_nothing_and_recycles():
    store = HostBlockStore([((1, 3, 2), np.float32)], 1, num_slots=3)
    assert store.available == 3 and store.nbytes == 3 * 2 * 4
    a = store.alloc(2)
    assert a is not None and store.used == 2
    assert store.alloc(2) is None, "partial grabs must not happen"
    assert store.used == 2  # the failed alloc changed nothing
    b = store.alloc(1)
    assert b is not None and store.available == 0
    store.release(a)
    c = store.alloc(2)
    assert c is not None and set(c) == set(a), "slots actually recycled"


def test_store_nbytes_is_slots_times_block_bytes(served_model):
    """The byte contract mdi-audit pins: a live engine's slabs hold
    exactly `num_host_blocks x block_bytes(tp=1)` bytes."""
    cfg, params = served_model
    sv = ServingConfig(block_size=4, host_pool_mib=4)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, host_pool_mib=4
    )
    per_block = sv.block_bytes(cfg, "float32", tp=1)["total_bytes"]
    assert engine.host_tier.store.num_slots == sv.num_host_blocks(
        cfg, "float32"
    )
    assert engine.host_tier.store.nbytes == engine.host_tier.store.num_slots * per_block
    assert engine.host_tier.store.nbytes == sv.host_pool_bytes(cfg, "float32")


# ---------------------------------------------------------------------------
# SwapCostModel
# ---------------------------------------------------------------------------


def test_cost_model_swap_vs_recompute_boundary():
    cm = SwapCostModel(link_gbps=8.0, prefill_tokens_per_s=2000.0)
    assert cm.swap_seconds(8_000_000_000) == pytest.approx(1.0)
    assert cm.recompute_seconds(2000) == pytest.approx(1.0)
    # round trip (2x swap) vs re-prefill: 2.0s vs recompute_seconds
    nbytes = 8_000_000_000  # 1.0s one way -> 2.0s round trip
    assert cm.should_swap(nbytes, 4001) is True  # 2.0005s recompute
    assert cm.should_swap(nbytes, 4000) is False  # exactly equal: no win
    assert cm.should_swap(nbytes, 3999) is False


def test_cost_model_zero_bandwidth_never_swaps():
    cm = SwapCostModel(link_gbps=0.0)
    assert cm.swap_seconds(1) == float("inf")
    assert cm.should_swap(1, 10**9) is False


def test_cost_model_ewma_tracks_measurements():
    cm = SwapCostModel(link_gbps=8.0, prefill_tokens_per_s=2000.0, ewma=0.25)
    cm.observe_transfer(16_000_000_000, 1.0)  # measured 16 GB/s
    assert cm.link_gbps == pytest.approx(8.0 + 0.25 * (16.0 - 8.0))
    cm.observe_prefill(4000, 1.0)  # measured 4000 tok/s
    assert cm.prefill_tokens_per_s == pytest.approx(2000 + 0.25 * 2000)
    before = (cm.link_gbps, cm.prefill_tokens_per_s)
    cm.observe_transfer(0, 1.0)  # degenerate measurements are ignored
    cm.observe_prefill(100, 0.0)
    assert (cm.link_gbps, cm.prefill_tokens_per_s) == before


def test_link_bandwidth_table_lookup():
    assert lookup_host_link_gbps("TPU v4") == HOST_LINK_GBPS["TPU v4"]
    # longest prefix wins: "TPU v5 lite" over "TPU v5"
    assert lookup_host_link_gbps("TPU v5 lite") == HOST_LINK_GBPS["TPU v5 lite"]
    assert lookup_host_link_gbps("TPU v5p") == HOST_LINK_GBPS["TPU v5p"]
    assert lookup_host_link_gbps("cpu") == DEFAULT_HOST_LINK_GBPS
    assert lookup_host_link_gbps(None) == DEFAULT_HOST_LINK_GBPS
    sv = ServingConfig(host_link_gbps=3.5)
    assert sv.resolved_host_link_gbps("TPU v4") == 3.5  # explicit wins


# ---------------------------------------------------------------------------
# HostTier capacity: state (swaps) beats cache (spills)
# ---------------------------------------------------------------------------


def _tier(num_slots):
    store = HostBlockStore([((1, 3, 2), np.float32)], 1, num_slots)
    return HostTier(store, SwapCostModel(link_gbps=8.0), prefix_spill=True)


def test_swap_alloc_evicts_spilled_blocks_lru():
    tier = _tier(3)
    for h in (101, 102, 103):
        slot = tier.alloc_for_spill()
        tier.record_spill(h, slot)
    assert len(tier.spilled) == 3 and tier.store.available == 0
    got = tier.alloc_for_swap(2)
    assert got is not None and len(got) == 2
    # oldest spills evicted first; the newest survives
    assert list(tier.spilled) == [103]


def test_spill_alloc_never_displaces_swap_slots():
    tier = _tier(2)
    swap = tier.alloc_for_swap(2)
    assert swap is not None and tier.store.available == 0
    # nothing spilled to recycle and no free slot: the spill is refused
    assert tier.alloc_for_spill() is None
    assert tier.store.used == 2  # the swap slots are untouched
    # with one spilled block present, spills recycle ONLY among spills
    tier.store.release([swap.pop()])
    s = tier.alloc_for_spill()
    tier.record_spill(7, s)
    s2 = tier.alloc_for_spill()
    assert s2 == s and tier.take_spill(7) is None  # recycled the spill


def test_tier_snapshot_keys():
    tier = _tier(2)
    snap = tier.snapshot()
    assert snap["host_blocks"] == 2 and snap["host_pool_bytes"] == tier.store.nbytes
    for k in ("host_used_blocks", "host_spilled_blocks", "swaps_out",
              "swaps_in", "swap_out_bytes", "swap_in_bytes"):
        assert snap[k] == 0


# ---------------------------------------------------------------------------
# engine: swap-out gather / restore scatter round-trip (byte parity)
# ---------------------------------------------------------------------------


def _block_payload(engine, blocks):
    """Per-leaf host copies of `blocks`, block axis leading (the store's
    layout) — the reference the swap round-trip must reproduce."""
    ba = engine._kv_block_axis
    out = []
    for leaf in jax.tree_util.tree_leaves(engine._kv):
        arr = np.asarray(leaf)  # mdi-lint: disable=host-sync -- test readback
        out.append(np.moveaxis(np.take(arr, blocks, axis=ba), ba, 0))
    return out


def _drive_until_decoding(engine, min_fed):
    for _ in range(200):
        running = engine.scheduler.running()
        if running and running[0].fed >= min_fed:
            return running[0]
        assert engine.step(), "engine went idle before the target fed"
    raise AssertionError("never reached the target fed position")


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_swap_roundtrip_byte_parity(served_model, kv_dtype):
    """Gather a victim's blocks to host slots, restore them into FRESH
    blocks: the restored device bytes equal the originals exactly (fp32
    and the int8 payload+scale layout)."""
    cfg, params = served_model
    rng = np.random.default_rng(3)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=1, max_blocks=1 + 30, prefix_caching=False,
        host_pool_mib=64, host_link_gbps=1000.0, kv_dtype=kv_dtype,
    )
    engine.add_request("r", rng.integers(1, cfg.vocab_size, 13).tolist(), 8)
    seq = _drive_until_decoding(engine, min_fed=9)
    n_blocks = engine.pool.blocks_needed(seq.fed)
    victim_blocks = list(seq.blocks[:n_blocks])
    want = _block_payload(engine, victim_blocks)

    record = engine._swap_out(seq)
    assert record is not None and len(record.slots) == n_blocks
    engine._drain_swaps()
    for w, h in zip(want, engine.host_tier.store.read(record.slots)):
        assert w.dtype == h.dtype
        assert np.array_equal(w.view(np.uint8), h.view(np.uint8))

    fresh = engine.pool.alloc(n_blocks)
    assert fresh is not None and set(fresh).isdisjoint(victim_blocks)
    engine._swap_in(record, fresh)
    for w, g in zip(want, _block_payload(engine, fresh)):
        assert np.array_equal(w.view(np.uint8), g.view(np.uint8)), (
            "host->HBM restore must be byte-identical"
        )
    assert engine.host_tier.swaps_in == 1
    assert engine.host_tier.store.used == 0  # slots released after restore


def test_swap_roundtrip_byte_parity_tp2(served_model):
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices")
    cfg, params = served_model
    rng = np.random.default_rng(4)
    engine = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    ).serve(block_size=4, max_batch=1, max_blocks=1 + 30,
            prefix_caching=False, host_pool_mib=64, host_link_gbps=1000.0)
    engine.add_request("r", rng.integers(1, cfg.vocab_size, 13).tolist(), 8)
    seq = _drive_until_decoding(engine, min_fed=9)
    n_blocks = engine.pool.blocks_needed(seq.fed)
    want = _block_payload(engine, list(seq.blocks[:n_blocks]))
    record = engine._swap_out(seq)
    assert record is not None
    engine._drain_swaps()
    fresh = engine.pool.alloc(n_blocks)
    engine._swap_in(record, fresh)
    for w, g in zip(want, _block_payload(engine, fresh)):
        # the store keeps GLOBAL (unsharded) blocks: tp round-trips whole
        assert np.array_equal(w.view(np.uint8), g.view(np.uint8))


# ---------------------------------------------------------------------------
# engine: swap preemption keeps the greedy parity contract
# ---------------------------------------------------------------------------

_PREEMPT_KNOBS = dict(block_size=4, max_batch=3, max_blocks=1 + 14,
                      prefix_caching=False, decode_chunk=1)


def _preempt_prompts(cfg):
    rng = np.random.default_rng(9)
    return [rng.integers(1, cfg.vocab_size, int(n)).tolist()
            for n in (9, 13, 11)]


def test_swap_preemption_matches_sequential_generate(served_model):
    """The acceptance contract, swap edition: the same pool-starved trace
    that forces recompute preemption, resolved by SWAP instead — outputs
    stay token-identical to solo `generate()` runs, with zero re-prefill
    hiding behind the parity (a wrong restored byte WOULD diverge)."""
    cfg, params = served_model
    prompts = _preempt_prompts(cfg)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        host_pool_mib=64, host_link_gbps=1000.0, **_PREEMPT_KNOBS
    )
    for i, p in enumerate(prompts):
        engine.add_request(f"p{i}", p, 10)
    results, stats = engine.run()
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    assert stats.swaps_out >= 1, "the 1000 GB/s link must choose swap"
    assert stats.swaps_in == stats.swaps_out
    assert stats.swap_out_bytes > 0 and stats.swap_in_bytes > 0
    want = _sequential_greedy(cfg, params, prompts, [10, 10, 10])
    for i in range(len(prompts)):
        assert results[f"p{i}"] == want[i], f"p{i} diverged across its swap"


def test_int8_swap_matches_int8_recompute(served_model):
    """int8 quantization shifts tokens vs fp, so the int8 swap engine is
    held to its int8 recompute twin: byte-identical restores mean the
    two resolutions of the same preemption cannot differ."""
    cfg, params = served_model
    prompts = _preempt_prompts(cfg)

    def run(host_mib):
        engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
            kv_dtype="int8", host_pool_mib=host_mib,
            host_link_gbps=1000.0, **_PREEMPT_KNOBS
        )
        for i, p in enumerate(prompts):
            engine.add_request(f"p{i}", p, 10)
        return engine.run()

    recompute, rstats = run(0)
    swapped, sstats = run(64)
    assert rstats.preemptions >= 1 and rstats.swaps_out == 0
    assert sstats.swaps_out >= 1
    assert swapped == recompute


# ---------------------------------------------------------------------------
# engine: spillable prefix cache
# ---------------------------------------------------------------------------


def test_prefix_spill_restores_evicted_chain(served_model):
    """Serial A/B/C trace: A registers a prefix chain, B's footprint
    evicts it (spilling to host), C re-uses the prefix — the hit restores
    from host (`prefix_hits_host`) and C's tokens match the no-tier run
    (which recomputes the evicted prefix from scratch)."""
    cfg, params = served_model
    rng = np.random.default_rng(6)
    shared = rng.integers(1, cfg.vocab_size, 16).tolist()
    reqs = [
        ("a", shared + rng.integers(1, cfg.vocab_size, 4).tolist(), 4),
        ("b", rng.integers(1, cfg.vocab_size, 24).tolist(), 4),
        ("c", shared + rng.integers(1, cfg.vocab_size, 4).tolist(), 4),
    ]

    def run(host_mib):
        engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
            block_size=4, max_batch=1, max_blocks=1 + 7,
            prefix_caching=True, host_pool_mib=host_mib,
            host_link_gbps=1000.0,
        )
        for rid, p, m in reqs:
            engine.add_request(rid, p, m)
        return engine.run()

    plain, _ = run(0)
    tiered, stats = run(64)
    assert stats.prefix_hits_host >= 1, "the evicted chain must hit on host"
    assert tiered == plain, "a host-restored prefix changed the tokens"


# ---------------------------------------------------------------------------
# engine: steady-state compile/transfer contract
# ---------------------------------------------------------------------------


def test_tier_steady_state_is_recompile_and_transfer_clean(served_model):
    """A warmed tiered engine keeps serving — with live swaps — under
    `jax.transfer_guard("disallow")` and with ZERO post-warmup retraces:
    every tier transfer is an explicit host-boundary op and the
    fixed-width fetch/restore executables cover any victim size."""
    cfg, params = served_model
    rng = np.random.default_rng(9)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(host_pool_mib=64, host_link_gbps=1000.0,
                       **_PREEMPT_KNOBS)
    mk = lambda n: rng.integers(1, cfg.vocab_size, int(n)).tolist()
    g = CompileGuard(label="tier")
    with g:
        for i, n in enumerate((9, 13, 11)):
            engine.add_request(f"w{i}", mk(n), 10)
        engine.run()  # warmup traces every reachable executable
        warm_swaps = engine.scheduler.swaps_out
        assert warm_swaps >= 1, "warmup trace must exercise the swap path"
        g.mark_warm()
        for i, n in enumerate((9, 13, 11)):
            engine.add_request(f"t{i}", mk(n), 10)
        with jax.transfer_guard("disallow"):
            while engine.step():
                pass
    assert engine.scheduler.swaps_out > warm_swaps, (
        "steady state must have swapped under the guard"
    )
    assert g.traces_after_warmup == 0
    g.expect_clean()


# ---------------------------------------------------------------------------
# scheduler seam: swap records, zero re-prefill resume, cancel drop
# ---------------------------------------------------------------------------


def _scheduler(num_blocks=17, block_size=4, max_batch=1):
    pool = KVPool(num_blocks=num_blocks, block_size=block_size)
    return Scheduler(pool, max_batch=max_batch, prefill_chunk=8,
                     max_seq_length=128), pool


def test_scheduler_swapped_resume_has_zero_reprefill():
    sched, pool = _scheduler()
    calls = {}
    record = SwapRecord(slots=[5, 6, 7], n_tokens=11, nbytes=99)
    sched.swap_out_hook = lambda seq: record
    sched.swap_in_hook = lambda rec, blocks: calls.update(
        rec=rec, blocks=list(blocks)
    )
    sched.add(Request("r0", list(range(1, 12)), 8))  # 11-token prompt
    sched.admit()
    seq = sched.running()[0]
    seq.fed = 11  # fully prefilled, mid-decode
    seq.next_tok = 77  # sampled, pending
    assert sched.preempt_latest()
    assert sched.swaps_out == 1 and "r0" in sched.swap_records
    assert sched.running() == [] and pool.used == 0

    resumed = sched.admit()
    assert len(resumed) == 1
    seq2 = resumed[0]
    # the restore covered every fed token: NO re-prefill, the pending
    # token is restored immediately and the lane is decode-ready
    assert calls["rec"] is record
    assert len(calls["blocks"]) == pool.blocks_needed(record.n_tokens)
    assert seq2.n_cached == record.n_tokens and seq2.fed == record.n_tokens
    assert not seq2.needs_prefill
    assert seq2.next_tok == 77
    assert sched.swaps_in == 1 and "r0" not in sched.swap_records


def test_scheduler_recompute_fallback_when_hook_declines():
    sched, pool = _scheduler()
    sched.swap_out_hook = lambda seq: None  # cost model said recompute
    sched.add(Request("r0", [1, 2, 3, 4, 5], 4))
    sched.admit()
    sched.running()[0].fed = 5
    assert sched.preempt_latest()
    assert sched.swaps_out == 0 and sched.swap_records == {}
    seq = sched.admit()[0]
    assert seq.needs_prefill, "recompute resumes re-prefill their tokens"


def test_scheduler_drop_swap_record_releases_host_slots():
    sched, _ = _scheduler()
    dropped = []
    record = SwapRecord(slots=[3, 4], n_tokens=8, nbytes=10)
    sched.swap_out_hook = lambda seq: record
    sched.swap_drop_hook = dropped.append
    sched.add(Request("r0", list(range(1, 10)), 4))
    sched.admit()
    sched.running()[0].fed = 9
    sched.preempt_latest()
    # the frontend's cancel path: remove from the queue, then drop
    sched.preempted.clear()
    sched.drop_swap_record("r0")
    assert dropped == [record] and sched.swap_records == {}
    sched.drop_swap_record("never-swapped")  # unknown rid: no-op
    assert dropped == [record]


# ---------------------------------------------------------------------------
# mdi-audit: bad-host-tier fixture pairs + the byte-exact breakdown
# ---------------------------------------------------------------------------


def _codes(report):
    return [f.rule for f in report.findings]


def test_audit_flags_host_tier_over_budget():
    r = preflight(Config.from_name("pythia-14m"),
                  serving=ServingConfig(host_pool_mib=2048),
                  host_gb=0.25)
    assert _codes(r).count("bad-host-tier") == 1


def test_audit_flags_spill_without_prefix_caching():
    r = preflight(Config.from_name("pythia-14m"),
                  serving=ServingConfig(host_pool_mib=64,
                                        prefix_caching=False,
                                        host_prefix_spill=True))
    assert _codes(r).count("bad-host-tier") == 1


def test_audit_flags_zero_bandwidth_link():
    r = preflight(Config.from_name("pythia-14m"),
                  serving=ServingConfig(host_pool_mib=64,
                                        host_link_gbps=0.0))
    assert _codes(r).count("bad-host-tier") == 1


def test_audit_good_tier_plan_is_clean():
    r = preflight(Config.from_name("pythia-14m"),
                  serving=ServingConfig(host_pool_mib=64), host_gb=1.0)
    assert "bad-host-tier" not in _codes(r)
    # tier off: the checker (and the breakdown bytes) stay zero
    r0 = preflight(Config.from_name("pythia-14m"),
                   serving=ServingConfig(), host_gb=0.0)
    assert "bad-host-tier" not in _codes(r0)
    assert r0.breakdown["kv_pool"]["host_pool_bytes"] == 0


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_audit_host_pool_bytes_matches_live_slabs(served_model, kv_dtype):
    """`kv_pool.host_pool_bytes` in the audit breakdown equals the LIVE
    `HostBlockStore.nbytes` exactly — the static estimate and the pinned
    allocation can never drift (fp32 and int8 payload+scale layouts)."""
    cfg, params = served_model
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, host_pool_mib=4, kv_dtype=kv_dtype
    )
    r = preflight(
        cfg, cache_dtype="float32",
        serving=ServingConfig(block_size=4, host_pool_mib=4,
                              kv_dtype=kv_dtype),
    )
    pool = r.breakdown["kv_pool"]
    assert pool["host_pool_bytes"] == engine.host_tier.store.nbytes
    assert pool["host_blocks"] == engine.host_tier.store.num_slots


# ---------------------------------------------------------------------------
# mdi-flow: the hbm-over-budget host credit, both directions
# ---------------------------------------------------------------------------


def test_flow_budget_credits_swapped_blocks_both_directions():
    """A budget chosen between the tiered and untiered high-waters: the
    untiered engine trips hbm-over-budget, a sufficient tier passes, and
    a too-small tier still trips — the credit is sized, not a waiver."""
    cfg = Config.from_name("pythia-14m")

    def report(host_mib, hbm_gb):
        eng = trace_serving(cfg, ServingConfig(host_pool_mib=host_mib),
                            max_seq_length=256)
        return flow_preflight(eng, origin="t", hbm_gb=hbm_gb)

    d0 = report(0, 64.0).breakdown["per_device"]
    dT = report(64, 64.0).breakdown["per_device"]
    dS = report(1, 64.0).breakdown["per_device"]
    assert d0["host_credit_bytes"] == 0 and dT["host_credit_bytes"] > 0
    assert dT["high_water_bytes"] == (
        d0["high_water_bytes"] - dT["host_credit_bytes"]
    )
    assert dT["high_water_bytes"] < dS["high_water_bytes"] < d0["high_water_bytes"]

    mid_gb = (dS["high_water_bytes"] + dT["high_water_bytes"]) / 2 / 2**30
    assert "hbm-over-budget" in [f.rule for f in report(0, mid_gb).findings]
    assert "hbm-over-budget" in [f.rule for f in report(1, mid_gb).findings]
    assert "hbm-over-budget" not in [
        f.rule for f in report(64, mid_gb).findings
    ]


# ---------------------------------------------------------------------------
# CLI surface: the tier knobs exist on every entry point
# ---------------------------------------------------------------------------


def test_cli_help_covers_host_tier_flags():
    from bench import build_parser as bench_parser
    from mdi_llm_tpu.analysis.audit import build_parser as audit_parser
    from mdi_llm_tpu.analysis.check import build_parser as check_parser
    from mdi_llm_tpu.cli.serve import build_parser as serve_parser
    from mdi_llm_tpu.cli.server import build_parser as server_parser

    for parser in (serve_parser(), server_parser()):
        help_text = parser.format_help()
        assert "--host-pool-mib" in help_text
        assert "--host-link-gbps" in help_text
    for parser in (audit_parser(), check_parser()):
        help_text = parser.format_help()
        assert "--host-pool-mib" in help_text
        assert "--host-gb" in help_text
    bench_help = bench_parser().format_help()
    assert "--serve-host-pool-mib" in bench_help
    assert "--host-link-gbps" in bench_help
