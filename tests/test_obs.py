"""Serving observability (`mdi_llm_tpu/obs/`): percentile math against a
fake clock, Chrome-trace schema/ordering, ring-buffer bounding, and the
overhead contract — with tracing + metrics enabled, a full mixed serving
trace shows ZERO post-warmup recompiles and an UNCHANGED host_syncs count
vs observability off (the acceptance criteria of the obs layer: it is a
serving feature precisely because enabling it cannot perturb serving).
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.obs import (
    Counter,
    Histogram,
    MetricsRegistry,
    ServingObserver,
    TraceRecorder,
    latency_summary,
    percentiles,
)
from tests.test_model import tiny_config


class FakeClock:
    """Deterministic, manually-advanced clock for timestamp math."""

    def __init__(self, t0: float = 100.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# metrics primitives
# ---------------------------------------------------------------------------


def test_counter_is_monotonic():
    c = Counter("c")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError):
        c.inc(-1)
    c.set_to(10)
    with pytest.raises(ValueError):
        c.set_to(4)


def test_percentiles_exact_match_numpy_linear():
    rng = np.random.default_rng(3)
    values = rng.uniform(0, 5, 37).tolist()
    for q in (0, 10, 50, 95, 99, 100):
        ours = percentiles(values, [q])[0]
        ref = float(np.percentile(values, q))  # default 'linear' method
        assert math.isclose(ours, ref, rel_tol=1e-12), (q, ours, ref)
    assert percentiles([], [50]) == [0.0]
    with pytest.raises(ValueError):
        percentiles([1.0], [101])


def test_latency_summary_block_shape():
    s = latency_summary([0.1, 0.2, 0.3, 0.4])
    assert set(s) == {"count", "p50", "p95", "p99", "mean", "max"}
    assert s["count"] == 4 and math.isclose(s["p50"], 0.25)
    assert math.isclose(s["mean"], 0.25) and s["max"] == 0.4
    empty = latency_summary([])
    assert empty["count"] == 0 and empty["p50"] == 0.0


def test_histogram_buckets_and_percentile():
    h = Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0, 100.0):
        h.observe(v)
    assert h.count == 5 and h.sum == pytest.approx(106.5)
    assert h.counts == [1, 2, 1, 1]  # per-bucket + overflow
    cum = h.cumulative()
    assert cum[:3] == [(1.0, 1), (2.0, 3), (4.0, 4)]
    assert cum[-1] == (math.inf, 5)
    # interpolated estimate lands inside the containing bucket
    assert 1.0 <= h.percentile(50) <= 2.0
    assert h.percentile(0) == 0.0 or h.percentile(0) <= 1.0
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(2.0, 1.0))


def test_registry_exposition_json_and_prometheus():
    r = MetricsRegistry()
    r.counter("reqs_total", "requests").inc(3)
    r.gauge("util", "pool util").set(0.5)
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    d = r.to_dict()
    assert d["counters"]["reqs_total"] == 3
    assert d["gauges"]["util"] == 0.5
    hd = d["histograms"]["lat_seconds"]
    assert hd["count"] == 2 and hd["buckets"][-1][0] == "+Inf"
    json.dumps(d)  # JSON-clean (inf encoded as the "+Inf" string)

    text = r.render_prometheus()
    assert "# TYPE reqs_total counter" in text
    assert "reqs_total 3" in text
    assert '# TYPE lat_seconds histogram' in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_sum" in text and "lat_seconds_count 2" in text

    # get-or-create returns the same object; type conflicts refuse
    assert r.counter("reqs_total") is r.counter("reqs_total")
    with pytest.raises(TypeError):
        r.gauge("reqs_total")


# ---------------------------------------------------------------------------
# fake-clock lifecycle -> latency percentiles (the derivation under test)
# ---------------------------------------------------------------------------


def test_request_latency_derivation_against_fake_clock():
    """Drive one request through the full lifecycle on a fake clock and
    check every derived latency by hand: queue-wait = admit - submit,
    TTFT = first token - submit, TPOT = (last - first)/(n - 1),
    E2E = finish - submit."""
    clk = FakeClock(1000.0)
    obs = ServingObserver(ring=64, clock=clk)
    obs.request_submitted("r0", n_prompt=7, max_new_tokens=4)
    clk.advance(2.0)
    obs.request_admitted("r0", slot=0, admit_order=0)
    clk.advance(1.0)
    obs.step("mixed", width=16, live=1)  # prefill chunk boundary
    obs.prefill_chunk("r0", 7)
    clk.advance(0.5)
    obs.step("mixed", width=16, live=1)  # prefill completes, first token
    obs.tokens("r0")
    for _ in range(3):
        clk.advance(0.25)
        obs.step("decode", width=1, live=1)
        obs.tokens("r0")
    obs.request_finished("r0")

    t = obs.tracer.completed[0]
    assert t.queue_wait == pytest.approx(2.0)
    assert t.ttft == pytest.approx(3.5)  # 2.0 queue + 1.0 + 0.5 to token 1
    assert t.n_tokens == 4
    assert t.tpot == pytest.approx(0.75 / 3)  # 3 gaps of 0.25 s
    assert t.e2e == pytest.approx(4.25)
    assert t.prefill_chunks == 1


def test_percentile_aggregation_over_many_fake_requests():
    """N requests with arithmetically spread latencies: the summaries'
    p50/p95/p99 must equal the hand-computed order statistics (exact
    percentiles over the completed-request window, NOT the histogram
    approximation)."""
    clk = FakeClock(0.0)
    obs = ServingObserver(ring=256, clock=clk)
    n = 20
    for i in range(n):
        rid = f"r{i}"
        t_submit = clk.t
        obs.request_submitted(rid, n_prompt=4, max_new_tokens=2)
        clk.advance(0.1 * (i + 1))  # queue wait: 0.1, 0.2, ... 2.0
        obs.request_admitted(rid, slot=0, admit_order=i)
        obs.step("mixed", width=8, live=1)
        obs.tokens(rid)  # TTFT == queue wait (token at admit instant)
        clk.advance(0.05)
        obs.step("decode", width=1, live=1)
        obs.tokens(rid)
        obs.request_finished(rid)
        assert obs.tracer.completed[-1].ttft == pytest.approx(
            clk.t - t_submit - 0.05
        )
        clk.advance(1.0)  # inter-arrival gap
    summ = obs.latency_summaries()
    waits = [0.1 * (i + 1) for i in range(n)]
    want50, want95, want99 = percentiles(waits, (50, 95, 99))
    assert summ["queue_wait_s"]["count"] == n
    assert summ["queue_wait_s"]["p50"] == pytest.approx(want50)
    assert summ["queue_wait_s"]["p95"] == pytest.approx(want95)
    assert summ["queue_wait_s"]["p99"] == pytest.approx(want99)
    assert summ["ttft_s"]["p50"] == pytest.approx(want50)
    assert summ["tpot_s"]["p99"] == pytest.approx(0.05)
    # every e2e = wait + 0.05
    assert summ["e2e_s"]["p95"] == pytest.approx(want95 + 0.05)


def test_preemption_and_resume_recorded():
    clk = FakeClock()
    obs = ServingObserver(ring=64, clock=clk)
    obs.request_submitted("r0", 4, 8)
    obs.request_admitted("r0", slot=0, admit_order=0)
    obs.step("decode", width=1, live=1)
    obs.tokens("r0")
    obs.request_preempted("r0", n_generated=1)
    clk.advance(1.0)
    obs.request_admitted("r0", slot=1, admit_order=1, resumed=True)
    obs.step("decode", width=1, live=1)
    obs.tokens("r0")
    obs.request_finished("r0")
    t = obs.tracer.completed[0]
    assert t.preemptions == 1
    assert t.admit_order == 0  # queue-wait keys on the FIRST admission
    names = [e["name"] for e in obs.tracer.events]
    assert "preempted" in names and "resumed" in names
    m = obs.metrics.to_dict()["counters"]
    assert m["serving_preemptions_total"] == 1
    assert m["serving_requests_resumed_total"] == 1


def test_spec_counters_split_by_source_and_accept_rate_gauge():
    """`ServingObserver.spec` keeps per-source (ngram vs model) drafted/
    accepted counters, the totals, and the lifetime accept-rate gauge."""
    obs = ServingObserver(ring=64, clock=FakeClock())
    obs.spec(4, 3, "ngram")
    obs.spec(4, 1, "model")
    obs.spec(2, 2, "ngram")
    d = obs.metrics.to_dict()
    c = d["counters"]
    assert c["serving_spec_drafted_ngram_total"] == 6
    assert c["serving_spec_accepted_ngram_total"] == 5
    assert c["serving_spec_drafted_model_total"] == 4
    assert c["serving_spec_accepted_model_total"] == 1
    assert c["serving_spec_drafted_total"] == 10
    assert c["serving_spec_accepted_total"] == 6
    assert d["gauges"]["serving_spec_accept_rate"] == pytest.approx(0.6)


def test_verify_spans_and_spec_counters_on_live_engine(served_model):
    """On a real speculative run the observer's spec counters equal the
    engine's aggregate stats, and every Perfetto verify span records
    spec_k and the accepted count for that round."""
    cfg, params = served_model
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, 5).tolist()  # cycling prompt
    obs = ServingObserver(ring=4096)
    engine = Generator(cfg, params, cache_dtype=jnp.float32).serve(
        block_size=4, max_batch=2, decode_chunk=4, spec_k=4, obs=obs,
    )
    engine.add_request("r0", prompt, 20)
    _, stats = engine.run()
    assert stats.spec_drafted > 0 and stats.spec_accepted > 0
    c = obs.metrics.to_dict()["counters"]
    assert c["serving_spec_drafted_total"] == stats.spec_drafted
    assert c["serving_spec_accepted_total"] == stats.spec_accepted
    assert c["serving_spec_drafted_ngram_total"] == stats.spec_drafted_ngram
    g = obs.metrics.to_dict()["gauges"]
    assert g["serving_spec_accept_rate"] == pytest.approx(
        stats.spec_accept_rate)
    spans = [e for e in obs.tracer.events
             if e["name"] == "verify" and e.get("ph") != "M"]
    assert spans, "speculative run produced no verify spans"
    accepted = 0
    for e in spans:
        args = e.get("args") or {}
        assert args.get("spec_k") == 4
        accepted += int(args.get("accepted", 0))
    assert accepted == stats.spec_accepted


# ---------------------------------------------------------------------------
# ring bounding
# ---------------------------------------------------------------------------


def test_trace_ring_is_bounded():
    clk = FakeClock()
    rec = TraceRecorder(capacity=8, clock=clk)
    for i in range(30):
        rec.instant(f"e{i}", clk.advance(0.1), pid=1, tid=0)
    assert len(rec.events) == 8
    assert rec.dropped == 22
    # the ring keeps the NEWEST events
    assert [e["name"] for e in rec.events] == [f"e{i}" for i in range(22, 30)]
    # the completed-request window is bounded by the same capacity
    obs = ServingObserver(ring=4, clock=clk)
    for i in range(10):
        rid = f"r{i}"
        obs.request_submitted(rid, 1, 1)
        obs.request_admitted(rid, slot=0, admit_order=i)
        obs.tokens(rid)
        obs.request_finished(rid)
    assert len(obs.tracer.completed) == 4
    assert [t.rid for t in obs.tracer.completed] == ["r6", "r7", "r8", "r9"]
    assert obs.latency_summaries()["e2e_s"]["count"] == 4
    with pytest.raises(ValueError):
        TraceRecorder(capacity=0)


# ---------------------------------------------------------------------------
# Chrome trace export: schema + admission-order reconstruction
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _mixed_trace(cfg, seed=5, lens=(3, 9, 17, 5, 33), news=(8, 12, 6, 10, 7)):
    rng = np.random.default_rng(seed)
    return [
        (f"r{i}", rng.integers(1, cfg.vocab_size, int(n)).tolist(), m)
        for i, (n, m) in enumerate(zip(lens, news))
    ]


def _run_engine(cfg, params, obs=None, **knobs):
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    knobs.setdefault("block_size", 4)
    knobs.setdefault("max_batch", 3)
    knobs.setdefault("prefill_chunk", 8)
    engine = gen.serve(obs=obs, **knobs)
    for rid, prompt, new in _mixed_trace(cfg):
        engine.add_request(rid, prompt, new)
    return engine.run()


def test_chrome_trace_schema_and_admission_order(served_model, tmp_path):
    cfg, params = served_model
    obs = ServingObserver(ring=4096)
    results, stats = _run_engine(cfg, params, obs=obs)
    assert stats.requests_finished == 5

    out = tmp_path / "trace.json"
    obs.tracer.write_chrome_trace(out)
    doc = json.loads(out.read_text())  # valid JSON end to end
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["events_dropped"] == 0

    for e in events:
        assert {"name", "ph", "pid", "tid"} <= set(e), e
        assert e["ph"] in ("X", "i", "M"), e
        if e["ph"] != "M":
            assert e["ts"] >= 0, "timestamps rebased to the trace epoch"
        if e["ph"] == "X":
            assert e["dur"] >= 0

    # per-request spans reconstruct the scheduler's admission order: span
    # start times sort identically to admit_order, and the track metadata
    # pins the same rank
    spans = sorted(
        (e for e in events if e["ph"] == "X" and e["pid"] == 1),
        key=lambda e: e["ts"],
    )
    assert len(spans) == 5
    orders = [e["args"]["admit_order"] for e in spans]
    assert orders == sorted(orders) == list(range(5))
    assert [e["tid"] for e in spans] == orders
    sort_meta = {
        e["tid"]: e["args"]["sort_index"]
        for e in events if e["name"] == "thread_sort_index"
    }
    assert sort_meta == {i: i for i in range(5)}
    # spans carry the latency attribution for Perfetto inspection
    for e in spans:
        assert e["args"]["ttft_s"] > 0 and e["args"]["n_tokens"] > 0
    # engine steps ride on their own process lane with packing detail
    steps = [e for e in events if e["ph"] == "X" and e["pid"] == 2]
    assert steps and all(
        e["args"]["packed_width"] > 0 and e["args"]["live_lanes"] >= 1
        for e in steps
    )
    assert {e["name"] for e in steps} <= {
        "mixed", "decode", "decode_chunk", "verify"
    }


def test_open_request_spans_exported_mid_run(served_model):
    """A live engine snapshot must render: requests admitted but not yet
    retired export partial spans up to 'now'."""
    cfg, params = served_model
    obs = ServingObserver(ring=1024)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    # decode_chunk=1 pins the per-step engine (a buffered chunk loop would
    # drain the whole request inside one step() call)
    engine = gen.serve(block_size=4, max_batch=2, prefill_chunk=8,
                       decode_chunk=1, obs=obs)
    rng = np.random.default_rng(0)
    engine.add_request("open", rng.integers(1, cfg.vocab_size, 5).tolist(), 30)
    for _ in range(3):
        engine.step()
    assert engine.scheduler.has_work  # still mid-request
    doc = obs.tracer.to_chrome_trace()
    open_spans = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("args", {}).get("open")
    ]
    assert len(open_spans) == 1 and open_spans[0]["name"] == "open"


# ---------------------------------------------------------------------------
# the overhead contract: zero recompiles, zero extra host syncs
# ---------------------------------------------------------------------------


def test_observability_adds_no_syncs_no_recompiles(served_model):
    """THE acceptance test: on a full mixed serving trace (prefill splits,
    chunked decode, retirement) enabling tracing + metrics changes
    NOTHING the device sees — token streams identical, host_syncs count
    identical, and zero post-warmup recompiles with the CompileGuard
    pinned across the observed run."""
    from mdi_llm_tpu.utils.profiling import CompileGuard

    cfg, params = served_model
    # one Generator: its _serve_fns cache is the warmup boundary
    gen = Generator(cfg, params, cache_dtype=jnp.float32)

    def run(obs):
        engine = gen.serve(block_size=4, max_batch=3, prefill_chunk=8,
                           obs=obs)
        for rid, prompt, new in _mixed_trace(cfg):
            engine.add_request(rid, prompt, new)
        return engine.run()

    guard = CompileGuard(label="obs-overhead")
    with guard:
        results_off, stats_off = run(None)  # warmup: compiles allowed
        guard.mark_warm()
        obs = ServingObserver(ring=4096, rss_interval_s=0.0)
        results_on, stats_on = run(obs)
    guard.expect_clean()  # zero post-warmup recompiles with obs enabled

    assert results_on == results_off, "observability perturbed the streams"
    assert stats_on.host_syncs == stats_off.host_syncs, \
        "observability added host syncs"
    assert stats_on.decode_steps == stats_off.decode_steps
    assert stats_on.mixed_steps == stats_off.mixed_steps

    # the observer's own counters agree with the engine's aggregates
    c = obs.metrics.to_dict()["counters"]
    assert c["serving_host_syncs_total"] == stats_on.host_syncs
    assert c["serving_tokens_generated_total"] == stats_on.tokens_generated
    assert c["serving_requests_finished_total"] == stats_on.requests_finished
    assert c["serving_prefill_tokens_total"] == stats_on.prefill_tokens
    # compile counters rode the same jax.monitoring stream the guard uses:
    # the observed run compiled nothing
    assert c["jax_jit_traces_total"] == 0
    # the latency block is fully populated for every finished request
    summ = obs.latency_summaries()
    for name in ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s"):
        assert summ[name]["count"] == 5, name
        assert summ[name]["p99"] >= summ[name]["p50"] >= 0
    # RSS sampling was on (interval 0 = every boundary) and found a gauge
    assert obs.metrics.to_dict()["gauges"].get("host_rss_bytes", 0) > 0


def test_stats_to_dict_is_canonical(served_model):
    """ServingStats.to_dict is the one JSON view both mdi-serve and bench
    embed: derived aggregates must match the properties exactly."""
    cfg, params = served_model
    _, stats = _run_engine(cfg, params)
    d = stats.to_dict()
    assert d["requests"] == stats.requests_finished
    assert d["tokens_generated"] == stats.tokens_generated
    assert d["host_syncs"] == stats.host_syncs
    assert d["tokens_per_sync"] == round(stats.tokens_per_sync, 2)
    assert d["padded_token_frac"] == round(stats.padded_token_frac, 4)
    assert d["mixed_batch_occupancy"] == round(stats.mixed_batch_occupancy, 4)
    assert d["kv_block_utilization_peak"] == round(stats.kv_utilization_peak, 4)
    json.dumps(d)
    # private aggregates stay private: no underscore keys leak
    assert not [k for k in d if k.startswith("_")]


def test_engine_preemption_feeds_lifecycle_events(served_model):
    """A pool sized to force preemption emits preempted/resumed edges and
    per-request preemption counts through the REAL engine path."""
    cfg, params = served_model
    obs = ServingObserver(ring=2048)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(block_size=4, max_batch=3, max_blocks=1 + 14,
                       prefix_caching=False, decode_chunk=1, obs=obs)
    rng = np.random.default_rng(9)
    for i, n in enumerate((9, 13, 11)):
        engine.add_request(
            f"r{i}", rng.integers(1, cfg.vocab_size, int(n)).tolist(), 10
        )
    _, stats = engine.run()
    assert stats.preemptions > 0, "pool sized to preempt"
    c = obs.metrics.to_dict()["counters"]
    assert c["serving_preemptions_total"] == stats.preemptions
    assert c["serving_requests_resumed_total"] >= 1
    assert sum(t.preemptions for t in obs.tracer.completed) == stats.preemptions


def test_serve_cli_exposes_observability_flags():
    from mdi_llm_tpu.cli.serve import build_parser

    help_text = build_parser().format_help()
    for flag in ("--metrics-out", "--trace-out", "--prom-out",
                 "--trace-ring", "--sample-rss"):
        assert flag in help_text, flag
    assert "Perfetto" in help_text


@pytest.mark.slow
def test_serve_cli_writes_metrics_and_trace_artifacts(tmp_path):
    """mdi-serve end-to-end on a synthetic mixed trace: the metrics JSON
    carries TTFT/TPOT/E2E/queue-wait p50/p95/p99 and the trace file is
    Perfetto-loadable with per-request spans in admission order — the
    CLI half of the acceptance criteria."""
    from mdi_llm_tpu.cli.serve import main as serve_main

    metrics_p = tmp_path / "metrics.json"
    trace_p = tmp_path / "trace.json"
    prom_p = tmp_path / "metrics.prom"
    serve_main([
        "--model", "pythia-14m", "--synthetic", "6", "--n-tokens", "8",
        "--sequence-length", "64", "--max-batch", "3", "--block-size", "8",
        "--device", "cpu",
        "--metrics-out", str(metrics_p), "--trace-out", str(trace_p),
        "--prom-out", str(prom_p), "--sample-rss", "0.0",
    ])
    m = json.loads(metrics_p.read_text())
    for name in ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s"):
        blk = m["latency"][name]
        assert blk["count"] == 6
        assert blk["p99"] >= blk["p95"] >= blk["p50"] >= 0.0
    assert m["serving_stats"]["requests"] == 6  # canonical to_dict embed
    assert m["metrics"]["counters"]["serving_requests_finished_total"] == 6
    assert m["metrics"]["gauges"].get("host_rss_bytes", 0) > 0
    assert "serving_request_ttft_seconds" in m["metrics"]["histograms"]

    doc = json.loads(trace_p.read_text())
    spans = sorted(
        (e for e in doc["traceEvents"]
         if e.get("ph") == "X" and e["pid"] == 1),
        key=lambda e: e["ts"],
    )
    orders = [e["args"]["admit_order"] for e in spans]
    assert len(orders) == 6 and orders == sorted(orders)

    text = prom_p.read_text()
    assert "# TYPE serving_requests_finished_total counter" in text
    assert 'serving_request_ttft_seconds_bucket{le="+Inf"} 6' in text
