"""Device-side observability (`mdi_llm_tpu/obs/device.py` + the engine/
Generator capture hooks): AOT ExecutableReports for the real serving
executables, the registry/publication plumbing, the StepWindowProfiler
window math — and THE acceptance pin: with device obs ENABLED the
serving run still shows zero post-warmup recompiles and bit-identical
host_syncs/token streams vs obs-off (introspection compiles at warmup,
caches on the Generator, and never lowers again).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.obs import ServingObserver
from mdi_llm_tpu.obs.device import (
    DeviceReportRegistry,
    ExecutableReport,
    introspect,
)
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def served_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _mixed_trace(cfg, seed=5, lens=(3, 9, 17, 5, 33), news=(8, 12, 6, 10, 7)):
    rng = np.random.default_rng(seed)
    return [
        (f"r{i}", rng.integers(1, cfg.vocab_size, int(n)).tolist(), m)
        for i, (n, m) in enumerate(zip(lens, news))
    ]


# ---------------------------------------------------------------------------
# the acceptance pin: overhead contract WITH device obs enabled
# ---------------------------------------------------------------------------


def test_device_obs_zero_postwarm_recompiles_and_identical_streams(
    served_model,
):
    """Warmup run with a device-capturing observer (AOT introspection
    compiles HERE and caches on the Generator) → mark warm → a second
    device-obs run and an obs-off run: zero post-warmup traces, token
    streams and host_syncs bit-identical across all three."""
    from mdi_llm_tpu.utils.profiling import CompileGuard

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)

    def run(obs):
        engine = gen.serve(block_size=4, max_batch=3, prefill_chunk=8,
                           obs=obs)
        for rid, prompt, new in _mixed_trace(cfg):
            engine.add_request(rid, prompt, new)
        return engine.run()

    guard = CompileGuard(label="device-obs-overhead")
    with guard:
        obs_warm = ServingObserver(device=True)
        results_warm, stats_warm = run(obs_warm)
        guard.mark_warm()
        obs_on = ServingObserver(device=True)
        results_on, stats_on = run(obs_on)
        results_off, stats_off = run(None)
    guard.expect_clean()  # introspection never lowers post-warmup

    assert results_on == results_off == results_warm
    assert stats_on.host_syncs == stats_off.host_syncs
    assert stats_on.mixed_steps == stats_off.mixed_steps

    # the warmup observer captured; the post-warm observer REPUBLISHED the
    # Generator-cached reports without a single new lower/compile
    assert len(obs_warm.device) > 0
    assert obs_on.device.to_dict().keys() == obs_warm.device.to_dict().keys()
    for rep in obs_on.device.reports():
        assert rep.error is None, rep.error
        assert rep.variant == "float32"  # the pool dtype tags the report
        assert rep.argument_bytes > 0
    labels = {r.label for r in obs_on.device.reports()}
    assert "mixed" in labels  # the unified step always runs on this trace

    # reports flow into the PR 7 surfaces: gauges + the metrics_dict block
    gauges = obs_on.metrics.to_dict()["gauges"]
    assert any(k.startswith("xla_mixed_") for k in gauges)
    md = obs_on.metrics_dict(stats_on)
    assert set(md["device"]) == set(obs_on.device.to_dict())
    json.dumps(md)


def test_cost_numbers_populated_when_backend_reports(served_model):
    """On backends with the AOT cost APIs (CPU included) the mixed
    report's FLOPs/bytes are positive and memory analysis itemizes."""
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    obs = ServingObserver(device=True)
    engine = gen.serve(block_size=4, max_batch=2, prefill_chunk=8, obs=obs)
    for rid, prompt, new in _mixed_trace(cfg)[:2]:
        engine.add_request(rid, prompt, new)
    engine.run()
    rep = next(r for r in obs.device.reports() if r.label == "mixed")
    if rep.flops is None:  # pragma: no cover - backend without the API
        pytest.skip("backend reports no cost_analysis flops")
    assert rep.flops > 0 and rep.bytes_accessed > 0
    assert rep.temp_bytes >= 0 and rep.output_bytes > 0
    assert tuple(rep.key) == (2, engine.token_budget)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_registry_dedups_and_publish_only_mode():
    def fake_fn():  # looks nothing like a jit fn: introspect must not raise
        pass

    reg = DeviceReportRegistry()
    r1 = reg.capture("decode", (2,), jax.jit(lambda x: x + 1),
                     (jnp.zeros((2,)),))
    r2 = reg.capture("decode", (2,), None, None)  # cached: args unused
    assert r1 is r2 and len(reg) == 1

    # publish-only registries never lower anything but accept reports
    pub = DeviceReportRegistry(capture_enabled=False)
    assert pub.capture("decode", (2,), fake_fn, ()) is None
    assert len(pub) == 0
    pub.add(r1)
    assert pub.get("decode", (2,)) is r1
    pub.add(ExecutableReport(label="decode", key=(2,)))  # first one wins
    assert pub.get("decode", (2,)) is r1


def test_introspect_failure_is_a_report_not_an_exception():
    rep = introspect(object(), (jnp.zeros((2,)),), label="bad", key=(1,))
    assert rep.error is not None
    assert rep.flops is None
    assert rep.name == "bad(1)"
    json.dumps(rep.to_dict())


def test_sequential_generator_captures_prefill_and_decode(served_model):
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    reg = DeviceReportRegistry()
    gen.attach_device_obs(reg)
    prompt = list(range(1, 9))
    out1, _ = gen.generate([prompt], 6, temperature=0.0)
    labels = {r.label for r in reg.reports()}
    assert labels == {"prefill", "decode_chunk"}
    n = len(reg)
    # same shapes again: the dedup means zero new captures
    out2, _ = gen.generate([prompt], 6, temperature=0.0)
    assert len(reg) == n and out1 == out2
    gen.attach_device_obs(None)  # detach: no capture, no error
    gen.generate([prompt], 2, temperature=0.0)
    assert len(reg) == n


# ---------------------------------------------------------------------------
# StepWindowProfiler: the bounded --xprof-steps window
# ---------------------------------------------------------------------------


def test_step_window_profiler_opens_and_closes_the_window(monkeypatch):
    from mdi_llm_tpu.utils import profiling

    events = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: events.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: events.append(("stop",))
    )
    prof = profiling.StepWindowProfiler("/tmp/x", n_steps=3, skip=2)
    for i in range(1, 10):
        prof.on_step(i)
    assert events == [("start", "/tmp/x"), ("stop",)]
    assert prof.window == (3, 5)  # steps 3,4,5 traced: skip 2, capture 3
    prof.close()  # idempotent after done
    assert events == [("start", "/tmp/x"), ("stop",)]

    # a run shorter than the window: close() stops the open trace
    events.clear()
    prof2 = profiling.StepWindowProfiler("/tmp/x", n_steps=50, skip=0)
    prof2.on_step(1)
    assert events == [("start", "/tmp/x")]
    prof2.close()
    assert events == [("start", "/tmp/x"), ("stop",)]

    # a run shorter than skip: the trace never starts
    events.clear()
    prof3 = profiling.StepWindowProfiler("/tmp/x", n_steps=2, skip=100)
    prof3.on_step(1)
    prof3.close()
    assert events == []

    with pytest.raises(ValueError):
        profiling.StepWindowProfiler("/tmp/x", n_steps=0)


def test_serve_cli_exposes_device_flags():
    from mdi_llm_tpu.cli.serve import build_parser

    help_text = build_parser().format_help()
    for flag in ("--xprof-steps", "--xprof-dir", "--xprof-skip",
                 "--no-device-obs"):
        assert flag in help_text, flag
