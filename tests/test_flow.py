"""mdi-flow: jaxpr buffer-liveness analysis of the serving compile set.

Four layers under test:

1. the per-rule checkers — every shipped FLOW_RULES entry has a
   PLANTED-bug fixture it must catch and a clean twin it must pass,
   enforced by a registry-wide property test (a check that can't fail
   proves nothing);
2. the static byte model — interior temp peaks are loop-length
   invariant (one allocation per body, like XLA's buffer reuse),
   digests are deterministic, and the CALIBRATION test compiles the
   REAL mixed and decode_chunk executables on CPU and pins the static
   high-water within 20% of XLA's own `memory_analysis` (in float32:
   the CPU backend materializes f32 upcasts of bf16 params — an
   emulation artifact TPUs don't have);
3. the repo self-check — the registry model's serving engines are
   donation-clean at single-device, tp=2 and pp=2, with a trip-wired
   backend_compile / device_put proving the whole pass never compiles
   or places a buffer; the committed goldens/flow-goldens.json stays
   in sync (drift here = re-run --update-goldens deliberately);
4. the CLI + integrations — exit codes 0/1/2, --format json, the
   goldens round-trip, the bench/serve gate, the mdi-audit --liveness
   agreement, and the mdi-check aggregate gate.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.analysis.check import main as check_main
from mdi_llm_tpu.analysis.ir import trace_serving
from mdi_llm_tpu.analysis.liveness import (
    FLOW_RULES,
    FlowReport,
    _check_goldens,
    analyze_flow,
    enforce_flow_preflight,
    flow_detail,
    flow_preflight,
    interior_peak_bytes,
    jaxpr_digest,
    load_goldens,
    main,
    profile_executable,
    write_goldens,
)
from mdi_llm_tpu.config import Config, ServingConfig
from mdi_llm_tpu.obs.device import ExecutableSpec

sds = jax.ShapeDtypeStruct
f32 = jnp.float32

MODEL = "pythia-14m"  # the registry self-check model
REPO = Path(__file__).resolve().parent.parent

_ENGINES = {}


def _engine(tp=1, pp=1, spec_k=0, dtype="bfloat16"):
    key = (tp, pp, spec_k, dtype)
    if key not in _ENGINES:
        _ENGINES[key] = trace_serving(
            Config.from_name(MODEL), ServingConfig(spec_k=spec_k),
            tp=tp, pp=pp, dtype=dtype, max_seq_length=256,
        )
    return _ENGINES[key]


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# per-rule planted-bug / clean fixtures (+ the registry property test)
# ---------------------------------------------------------------------------

BUF = sds((512, 1024), f32)  # 2 MiB — above the 1 MiB default floor


def _spec(name, fn, args, donate=()):
    return ExecutableSpec(name, (), fn, args, None, tuple(donate))


def _donation_spec(donated: bool):
    fn = jax.jit(
        lambda b: b.at[0].add(1.0),
        donate_argnums=(0,) if donated else (),
    )
    return _spec("upd", fn, (BUF,), donate=(0,) if donated else ())


def _bloat_spec(read: bool):
    def stepper(buf, xs):
        def body(carry, x):
            dead, acc = carry
            acc = acc + x.sum() + (dead[0, 0] if read else 0.0)
            return (dead, acc), ()
        (_, acc), _ = jax.lax.scan(body, (buf, jnp.float32(0.0)), xs)
        return acc

    return _spec("loop", jax.jit(stepper), (BUF, sds((8, 4), f32)))


def _boom_spec(ok: bool):
    def boom(a):
        raise RuntimeError("boom")

    return _spec("boom", jax.jit((lambda a: a * 2) if ok else boom),
                 (sds((4,), f32),))


def _budget_findings(hbm_gb):
    return flow_preflight(_engine(), origin="t", hbm_gb=hbm_gb).findings


def _golden_findings(tamper):
    _, profiles = analyze_flow([_donation_spec(True)], origin="t")
    (p,) = profiles
    entry = {"peak_bytes": p.peak_bytes, "digest": p.digest,
             "ops": dict(p.ops)}
    if tamper == "peak":
        entry["peak_bytes"] = max(1, p.peak_bytes // 2)
    elif tamper == "digest":
        entry["digest"] = "0" * 16
        entry["ops"] = {"fake_op": 3}
    goldens = {"tolerance": 0.10, "budgets": {f"t::{p.name}": entry}}
    return _check_goldens(profiles, goldens, "t")


# rule -> zero-arg callable returning findings; the planted twin MUST
# contain the rule, the clean twin must NOT — and the registry test
# below pins that every shipped rule has both
BAD = {
    "missed-donation": lambda: analyze_flow([_donation_spec(False)])[0],
    "live-range-bloat": lambda: analyze_flow([_bloat_spec(False)])[0],
    "trace-failure": lambda: analyze_flow([_boom_spec(False)])[0],
    "hbm-over-budget": lambda: _budget_findings(0.001),
    "peak-memory-regression": lambda: _golden_findings("peak"),
    "jaxpr-drift": lambda: _golden_findings("digest"),
}
GOOD = {
    "missed-donation": lambda: analyze_flow([_donation_spec(True)])[0],
    "live-range-bloat": lambda: analyze_flow([_bloat_spec(True)])[0],
    "trace-failure": lambda: analyze_flow([_boom_spec(True)])[0],
    "hbm-over-budget": lambda: _budget_findings(64.0),
    "peak-memory-regression": lambda: _golden_findings(None),
    "jaxpr-drift": lambda: _golden_findings(None),
}


def test_every_shipped_rule_has_a_fixture_pair():
    """Registry-wide property: adding a FLOW_RULES entry without a
    planted/clean fixture pair fails here."""
    assert set(BAD) == set(FLOW_RULES) == set(GOOD)


@pytest.mark.parametrize("rule", sorted(FLOW_RULES))
def test_planted_fixture_caught_and_clean_twin_passes(rule):
    assert rule in rules_of(BAD[rule]()), f"{rule}: planted bug missed"
    assert rule not in rules_of(GOOD[rule]()), f"{rule}: clean twin flagged"


def test_missed_donation_message_names_the_argnum():
    findings, _ = analyze_flow([_donation_spec(False)])
    (f,) = findings
    assert "argnum 0" in f.message and "donate_argnums" in f.message
    assert "2.0 MiB" in f.message


def test_live_range_bloat_names_the_extending_site():
    findings, _ = analyze_flow([_bloat_spec(False)])
    (f,) = findings
    assert "`scan`" in f.message and "never reads it" in f.message
    assert f.line_text.startswith("bloat:scan:")


def test_min_bytes_floor_silences_small_buffers():
    findings, _ = analyze_flow([_bloat_spec(False)], min_bytes=1 << 30)
    assert findings == []


# ---------------------------------------------------------------------------
# the static byte model
# ---------------------------------------------------------------------------


def test_interior_peak_is_loop_length_invariant():
    """A scan body's temps are counted ONCE (XLA reuses body buffers
    across iterations): 4 vs 64 iterations over the same row must give
    the same interior peak."""

    def make(n):
        def f(xs):
            def body(c, x):
                t = x * 2.0 + 1.0
                return c + t.sum(), ()
            out, _ = jax.lax.scan(body, jnp.float32(0.0), xs)
            return out
        return jax.jit(f).trace(sds((n, 4096), f32)).jaxpr.jaxpr

    p4, p64 = interior_peak_bytes(make(4)), interior_peak_bytes(make(64))
    assert p4 == p64 > 0


def test_profile_accounting_and_donation_alias():
    p = profile_executable(_donation_spec(True))
    nb = 512 * 1024 * 4
    assert p.argument_bytes == nb and p.output_bytes == nb
    assert p.alias_bytes == nb  # donated in-place update aliases fully
    assert p.peak_bytes == nb + p.temp_peak_bytes
    q = profile_executable(_donation_spec(False))
    assert q.alias_bytes == 0 and q.peak_bytes >= 2 * nb


def test_digest_deterministic_and_discriminating():
    d1, ops1 = jaxpr_digest(jax.jit(lambda a: a + 1).trace(BUF).jaxpr)
    d2, _ = jaxpr_digest(jax.jit(lambda a: a + 1).trace(BUF).jaxpr)
    d3, _ = jaxpr_digest(jax.jit(lambda a: a * 2).trace(BUF).jaxpr)
    assert d1 == d2 and d1 != d3
    assert len(d1) == 16 and sum(ops1.values()) >= 1


def test_goldens_write_merges_origins_and_load_validates(tmp_path):
    _, profiles = analyze_flow([_donation_spec(True)], origin="a")
    g = tmp_path / "g.json"
    write_goldens(g, "a", profiles)
    write_goldens(g, "b", profiles)
    data = load_goldens(g)
    assert {"a::upd()", "b::upd()"} <= set(data["budgets"])
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    with pytest.raises(ValueError, match="budgets"):
        load_goldens(bad)


def test_calibration_static_peak_within_20pct_of_xla_memory_analysis():
    """The acceptance gate: compile the REAL mixed and decode_chunk
    executables on CPU (float32 — see module docstring) and pin the
    static model against XLA's own accounting
    (args + outputs + temps − aliases)."""
    from mdi_llm_tpu.obs.device import abstractify

    engine = _engine(dtype="float32")
    specs = engine.enumerate_executables()
    assert {s.label for s in specs} == {"mixed", "decode_chunk"}
    _, profiles = analyze_flow(specs, origin="calib")
    prof = {p.name: p for p in profiles}
    for spec in specs:
        absargs = tuple(abstractify(a) for a in spec.args)
        ma = (spec.fn.lower(*absargs, **(spec.static_kwargs or {}))
              .compile().memory_analysis())
        xla = (ma.argument_size_in_bytes + ma.output_size_in_bytes
               + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        ratio = prof[spec.name].peak_bytes / xla
        assert 0.8 <= ratio <= 1.2, (
            f"{spec.name}: static {prof[spec.name].peak_bytes} vs "
            f"XLA {xla} (ratio {ratio:.3f}) — outside the 20% band"
        )


# ---------------------------------------------------------------------------
# the repo self-check: registry model, three meshes, zero device use
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tp,pp", [(1, 1), (2, 1), (1, 2)])
def test_self_check_donation_clean_and_never_touches_a_backend(
    tp, pp, monkeypatch, devices
):
    """The acceptance gate: the full liveness pass on the registry
    model's live engine shapes is CLEAN (donation sets verified — every
    executable aliases its kv pool) at single-device, tp=2 and pp=2,
    and a trip-wired backend_compile / device_put proves the analyzer
    performs zero backend compiles and zero device transfers."""
    from jax._src import compiler as jax_compiler

    def tripped(*a, **k):
        raise AssertionError("mdi-flow touched a backend/device")

    monkeypatch.setattr(jax_compiler, "backend_compile", tripped)
    monkeypatch.setattr(jax, "device_put", tripped)

    engine = trace_serving(
        Config.from_name(MODEL), ServingConfig(spec_k=3), tp=tp, pp=pp,
        max_seq_length=256,
    )
    specs = engine.enumerate_executables()
    assert all(s.roles == {0: "params", 2: "kv"} for s in specs)
    report = flow_preflight(engine, origin=f"self@tp{tp}pp{pp}",
                            hbm_gb=64.0)
    assert report.findings == [], report.render_text()
    assert len(report.profiles) == 3  # mixed, decode_chunk, verify
    # the donation sets are live: every executable aliases its kv pool
    assert all(p.alias_bytes > 0 for p in report.profiles)
    assert all(p.peak_bytes > 0 and p.device_peak_bytes > 0
               for p in report.profiles)
    dev = report.breakdown["per_device"]
    assert 0 < dev["high_water_bytes"] <= 64 * 2**30
    if tp > 1:
        # tp shards params+pool: per-device strictly below global
        assert all(p.device_peak_bytes < p.peak_bytes
                   for p in report.profiles)


def test_committed_goldens_match_the_current_compile_set():
    """goldens/flow-goldens.json stays in sync with the registry
    model's serving IR — drift means review the churn, then re-run
    `mdi-flow --model pythia-14m --update-goldens` deliberately."""
    goldens = load_goldens(REPO / "goldens" / "flow-goldens.json")
    engine = trace_serving(Config.from_name(MODEL), ServingConfig())
    _, profiles = analyze_flow(engine.enumerate_executables(),
                               origin=MODEL)
    findings = _check_goldens(profiles, goldens, MODEL)
    assert findings == [], "\n".join(f.message for f in findings)
    # and the committed file actually covers this compile set
    assert {f"{MODEL}::{p.name}" for p in profiles} <= set(
        goldens["budgets"]
    )


def test_committed_goldens_cover_spec_draft_compile_set():
    """The sampled-speculative + draft-model serving set has committed
    budgets too (origin `@spec4@draft`): rejection verify, draft mirror
    and draft catch-up scan — regenerate with `mdi-flow --model pythia-14m
    --spec-k 4 --temperature 0.8 --draft-model pythia-14m
    --update-goldens` on deliberate churn."""
    goldens = load_goldens(REPO / "goldens" / "flow-goldens.json")
    engine = trace_serving(
        Config.from_name(MODEL),
        ServingConfig(spec_k=4, temperature=0.8, draft_model=MODEL),
    )
    origin = f"{MODEL}@spec4@draft"
    _, profiles = analyze_flow(engine.enumerate_executables(),
                               origin=origin)
    labels = {p.name.split("(")[0] for p in profiles}
    assert {"verify_sample", "draft_scan", "draft_mixed"} <= labels
    findings = _check_goldens(profiles, goldens, origin)
    assert findings == [], "\n".join(f.message for f in findings)
    assert {f"{origin}::{p.name}" for p in profiles} <= set(
        goldens["budgets"]
    )


# ---------------------------------------------------------------------------
# preflight gate + detail record (bench.py / mdi-serve wiring)
# ---------------------------------------------------------------------------


def test_enforce_flow_preflight_refuses_on_errors_allows_with_flag():
    report = flow_preflight(_engine(), origin="gate")
    emitted = []
    assert enforce_flow_preflight(report, "bench", emit=emitted.append)
    assert emitted == []  # clean pass stays silent

    findings, profiles = analyze_flow([_boom_spec(False)], origin="gate")
    broken = FlowReport(origin="gate", findings=findings,
                        profiles=profiles)
    with pytest.raises(SystemExit, match="no-preflight"):
        enforce_flow_preflight(broken, "bench", emit=emitted.append)
    assert any("trace-failure" in line for line in emitted)
    assert enforce_flow_preflight(broken, "bench", allow=True,
                                  emit=emitted.append)

    d = flow_detail(report)
    assert d["findings"] == 0 and d["warnings"] == 0
    assert set(d["peak_bytes"]) == set(d["device_peak_bytes"]) != set()


def test_audit_liveness_path_agrees_with_flow_temp_peak():
    """mdi-audit --liveness replaces the analytic activation term with
    mdi-flow's worst interior temp peak; the two paths must agree
    exactly on the registry model (same engine tuple), and plans that
    are not engine-enumerable keep the heuristic."""
    from mdi_llm_tpu.analysis.audit import preflight

    cfg = Config.from_name(MODEL)
    report = preflight(cfg, serving=ServingConfig(), seq_len=256,
                       origin="t", liveness=True)
    dev = report.breakdown["per_device"]
    assert dev["act_source"] == "liveness"
    _, profiles = analyze_flow(_engine().enumerate_executables())
    assert dev["act_bytes"] == max(p.temp_peak_bytes for p in profiles)

    heur = preflight(cfg, serving=ServingConfig(), seq_len=256,
                     origin="t")
    assert heur.breakdown["per_device"]["act_source"] == "heuristic"
    # no-serving plans fall back even with the flag on
    dense = preflight(cfg, seq_len=256, origin="t", liveness=True)
    assert dense.breakdown["per_device"]["act_source"] == "heuristic"


# ---------------------------------------------------------------------------
# CLI: exit codes, json, goldens round-trip, suppression, help
# ---------------------------------------------------------------------------


def test_cli_clean_self_check_exit_0(capsys):
    rc = main(["--model", MODEL, "--seq-len", "256"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "findings: none" in out and "mixed(8,136)" in out
    assert "digest=" in out


def test_cli_goldens_round_trip_regression_and_drift(tmp_path, capsys):
    g = tmp_path / "g.json"
    base = ["--model", MODEL, "--seq-len", "256"]
    assert main(base + ["--goldens", str(g), "--update-goldens"]) == 0
    assert main(base + ["--goldens", str(g)]) == 0
    capsys.readouterr()

    data = json.loads(g.read_text())
    for entry in data["budgets"].values():
        entry["peak_bytes"] = max(1, entry["peak_bytes"] // 2)
    g.write_text(json.dumps(data))
    assert main(base + ["--goldens", str(g)]) == 1
    assert "peak-memory-regression" in capsys.readouterr().out

    for entry in data["budgets"].values():
        entry["peak_bytes"] = entry["peak_bytes"] * 2
        entry["digest"] = "0" * 16
        entry["ops"] = {}
    g.write_text(json.dumps(data))
    rc = main(base + ["--goldens", str(g)])
    out = capsys.readouterr().out
    assert rc == 0  # drift is a warning, not a gate
    assert "jaxpr-drift" in out and "op-level diff" in out


def test_cli_hbm_budget_json_exit_1(capsys):
    rc = main(["--model", MODEL, "--seq-len", "256", "--hbm-gb",
               "0.001", "--format", "json"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["errors"] >= 1 and out["new_errors"] >= 1
    assert any(f["rule"] == "hbm-over-budget" for f in out["findings"])
    assert out["breakdown"]["per_device"]["high_water_bytes"] > 0
    assert all("peak_bytes" in e for e in out["executables"])


def test_cli_suppress_needs_known_rule_and_justification(capsys):
    assert main(["--model", MODEL, "--suppress", "not-a-rule=x"]) == 2
    assert main(["--model", MODEL, "--suppress", "hbm-over-budget="]) == 2
    capsys.readouterr()
    rc = main(["--model", MODEL, "--seq-len", "256", "--hbm-gb", "0.001",
               "--suppress", "hbm-over-budget=lab box, budget tracked"])
    assert rc == 0
    assert "suppressed: hbm-over-budget (lab box" in capsys.readouterr().out


def test_cli_usage_errors_exit_2(capsys):
    assert main([]) == 2  # no --model/--config
    assert main(["--model", "no-such-model-xyz"]) == 2
    assert main(["--model", MODEL, "--goldens", "/no/such/file.json"]) == 2
    err = capsys.readouterr().err
    assert "mdi-flow:" in err


def test_cli_list_checks_covers_registry(capsys):
    assert main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for rule in FLOW_RULES:
        assert rule in out


def test_cli_help_covers_flags(capsys):
    with pytest.raises(SystemExit):
        main(["--help"])
    text = capsys.readouterr().out
    for flag in ("--model", "--config", "--tp", "--pp", "--seq-len",
                 "--dtype", "--quantize", "--block-size", "--max-batch",
                 "--prefill-chunk", "--token-budget", "--decode-chunk",
                 "--spec-k", "--kv-dtype", "--sequential", "--hbm-gb",
                 "--min-bytes", "--goldens", "--update-goldens",
                 "--golden-tolerance", "--suppress", "--baseline",
                 "--update-baseline", "--format", "--list-checks"):
        assert flag in text, f"{flag} missing from mdi-flow --help"


# ---------------------------------------------------------------------------
# mdi-check: the aggregate gate
# ---------------------------------------------------------------------------


def test_check_self_check_all_families_clean(monkeypatch, capsys):
    """The tier-1 aggregate self-check: lint + audit + ir + flow over
    the registry model, one engine trace shared by ir/flow, exit 0."""
    monkeypatch.chdir(REPO)  # default goldens + lint baseline resolve
    rc = check_main(["--model", MODEL, "--seq-len", "256"])
    out = capsys.readouterr().out
    assert rc == 0, out
    for family in ("lint", "audit", "ir", "flow"):
        assert f"{family:<6} clean" in out
    assert "check: PASS" in out


def test_check_json_report_and_skip(monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    rc = check_main(["--model", MODEL, "--seq-len", "256", "--skip",
                     "lint", "--skip", "audit", "--format", "json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert set(out["families"]) == {"ir", "flow"}
    assert out["errors"] == 0
    assert out["families"]["flow"]["peak_bytes"]


def test_check_usage_error_exit_2(capsys):
    assert check_main([]) == 2  # families need --model/--config
    assert "mdi-check:" in capsys.readouterr().err


def test_check_list_checks_spans_all_families(capsys):
    assert check_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for family in ("lint:", "audit:", "ir:", "flow:"):
        assert family in out
    for rule in FLOW_RULES:
        assert f"flow:{rule}" in out


def test_check_help_covers_flags(capsys):
    with pytest.raises(SystemExit):
        check_main(["--help"])
    text = capsys.readouterr().out
    for flag in ("--model", "--config", "--tp", "--pp", "--hbm-gb",
                 "--goldens", "--skip", "--paths", "--lint-baseline",
                 "--format", "--list-checks"):
        assert flag in text, f"{flag} missing from mdi-check --help"


# ---------------------------------------------------------------------------
# mdi-ir satellite: --const-bytes counts bytes per device
# ---------------------------------------------------------------------------


def test_ir_const_bytes_flag_and_alias():
    from mdi_llm_tpu.analysis.ir import build_parser

    ap = build_parser()
    assert ap.parse_args(
        ["--model", MODEL, "--const-bytes", "123"]
    ).max_const_bytes == 123
    assert ap.parse_args(
        ["--model", MODEL, "--max-const-bytes", "456"]
    ).max_const_bytes == 456


def test_ir_const_bloat_counts_per_device_bytes(devices):
    """A baked constant sharded over tp=2 counts HALF per device: at a
    threshold between half and full size the per-device count passes
    where the global count would have flagged."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mdi_llm_tpu.analysis.ir import analyze_executables, sharding_denom
    from mdi_llm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 2}, jax.devices()[:2])
    leaf = sds((4, 8), f32, sharding=NamedSharding(mesh, P(None, "tp")))
    assert sharding_denom(leaf) == 2
    assert sharding_denom(sds((4, 8), f32)) == 1

    big = jax.device_put(
        np.arange(4096, dtype=np.float32).reshape(4, 1024),
        NamedSharding(mesh, P(None, "tp")),
    )  # 16 KiB global, 8 KiB per device
    spec = ExecutableSpec(
        "bloat", (), jax.jit(lambda a: a + big), (sds((4, 1024), f32),),
        None, (),
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      max_const_bytes=12 * 1024)
    assert findings == []  # 8 KiB/device under the 12 KiB threshold
    findings, _ = analyze_executables([spec], origin="t",
                                      max_const_bytes=4 * 1024)
    assert rules_of(findings) == ["baked-constant-bloat"]
    assert "per device" in findings[0].message
