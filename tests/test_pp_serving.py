"""Pipeline-parallel serving: the recurrent stage ring under the
continuous-batching engine must reproduce the single-device engine — and
sequential `Generator.generate` — token-for-token across every serving
feature (unified mixed steps, chunked decode, speculative verify,
preemption/resume, prefix caching), with the host-sync cadence
bit-identical, zero post-warmup recompiles, and per-stage pool shards
whose bytes match mdi-audit's static estimate exactly.

The ring is a manual-pp shard_map region, so these tests run wherever
either shard_map generation exists (`jax.shard_map`, or the experimental
one on older builds — pp-only rings are fully manual and work on both).
Composing tp x pp needs the modern API: on old builds the engine refuses
actionably and the composed parity test skips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.serving.pipeline import PipelinedServingEngine, _shard_map_api
from mdi_llm_tpu.utils.profiling import CompileGuard
from tests.test_model import tiny_config

HAS_RING = _shard_map_api() is not None
NEW_API = _shard_map_api() == "new"

ring = pytest.mark.skipif(
    not HAS_RING,
    reason="no shard_map in this jax build (the stage ring cannot run)",
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def single_gen(model):
    cfg, params = model
    return Generator(cfg, params, cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def pp_gen(model, devices):
    cfg, params = model
    return Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"pp": 2}, devices[:2]),
    )


def _trace(cfg, lengths, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, int(n)).tolist() for n in lengths]


def _run_engine(gen, prompts, max_news, **knobs):
    engine = gen.serve(**knobs)
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    return results, stats, engine


def _sequential_greedy(gen, prompts, max_news):
    return [
        gen.generate([p], m, temperature=0.0)[0][0]
        for p, m in zip(prompts, max_news)
    ]


@ring
@pytest.mark.smoke
def test_pp_engine_matches_single_engine_and_generate(model, single_gen,
                                                      pp_gen):
    """The acceptance contract: a mixed-length trace whose 33-token prompt
    splits across several unified mixed steps — the staged engine's
    streams equal BOTH the single-device engine's and sequential
    generate()'s, and the host-sync cadence is IDENTICAL (same step
    counts: the ring changes device math only, never dispatch)."""
    cfg, _ = model
    prompts = _trace(cfg, (3, 9, 17, 5, 33))
    max_news = [8, 12, 6, 10, 7]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=16, token_budget=12)
    want_gen = _sequential_greedy(single_gen, prompts, max_news)
    want, base_stats, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, engine = _run_engine(pp_gen, prompts, max_news, **knobs)
    for i in range(len(prompts)):
        assert got[f"r{i}"] == want[f"r{i}"], f"r{i} diverged from engine"
        assert got[f"r{i}"] == want_gen[i], f"r{i} diverged from generate()"
    # host-sync cadence parity, not just token parity
    assert stats.mixed_steps == base_stats.mixed_steps
    assert stats.decode_steps == base_stats.decode_steps
    assert stats.host_syncs == base_stats.host_syncs
    assert stats.requests_finished == len(prompts)
    assert isinstance(engine, PipelinedServingEngine)
    assert engine.n_stages == 2
    # the pool really is staged: leading stage axis laid out over pp
    assert "pp" in str(engine._kv["k"].sharding.spec)
    assert engine.pool.used == 0


@ring
@pytest.mark.parametrize("chunk,buffered", [(4, True), (8, False)],
                         ids=["k4-buffered", "k8-nobuf"])
def test_pp_chunked_decode_token_identical(model, single_gen, pp_gen,
                                           chunk, buffered):
    """The recurrent ring proper: K decode steps circle the stages in ONE
    jitted call (relaunch-on-return), double-buffered or not —
    token-identical, same sync amortization as the flat engine."""
    cfg, _ = model
    prompts = _trace(cfg, (3, 9, 17))
    max_news = [8, 12, 6]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8,
                 decode_chunk=chunk, double_buffer=buffered)
    want, base_stats, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, _ = _run_engine(pp_gen, prompts, max_news, **knobs)
    assert got == want
    assert stats.host_syncs == base_stats.host_syncs
    assert stats.tokens_per_sync > 1.0


@ring
def test_pp_speculative_serving_token_identical(model, single_gen, pp_gen):
    """Batched n-gram speculative verify rides the ring's grouped sweep
    and stays exact — drafts still accept."""
    cyc = [np.random.default_rng(s).integers(1, tiny_config().vocab_size,
                                             5).tolist() for s in (5, 7, 0)]
    max_news = [30, 25, 20]
    knobs = dict(block_size=4, max_batch=3, decode_chunk=4, spec_k=4)
    want, _, _ = _run_engine(single_gen, cyc, max_news, **knobs)
    got, stats, _ = _run_engine(pp_gen, cyc, max_news, **knobs)
    assert got == want
    assert stats.spec_drafted > 0 and stats.spec_accepted > 0


@ring
def test_pp_preemption_resume_parity(model, single_gen, pp_gen):
    """A pool sized to force recompute preemption: victims resume and
    re-feed through the staged mixed step, outputs exact, every stage's
    pool shard drained."""
    cfg, _ = model
    prompts = _trace(cfg, (9, 13, 11), seed=9)
    max_news = [10, 10, 10]
    knobs = dict(block_size=4, max_batch=3, max_blocks=1 + 10,
                 prefix_caching=False, decode_chunk=4)
    want, _, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, engine = _run_engine(pp_gen, prompts, max_news, **knobs)
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    assert got == want
    assert engine.pool.used == 0


@ring
def test_pp_prefix_cache_hits_parity(model, single_gen, pp_gen):
    """Copy-free prefix reuse under pp: a block id indexes every stage's
    shard at once, so reuse moves no bytes on ANY stage — hits fire and
    the output matches the sequential run."""
    cfg, _ = model
    head = _trace(cfg, (21,), seed=7)[0]
    engine = pp_gen.serve(block_size=4, max_batch=2)
    engine.add_request("first", head, 6)
    engine.run()
    tail = head + [7, 8]
    engine.add_request("second", tail, 6)
    results, stats = engine.run()
    assert stats.prefix_cache_hits >= 5  # 21-token head -> 5 full blocks
    assert results["second"] == _sequential_greedy(single_gen, [tail], [6])[0]


@pytest.mark.skipif(not NEW_API, reason=(
    "composed tp x pp needs the modern jax.shard_map (partial-auto rings "
    "crash this older XLA's SPMD partitioner)"))
def test_tp_pp_composed_token_identical(model, single_gen, devices):
    """tp=2 x pp=2 on 4 devices: the ring stays manual over pp while
    GSPMD lays out each stage's matmuls over tp — streams still exact."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"tp": 2, "pp": 2}, devices[:4]))
    prompts = _trace(cfg, (3, 9, 17))
    max_news = [6, 8, 5]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=16,
                 token_budget=12, decode_chunk=4)
    want, _, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, _, engine = _run_engine(gen, prompts, max_news, **knobs)
    assert got == want
    spec = str(engine._kv["k"].sharding.spec)
    assert "pp" in spec and "tp" in spec


@pytest.mark.skipif(NEW_API, reason=(
    "modern jax.shard_map present: composed tp x pp is supported, the "
    "old-build refusal gate does not apply"))
def test_tp_pp_composed_refused_on_old_shard_map(model, devices):
    """On builds with only the experimental shard_map, composing tp with
    pp must refuse AT ENGINE CONSTRUCTION with the upgrade path named —
    the partial-auto ring would abort the whole process inside XLA."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"tp": 2, "pp": 2}, devices[:4]))
    with pytest.raises(ValueError, match="composed tp x pp"):
        gen.serve(block_size=4, max_batch=2)


def test_pp_serve_routing_and_refusals(model, devices):
    """Generator.serve() routes pp>=2 meshes to the pipelined engine;
    unsupported axes and the kernel path refuse actionably at serve
    time."""
    cfg, params = model
    # dp alongside pp: refused, axis named
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"dp": 2, "pp": 2}, devices[:4]))
    with pytest.raises(ValueError, match="dp"):
        gen.serve(block_size=4, max_batch=2)
    if not HAS_RING:
        return
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"pp": 2}, devices[:2]))
    # Pallas kernels are not wired through the ring
    with pytest.raises(ValueError, match="use_kernel"):
        gen.serve(block_size=4, max_batch=2, use_kernel=True)
    engine = gen.serve(block_size=4, max_batch=2)
    assert isinstance(engine, PipelinedServingEngine)
    fill = engine.pipeline_fill()
    assert fill["stages"] == 2 and fill["lanes"] == 2
    assert fill["bubble_fraction"] == 0.0
    assert sum(fill["stage_layers"]) == cfg.n_layer


def test_pp_stage_split_refused_when_too_few_layers(model, devices):
    """More stages than layers cannot split: the engine refuses with the
    layer arithmetic spelled out (stage_layers' actionable error)."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"pp": 4}, devices[:4]))
    with pytest.raises(ValueError, match="cannot split 3 layers over 4"):
        gen.serve(block_size=4, max_batch=4)


@ring
def test_pp_pool_bytes_match_audit_estimate(model, pp_gen, devices):
    """mdi-audit's per-stage pool estimate must equal the LIVE staged
    pool byte-for-byte: the analytic total, the per-stage share, and the
    bytes actually resident on one stage's device."""
    from mdi_llm_tpu.analysis.audit import preflight
    from mdi_llm_tpu.config import ServingConfig

    cfg, _ = model
    sv = ServingConfig(block_size=4, max_batch=3, prefill_chunk=8)
    report = preflight(cfg, pp=2, batch=3, seq_len=128,
                       cache_dtype="float32", serving=sv)
    assert not report.errors
    pool = report.breakdown["kv_pool"]
    engine = pp_gen.serve(serving=sv)
    leaves = jax.tree_util.tree_leaves(engine._kv)
    live_total = sum(int(x.nbytes) for x in leaves)
    dev0 = devices[0]
    live_dev = sum(
        int(s.data.nbytes)
        for x in leaves for s in x.addressable_shards if s.device == dev0
    )
    assert pool["pp"] == 2
    assert pool["stage_layers"] == [1, 2]
    assert pool["pool_bytes"] == live_total
    assert pool["pool_bytes_per_stage"] == live_total // 2 == live_dev
    assert pool["pool_bytes_per_device"] == live_dev
    # the per-device HBM budget line uses the staged number too
    assert report.breakdown["per_device"]["kv_bytes"] == live_dev


def test_audit_flags_pipeline_underfill_and_bad_stage_split():
    """Static twins of the runtime behavior: max_batch < pp warns with
    the bubble fraction; pp > n_layer is a bad-serving-mesh error."""
    from mdi_llm_tpu.analysis.audit import audit_plan
    from mdi_llm_tpu.analysis.plan import MeshSpec, PlanSpec
    from mdi_llm_tpu.config import ServingConfig

    cfg = tiny_config(block_size=128)  # n_layer=3
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"pp": 2}),
        serving=ServingConfig(block_size=4, max_batch=1),
    ))
    under = [f for f in r.findings if f.rule == "pipeline-underfill"]
    assert under and "50%" in under[0].message
    ringinfo = r.breakdown["serving_ring"]
    assert ringinfo["stages"] == 2 and ringinfo["lanes"] == 1
    assert ringinfo["bubble_fraction"] == 0.5

    # saturated plan: no underfill finding
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"pp": 2}),
        serving=ServingConfig(block_size=4, max_batch=4),
    ))
    assert not [f for f in r.findings if f.rule == "pipeline-underfill"]

    # unstageable split: pp exceeds layers
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"pp": 8}),
        serving=ServingConfig(block_size=4, max_batch=8),
    ))
    assert any(f.rule == "bad-serving-mesh" and "pp=8" in f.message
               for f in r.findings)


def test_preempt_latest_kicks_lowest_priority_not_newest():
    """Priority-inversion guard under pool pressure: preemption victims
    are chosen lowest-priority-first, recency only breaking ties — a
    high-priority stream admitted LAST must survive while the older
    low-priority lane yields."""
    from mdi_llm_tpu.serving.kv_pool import KVPool
    from mdi_llm_tpu.serving.scheduler import Request, Scheduler

    pool = KVPool(32, 4)
    sched = Scheduler(pool, max_batch=3, prefill_chunk=8, max_seq_length=64)
    sched.add(Request(rid="low", prompt=[1] * 6, max_new_tokens=4,
                      priority=0))
    sched.add(Request(rid="high", prompt=[2] * 6, max_new_tokens=4,
                      priority=5))
    kind, _ = sched.next_batch(32)  # admits both (FCFS: low first)
    assert kind == "mixed"
    running = {s.req.rid: s for s in sched.running()}
    assert set(running) == {"low", "high"}
    # the high-priority lane is the NEWEST admission — the old pure
    # recency rule would have evicted it here
    assert running["high"].admit_order > running["low"].admit_order
    assert sched.preempt_latest()
    assert [s.req.rid for s in sched.running()] == ["high"]
    assert sched.preempted and sched.preempted[0][0].rid == "low"
    # within one priority class the rule reduces to recency: the newest
    # equal-priority lane yields (least paid-for KV to recompute)
    pool2 = KVPool(32, 4)
    sched2 = Scheduler(pool2, max_batch=2, prefill_chunk=8,
                       max_seq_length=64)
    sched2.add(Request(rid="a", prompt=[1] * 6, max_new_tokens=4,
                       priority=5))
    sched2.add(Request(rid="b", prompt=[2] * 6, max_new_tokens=4,
                       priority=5))
    sched2.next_batch(32)
    assert sched2.preempt_latest()
    assert [s.req.rid for s in sched2.running()] == ["a"]
    assert sched2.preempted[0][0].rid == "b"


@ring
def test_pp_engine_zero_postwarmup_recompiles(model, devices):
    """The acceptance criterion's CompileGuard half: a warmup engine and
    its timed twin on ONE pp Generator share the ring jit cache, and the
    timed run neither re-traces nor re-compiles — the staged pool pin
    survives donation round-trips."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"pp": 2}, devices[:2]))
    prompts = _trace(cfg, (3, 9, 17))
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8, decode_chunk=4)

    def drive(engine):
        for i, p in enumerate(prompts):
            engine.add_request(f"r{i}", p, 8)
        engine.run()

    guard = CompileGuard(label="pp-serve")
    with guard:
        drive(gen.serve(**knobs))
        guard.mark_warm()
        drive(gen.serve(**knobs))
    assert guard.traces_after_warmup == 0
    assert guard.backend_compiles_after_warmup == 0
    guard.expect_clean()


def test_cli_help_covers_pp_flags():
    """Both serving front-ends and the benchmark document the new
    pipeline-parallel knob."""
    import bench
    from mdi_llm_tpu.cli.serve import build_parser as serve_parser

    serve_help = serve_parser().format_help()
    assert "--pp" in serve_help and "pipeline-parallel" in serve_help
    bench_help = bench.build_parser().format_help()
    assert "--pp" in bench_help and "pipeline" in bench_help
