"""Checkpoint pipeline tests.

Golden parity: build tiny HF models with `transformers` (torch CPU), save
them as real HF snapshots, convert with `convert_hf_checkpoint`, and require
logit agreement between the JAX forward and the torch forward — this pins
the QKV interleave, weight transposes, norm semantics, and RoPE convention
against an independent public implementation (NOT the reference repo).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import forward, init_params
from mdi_llm_tpu.parallel.partition import (
    split_params,
    stage_layers,
    save_stage_manifest,
)
from mdi_llm_tpu.utils.checkpoint import (
    convert_hf_checkpoint,
    convert_to_hf_state_dict,
    load_checkpoint,
    save_checkpoint,
)
from tests.test_model import tiny_config


def test_orbax_roundtrip(tmp_path):
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_checkpoint(params, cfg, tmp_path / "ckpt")
    cfg2, params2 = load_checkpoint(tmp_path / "ckpt")
    assert cfg2.n_layer == cfg.n_layer
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.smoke
@pytest.mark.parametrize("gqa", [False, True])
def test_hf_llama_logit_parity(tmp_path, gqa):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2 if gqa else 4,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")

    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.n_query_groups == (2 if gqa else 4)

    toks = np.array([[1, 5, 9, 44, 63, 2, 17]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=2e-4, atol=2e-4)


def test_hf_gpt2_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=96,
        n_positions=64,
        n_embd=32,
        n_layer=2,
        n_head=4,
    )
    torch.manual_seed(1)
    model = GPT2LMHeadModel(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")

    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.pos_embedding == "learned" and cfg.tie_embeddings

    toks = np.array([[4, 7, 2, 90, 31]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=2e-4, atol=2e-4)


def test_hf_neox_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=96,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        rotary_pct=0.25,
        max_position_embeddings=64,
        use_parallel_residual=True,
    )
    torch.manual_seed(2)
    model = GPTNeoXForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")

    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)

    toks = np.array([[4, 7, 2, 90, 31, 8]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=3e-4, atol=3e-4)


def test_hf_falcon_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=96,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        new_decoder_architecture=False,
        multi_query=True,
        parallel_attn=True,
        bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(4)
    model = FalconForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.n_query_groups == 1 and cfg.shared_attention_norm

    toks = np.array([[4, 7, 2, 90, 31, 8]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=3e-4, atol=3e-4)


def test_hf_phi_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        partial_rotary_factor=0.5,
        max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = PhiForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.shared_attention_norm and cfg.lm_head_bias and cfg.rotary_percentage == 0.5

    toks = np.array([[4, 7, 2, 90, 31]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=3e-4, atol=3e-4)


def test_hf_gemma_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        head_dim=8,
        max_position_embeddings=64,
    )
    torch.manual_seed(6)
    model = GemmaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.tie_embeddings and cfg.scale_embeddings and cfg.rmsnorm_add_unit_offset

    toks = np.array([[4, 7, 2, 90, 31]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=5e-4, atol=5e-4)


def test_hf_mixtral_moe_logit_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(7)
    model = MixtralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.n_expert == 4 and cfg.n_expert_per_token == 2

    toks = np.array([[4, 7, 2, 90, 31, 11]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=3e-4, atol=3e-4)


def test_hf_falcon_new_decoder_logit_parity(tmp_path):
    """Falcon 40b/180B layout: new_decoder_architecture (GQA, two norms:
    ln_attn + ln_mlp) — reference convert_hf_checkpoint.py:88-94."""
    torch = pytest.importorskip("torch")
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=96,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_kv_heads=2,
        new_decoder_architecture=True,
        bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(8)
    model = FalconForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    assert cfg.n_query_groups == 2 and not cfg.shared_attention_norm

    toks = np.array([[4, 7, 2, 90, 31, 8]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(toks)).logits.numpy()
    got, _ = forward(cfg, params, jnp.asarray(toks, jnp.int32), jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got)[..., : hf_cfg.vocab_size], ref, rtol=3e-4, atol=3e-4
    )


def _assert_reverse_roundtrip(model, tmp_path, allow_missing=()):
    """HF model → native → HF state dict must reproduce the original tensors
    bit-exactly for every key the reverse map emits."""
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    sd = convert_to_hf_state_dict(cfg, params)
    assert sd, "reverse conversion produced nothing"
    ref_sd = {k: v.numpy() for k, v in model.state_dict().items()}
    for k, v in sd.items():
        np.testing.assert_array_equal(v, ref_sd[k], err_msg=k)
    # nothing real was dropped: every original tensor is covered except
    # non-weight buffers and the explicitly allowed (tied) entries
    missing = set(ref_sd) - set(sd)
    for k in missing:
        assert (
            "rotary" in k or "masked_bias" in k or ".attn.bias" in k
            or k in allow_missing
        ), f"reverse map silently dropped {k}"


def test_reverse_roundtrip_neox(tmp_path):
    """≡ reference copy_weights_gpt_neox (convert_lit_checkpoint.py:77-110)."""
    torch = pytest.importorskip("torch")
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128, rotary_pct=0.25,
        max_position_embeddings=64, use_parallel_residual=True,
    )
    torch.manual_seed(12)
    _assert_reverse_roundtrip(GPTNeoXForCausalLM(hf_cfg).eval(), tmp_path)


@pytest.mark.parametrize("new_arch", [False, True])
def test_reverse_roundtrip_falcon(tmp_path, new_arch):
    """≡ reference copy_weights_falcon (convert_lit_checkpoint.py:15-74),
    both the 7b and the 40b/180B (new_decoder_architecture) layouts."""
    torch = pytest.importorskip("torch")
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, bias=False, tie_word_embeddings=False,
        new_decoder_architecture=new_arch,
        **({"num_kv_heads": 2} if new_arch else {"multi_query": True, "parallel_attn": True}),
    )
    torch.manual_seed(13)
    _assert_reverse_roundtrip(FalconForCausalLM(hf_cfg).eval(), tmp_path)


def test_reverse_roundtrip_phi(tmp_path):
    """≡ reference copy_weights_phi (convert_lit_checkpoint.py:168-220)."""
    torch = pytest.importorskip("torch")
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, partial_rotary_factor=0.5,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(14)
    _assert_reverse_roundtrip(PhiForCausalLM(hf_cfg).eval(), tmp_path)


def test_reverse_roundtrip_gpt2(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    torch.manual_seed(15)
    # gpt2 ties lm_head to wte: the reverse map emits only the embedding
    _assert_reverse_roundtrip(
        GPT2LMHeadModel(hf_cfg).eval(), tmp_path, allow_missing={"lm_head.weight"}
    )


def test_reverse_conversion_roundtrip(tmp_path):
    """convert_to_hf_state_dict must invert the fused layout exactly."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf")
    out_dir = convert_hf_checkpoint(tmp_path / "hf", dtype=jnp.float32)
    cfg, params = load_checkpoint(out_dir)
    sd = convert_to_hf_state_dict(cfg, params)
    ref_sd = {k: v.numpy() for k, v in model.state_dict().items()}
    for k, v in sd.items():
        np.testing.assert_array_equal(v, ref_sd[k], err_msg=k)


# ---- partition policy ------------------------------------------------------


def test_stage_layers_reference_parity():
    """Hand-tuned reference table entries (config.py:56-98) survive."""
    assert stage_layers(22, 3) == [6, 8, 8]
    assert stage_layers(32, 3) == [8, 12, 12]
    assert stage_layers(48, 2) == [22, 26]
    assert stage_layers(12, 1) == [12]
    assert stage_layers(22, 5) == [2, 5, 5, 5, 5]


def test_stage_layers_generalizes():
    for n_layer, n_stages in [(80, 8), (32, 6), (22, 7), (10, 10), (100, 3)]:
        counts = stage_layers(n_layer, n_stages)
        assert sum(counts) == n_layer
        assert all(c >= 1 for c in counts)
        assert counts[0] <= max(counts)  # starter never the heaviest


def test_split_params_slices(tmp_path):
    cfg = tiny_config(n_layer=5)
    params = init_params(cfg, jax.random.PRNGKey(0))
    stages = split_params(cfg, params, 3)
    assert [s["blocks"]["norm_1"]["weight"].shape[0] for s in stages] == stage_layers(5, 3)
    assert "wte" in stages[0] and "ln_f" in stages[0]
    assert "wte" not in stages[1] and "ln_f" not in stages[2]
    # stage blocks concatenated == original
    cat = np.concatenate([np.asarray(s["blocks"]["attn"]["qkv"]["weight"]) for s in stages])
    np.testing.assert_array_equal(cat, np.asarray(params["blocks"]["attn"]["qkv"]["weight"]))
    p = save_stage_manifest(tmp_path, cfg, 3)
    assert json.loads(p.read_text())["stage_layers"] == stage_layers(5, 3)
