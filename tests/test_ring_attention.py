"""Ring attention (sequence parallelism) correctness.

Pins: (1) the ring op itself matches dense causal attention with the
sequence sharded over 4 devices; (2) a full sp-sharded forward matches the
unsharded forward; (3) dp×sp training matches single-device training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.models import init_params, transformer
from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.ring_attention import ring_attention
from mdi_llm_tpu.parallel.mesh import make_mesh
from tests.test_model import tiny_config


@pytest.mark.parametrize("groups", [4, 2])
def test_ring_matches_dense(devices, groups):
    B, H, T, hs = 2, 4, 32, 8
    P_sp = 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, H, T, hs), jnp.float32)
    k = jax.random.normal(k2, (B, groups, T, hs), jnp.float32)
    v = jax.random.normal(k3, (B, groups, T, hs), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    dense = multihead_attention(q, k, v, pos)

    mesh = make_mesh({"sp": P_sp}, devices[:P_sp])
    ring = jax.jit(
        jax.shard_map(
            lambda q, k, v, qp, kp: ring_attention(q, k, v, qp, kp, "sp"),
            mesh=mesh,
            in_specs=(
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, "sp"),
                P(None, "sp"),
            ),
            out_specs=P(None, None, "sp", None),
        )
    )
    got = ring(q, k, v, pos, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(dense), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("groups", [4, 2])
def test_ring_flash_diagonal_matches_dense(devices, groups):
    """use_flash=True routes each device's own (diagonal, causal) chunk
    through the Pallas kernel and seeds the ring carry from its (out, lse);
    values must still match dense causal attention."""
    B, H, T, hs = 2, 4, 32, 8
    P_sp = 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(k1, (B, H, T, hs), jnp.float32)
    k = jax.random.normal(k2, (B, groups, T, hs), jnp.float32)
    v = jax.random.normal(k3, (B, groups, T, hs), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    dense = multihead_attention(q, k, v, pos)

    mesh = make_mesh({"sp": P_sp}, devices[:P_sp])
    ring = jax.jit(
        jax.shard_map(
            lambda q, k, v, qp, kp: ring_attention(
                q, k, v, qp, kp, "sp", use_flash=True, flash_interpret=True
            ),
            mesh=mesh,
            in_specs=(
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, "sp"),
                P(None, "sp"),
            ),
            out_specs=P(None, None, "sp", None),
            # interpret-mode pallas can't satisfy the vma checker (its HLO
            # interpreter mixes varied operands with fresh iota constants)
            check_vma=False,
        )
    )
    got = ring(q, k, v, pos, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_ring_flash_grads_match_plain_ring(devices):
    """Gradients through the flash-seeded ring (lse cotangent folded into
    the FA-2 backward) equal the einsum ring's gradients."""
    B, H, T, hs = 1, 2, 16, 8
    P_sp = 2
    ks = jax.random.split(jax.random.PRNGKey(5), 4)
    q = jax.random.normal(ks[0], (B, H, T, hs), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, T, hs), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, T, hs), jnp.float32)
    co = jax.random.normal(ks[3], (B, H, T, hs), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    mesh = make_mesh({"sp": P_sp}, devices[:P_sp])

    def make_loss(use_flash):
        sm = jax.shard_map(
            lambda q, k, v, qp, kp: ring_attention(
                q, k, v, qp, kp, "sp",
                use_flash=use_flash, flash_interpret=True,
            ),
            mesh=mesh,
            in_specs=(
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, None, "sp", None),
                P(None, "sp"),
                P(None, "sp"),
            ),
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )
        return lambda q, k, v: jnp.sum(sm(q, k, v, pos, pos) * co)

    want = jax.grad(make_loss(False), argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(make_loss(True), argnums=(0, 1, 2))(q, k, v)
    for name, w, g in zip("qkv", want, got):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_sp_training_step_traces_flash_kernel(devices):
    """An sp long-context training step with use_flash=True demonstrably
    runs the flash kernel: its gradient jaxpr contains the pallas_calls."""
    from mdi_llm_tpu.training import Trainer, TrainingConfig

    cfg = tiny_config(block_size=64, n_layer=2)
    mesh = make_mesh({"dp": 1, "sp": 4}, devices[:4])
    tc = TrainingConfig(batch_size=2, block_size=32, grad_acc_steps=1,
                        dtype="float32", max_iters=1, use_flash=True)
    tr = Trainer(cfg, tc, mesh=mesh)
    xs = np.zeros((1, 2, 32), np.int32)
    txt = str(
        jax.make_jaxpr(lambda p, x, y: jax.grad(
            lambda pp: tr._sp_loss_fn()(pp, x, y)
        )(p))(tr.params, xs[0], xs[0])
    )
    assert "pallas_call" in txt


def test_sp_forward_matches_dense(devices):
    """Full transformer forward with sequence sharded over 4 devices."""
    cfg = tiny_config(block_size=64, n_layer=3)
    params = init_params(cfg, jax.random.PRNGKey(1))
    B, T = 2, 32
    toks = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)

    want, _ = transformer.forward(cfg, params, toks, jnp.zeros((B,), jnp.int32))

    mesh = make_mesh({"sp": 4}, devices[:4])
    repl = jax.tree_util.tree_map(lambda _: P(), params)

    def local(params, x):
        start = jax.lax.axis_index("sp") * x.shape[1]
        ip = jnp.full((x.shape[0],), start, jnp.int32)
        logits, _ = transformer.forward(cfg, params, x, ip, sp_axis="sp")
        return logits

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(repl, P(None, "sp")), out_specs=P(None, "sp")
        )
    )
    got = f(params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize(
    "axes", [{"dp": 2, "sp": 4}, {"dp": 2, "sp": 2, "tp": 2}]
)
def test_sp_training_matches_single_device(axes, devices):
    """Ring-attention training parity vs unmeshed — including the 3D
    dp×sp×tp composition (ring manual over dp/sp, Megatron-sharded
    matmuls on the auto tp axis)."""
    from mdi_llm_tpu.training import Trainer
    from tests.test_training import small_tc, toy_data
    from mdi_llm_tpu.utils import data_loader

    cfg = tiny_config(block_size=32, n_layer=2)
    data = toy_data(1024)

    def run(mesh):
        tc = small_tc(grad_acc_steps=1, block_size=32, batch_size=4)
        tr = Trainer(cfg, tc, mesh=mesh)
        rng = np.random.default_rng(3)
        losses = []
        for _ in range(3):
            x, y = data_loader.get_batch(data, tc.batch_size, tc.block_size, rng)
            losses.append(tr.train_step(x[None], y[None]))
        return losses, tr

    base_losses, base_tr = run(None)
    base = jax.tree_util.tree_map(np.asarray, base_tr.params)
    sp_losses, sp_tr = run(make_mesh(axes, devices))
    sp = jax.tree_util.tree_map(np.asarray, sp_tr.params)
    np.testing.assert_allclose(base_losses, sp_losses, rtol=2e-4)
    for a, b in zip(jax.tree_util.tree_leaves(base), jax.tree_util.tree_leaves(sp)):
        np.testing.assert_allclose(a, b, rtol=3e-4, atol=3e-5)
    if "tp" in axes:
        # Megatron sharding actually engaged on the auto tp axis
        qkv = sp_tr.params["blocks"]["attn"]["qkv"]["weight"]
        assert "tp" in str(qkv.sharding.spec)
