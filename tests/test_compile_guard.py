"""CompileGuard (utils/profiling.py): the runtime half of the mdi-lint
story — prove on a live trace that the post-warmup steady state never
builds a new executable, and that the traced-sampling refactor actually
bought what static-float-arg promises: sweeping temperature/top_p reuses
one decode executable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import (
    sample,
    sample_mode,
    sample_traced,
    sampling_operands,
)
from mdi_llm_tpu.utils.profiling import CompileGuard, RecompileError


def test_guard_counts_and_clean_steady_state():
    @jax.jit
    def f(x):
        return x * 2

    g = CompileGuard(label="t")
    with g:
        f(jnp.ones((4,))).block_until_ready()
        g.mark_warm()
        f(jnp.ones((4,))).block_until_ready()
    assert g.traces >= 1
    assert g.traces_after_warmup == 0
    assert g.backend_compiles_after_warmup == 0
    g.expect_clean()  # must not raise
    s = g.summary()
    assert s["traces_after_warmup"] == 0 and s["traces"] == g.traces


def test_guard_flags_post_warmup_recompile():
    @jax.jit
    def f(x):
        return x + 1

    g = CompileGuard(label="t")
    with g:
        f(jnp.ones((4,)))
        g.mark_warm()
        f(jnp.ones((6,)))  # new shape -> retrace
    assert g.traces_after_warmup > 0
    with pytest.raises(RecompileError, match="after warmup"):
        g.expect_clean()


def test_guard_without_warmup_mark_is_lenient():
    g = CompileGuard()
    with g:
        jax.jit(lambda x: x - 1)(jnp.ones((3,)))
    assert g.traces_after_warmup is None
    g.expect_clean()  # no steady-state region declared: no-op


def test_guard_allowance():
    @jax.jit
    def f(x):
        return x * 3

    g = CompileGuard(max_recompiles_after_warmup=8)
    with g:
        g.mark_warm()
        f(jnp.ones((5,)))
    assert g.traces_after_warmup >= 1
    g.expect_clean()  # within the allowance


def test_guards_nest_independently():
    @jax.jit
    def f(x):
        return x * 5

    outer = CompileGuard()
    with outer:
        f(jnp.ones((7,)))
        inner = CompileGuard()
        with inner:
            f(jnp.ones((7,)))  # cached: no new trace
        assert inner.traces == 0
    assert outer.traces >= 1


# ---------------------------------------------------------------------------
# the static-float-arg fix, measured: distinct sampling floats share one
# decode executable; and sample_traced is draw-identical to sample
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,top_p",
    [(0.0, None, None), (0.8, None, None), (0.8, 5, None), (0.7, None, 0.9)],
)
def test_sample_traced_matches_sample(temperature, top_k, top_p):
    key = jax.random.PRNGKey(3)
    logits = jax.random.normal(jax.random.PRNGKey(4), (6, 64)) * 3.0
    want = sample(logits, key, temperature=temperature, top_k=top_k, top_p=top_p)
    t_op, p_op = sampling_operands(temperature, top_p)
    got = sample_traced(
        logits, key, t_op, p_op,
        mode=sample_mode(temperature, top_k, top_p), top_k=top_k,
    )
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_sample_mode_dispatch_order_matches_sample():
    assert sample_mode(0.0, 5, 0.9) == "greedy"      # temperature wins
    assert sample_mode(0.8, 5, 0.9) == "top_p"       # top-p beats top-k
    assert sample_mode(0.8, 5, None) == "top_k"
    assert sample_mode(0.8, None, 1.0) == "top_k"    # top_p=1.0 -> disabled


def _tiny_generator():
    cfg = Config(
        name="lint-tiny", block_size=64, vocab_size=128, n_layer=2, n_head=2,
        n_embd=32, n_query_groups=2, intermediate_size=64,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return Generator(cfg, params, max_seq_length=64)


def test_temperature_sweep_reuses_one_decode_executable():
    """The satellite fix itself: decode at temperature 0.7 then 0.9 (same
    mode, different float) must NOT retrace — before the refactor each
    distinct float was a static arg and compiled its own executable."""
    gen = _tiny_generator()
    prompts = [[5, 9, 2, 7]]
    gen.generate(prompts, 4, temperature=0.7, top_k=None)  # compile everything
    guard = CompileGuard(label="temp-sweep")
    with guard:
        guard.mark_warm()
        gen.generate(prompts, 4, temperature=0.9, top_k=None)
        gen.generate(prompts, 4, temperature=1.3, top_k=None)
    assert guard.traces_after_warmup == 0, (
        "distinct temperatures retraced the decode fn — float knobs leaked "
        "back into the jit cache key"
    )
    guard.expect_clean()


def test_greedy_decode_steady_state_is_compile_free():
    """The bench.py --mode decode contract at test scale: after a warmup
    generate(), an identical generate() performs ZERO jit traces."""
    gen = _tiny_generator()
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]]
    gen.generate(prompts, 6, temperature=0.0)  # warmup
    guard = CompileGuard(label="decode-steady")
    with guard:
        guard.mark_warm()
        out, _ = gen.generate(prompts, 6, temperature=0.0)
    assert len(out) == 2
    assert guard.traces_after_warmup == 0
    guard.expect_clean()
