"""Pallas flash attention: numeric parity with dense attention (interpret
mode on CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.models import forward, init_params, init_kv_cache
from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.flash import flash_attention
from tests.test_model import tiny_config


@pytest.mark.parametrize("groups,T,hs", [(4, 64, 16), (2, 100, 16), (1, 32, 8)])
def test_flash_matches_dense(groups, T, hs):
    B, H = 2, 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, H, T, hs), jnp.float32)
    k = jax.random.normal(k2, (B, groups, T, hs), jnp.float32)
    v = jax.random.normal(k3, (B, groups, T, hs), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    dense = multihead_attention(q, k, v, pos)
    flash = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_fresh_prefill_path_matches_cache_path():
    """forward(fresh_prefill=True) must produce identical logits and caches
    to the default cache-buffer attention path."""
    cfg = tiny_config(block_size=64)
    params = init_params(cfg, jax.random.PRNGKey(1))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 0, cfg.vocab_size)
    ip = jnp.zeros((2,), jnp.int32)

    kv_a = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
    la, kv_a = forward(cfg, params, toks, ip, kv=kv_a)
    kv_b = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
    lb, kv_b = forward(cfg, params, toks, ip, kv=kv_b, fresh_prefill=True)
    # the two paths reduce the softmax in different orders (T×cache vs T×T)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(kv_a["k"]), np.asarray(kv_b["k"]), rtol=1e-6, atol=1e-7
    )
