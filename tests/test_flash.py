"""Pallas flash attention: numeric parity with dense attention (interpret
mode on CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.models import forward, init_params, init_kv_cache
from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.flash import flash_attention
from tests.test_model import tiny_config


@pytest.mark.parametrize("groups,T,hs", [(4, 64, 16), (2, 100, 16), (1, 32, 8)])
def test_flash_matches_dense(groups, T, hs):
    B, H = 2, 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, H, T, hs), jnp.float32)
    k = jax.random.normal(k2, (B, groups, T, hs), jnp.float32)
    v = jax.random.normal(k3, (B, groups, T, hs), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))
    dense = multihead_attention(q, k, v, pos)
    flash = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("groups,T,hs,bq,bk", [
    (4, 64, 16, 32, 32),   # MHA, aligned T
    (2, 100, 16, 32, 32),  # GQA, T not a multiple of the blocks
    (1, 48, 8, 16, 32),    # MQA, mixed block sizes
])
def test_flash_vjp_matches_dense_grads(groups, T, hs, bq, bk):
    """Reverse-mode through the Pallas kernels (FA-2 recompute backward)
    must match the XLA path's gradients for q, k, and v — incl. the GQA
    group-summed dK/dV and odd-T padding."""
    B, H = 2, 4
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(7), 4)
    q = jax.random.normal(k1, (B, H, T, hs), jnp.float32)
    k = jax.random.normal(k2, (B, groups, T, hs), jnp.float32)
    v = jax.random.normal(k3, (B, groups, T, hs), jnp.float32)
    co = jax.random.normal(k4, (B, H, T, hs), jnp.float32)  # cotangent
    pos = jnp.broadcast_to(jnp.arange(T), (B, T))

    def loss_dense(q, k, v):
        return jnp.sum(multihead_attention(q, k, v, pos) * co)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True) * co
        )

    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, w, g in zip("qkv", want, got):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_vjp_value_unchanged():
    """The custom_vjp primal equals the plain forward (no lse overhead)."""
    B, H, T, hs = 1, 2, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, H, T, hs), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, T, hs), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, T, hs), jnp.float32)
    out, f_vjp = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, block_q=16, block_k=16,
                                        interpret=True), q, k, v)
    plain = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(plain), rtol=1e-6, atol=1e-6)
    dq, dk, dv = f_vjp(jnp.ones_like(out))
    assert dq.shape == q.shape and dk.shape == k.shape and dv.shape == v.shape


def test_flash_noncausal_matches_dense():
    """causal=False attends the whole chunk (ring off-diagonal blocks):
    values and gradients vs a plain softmax reference."""
    from mdi_llm_tpu.ops.flash import flash_attention_lse

    B, H, T, hs = 2, 4, 48, 16
    ks = jax.random.split(jax.random.PRNGKey(11), 4)
    q = jax.random.normal(ks[0], (B, H, T, hs), jnp.float32)
    k = jax.random.normal(ks[1], (B, 2, T, hs), jnp.float32)
    v = jax.random.normal(ks[2], (B, 2, T, hs), jnp.float32)
    co = jax.random.normal(ks[3], (B, H, T, hs), jnp.float32)

    def dense(q, k, v):
        qg = q.reshape(B, 2, 2, T, hs)
        s = jnp.einsum("bgqth,bgsh->bgqts", qg, k) / (hs**0.5)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgqts,bgsh->bgqth", p, v)
        return o.reshape(B, H, T, hs), jax.scipy.special.logsumexp(s, axis=-1).reshape(B, H, T)

    def loss_flash(q, k, v):
        o, lse = flash_attention_lse(q, k, v, block_q=16, block_k=16,
                                     interpret=True, causal=False)
        return jnp.sum(o * co) + jnp.sum(lse)

    def loss_dense(q, k, v):
        o, lse = dense(q, k, v)
        return jnp.sum(o * co) + jnp.sum(lse)

    o_f, lse_f = flash_attention_lse(q, k, v, block_q=16, block_k=16,
                                     interpret=True, causal=False)
    o_d, lse_d = dense(q, k, v)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_d), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_d), rtol=2e-5, atol=2e-5)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, w, g in zip("qkv", want, got):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=3e-4, atol=3e-4,
            err_msg=f"d{name} mismatch",
        )


def test_training_step_traces_flash_kernel():
    """A training loss with use_flash=True demonstrably runs the Pallas
    kernel: the jaxpr of its gradient contains the flash pallas_calls (one
    forward + the dQ and dK/dV backward kernels), under remat."""
    from mdi_llm_tpu.training import cross_entropy_loss

    cfg = tiny_config(block_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    x = jnp.zeros((2, 32), jnp.int32)
    y = jnp.zeros((2, 32), jnp.int32)

    def loss(p):
        return cross_entropy_loss(cfg, p, x, y, remat=True, use_flash=True)

    txt = str(jax.make_jaxpr(jax.grad(loss))(params))
    assert txt.count("pallas_call") >= 2  # fwd (recomputed) + bwd kernels
    # and the XLA-path loss must trace clean of it
    def loss_xla(p):
        return cross_entropy_loss(cfg, p, x, y, remat=True, use_flash=False)

    assert "pallas_call" not in str(jax.make_jaxpr(jax.grad(loss_xla))(params))


def test_trainer_use_flash_resolution():
    """TrainingConfig.use_flash=None resolves from the backend; an explicit
    value wins."""
    from mdi_llm_tpu.training import Trainer, TrainingConfig

    cfg = tiny_config(block_size=64)
    tc = TrainingConfig(batch_size=2, block_size=16, max_iters=1,
                        dtype="float32", use_flash=False)
    assert Trainer(cfg, tc).use_flash is False
    tc_auto = TrainingConfig(batch_size=2, block_size=16, max_iters=1,
                             dtype="float32")
    # auto = TPU AND unmeshed AND block_size >= 2048; always off here
    # (CPU backend, and the tiny block_size fails the crossover gate too)
    assert Trainer(cfg, tc_auto).use_flash is False


def test_fresh_prefill_path_matches_cache_path():
    """forward(fresh_prefill=True) must produce identical logits and caches
    to the default cache-buffer attention path."""
    cfg = tiny_config(block_size=64)
    params = init_params(cfg, jax.random.PRNGKey(1))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 0, cfg.vocab_size)
    ip = jnp.zeros((2,), jnp.int32)

    kv_a = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
    la, kv_a = forward(cfg, params, toks, ip, kv=kv_a)
    kv_b = init_kv_cache(cfg, 2, 32, dtype=jnp.float32)
    lb, kv_b = forward(cfg, params, toks, ip, kv=kv_b, fresh_prefill=True)
    # the two paths reduce the softmax in different orders (T×cache vs T×T)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(kv_a["k"]), np.asarray(kv_b["k"]), rtol=1e-6, atol=1e-7
    )
