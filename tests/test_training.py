"""Trainer tests: loss goes down, mesh (dp / dp×tp) equivalence with
single-device training, checkpoint/resume exactness, LR schedule parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.training import Trainer, TrainingConfig, get_lr, lr_schedule
from mdi_llm_tpu.utils import data_loader
from tests.test_model import tiny_config


def toy_data(n=4096, vocab=128, seed=0):
    """Learnable sequence: token t+1 = (t*3 + 1) % vocab with noise-free
    structure so a tiny model's loss drops fast."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab)
    seq = [int(start)]
    for _ in range(n - 1):
        seq.append((seq[-1] * 3 + 1) % vocab)
    return np.asarray(seq, np.uint16)


def small_tc(**kw):
    base = dict(
        batch_size=4,
        block_size=16,
        grad_acc_steps=2,
        learning_rate=1e-2,
        warmup_iters=2,
        lr_decay_iters=100,
        min_lr=1e-3,
        max_iters=30,
        eval_iters=2,
        ckpt_interval=10,
        log_interval=5,
        dtype="float32",
        remat=False,
        seed=10137,
    )
    base.update(kw)
    return TrainingConfig(**base)


def test_lr_schedule_parity():
    tc = small_tc(warmup_iters=10, lr_decay_iters=200)
    sched = lr_schedule(tc)
    for it in [0, 1, 5, 10, 50, 150, 200, 300]:
        assert np.isclose(float(sched(it)), get_lr(it, tc), rtol=1e-6), it


def test_loss_decreases():
    cfg = tiny_config(block_size=32)
    tc = small_tc()
    tr = Trainer(cfg, tc)
    data = toy_data()
    rng = np.random.default_rng(0)
    first = None
    for i in range(25):
        xs = np.empty((tc.grad_acc_steps, tc.batch_size, tc.block_size), np.int32)
        ys = np.empty_like(xs)
        for m in range(tc.grad_acc_steps):
            xs[m], ys[m] = data_loader.get_batch(data, tc.batch_size, tc.block_size, rng)
        loss = tr.train_step(xs, ys)
        if first is None:
            first = loss
    assert loss < first * 0.5, (first, loss)


@pytest.mark.parametrize("axes", [{"dp": 4}, {"dp": 2, "tp": 2}])
def test_mesh_training_matches_single_device(axes, devices):
    """dp and dp×tp sharded training must produce the same params as
    unsharded training (the declarative analog of DDP equivalence)."""
    cfg = tiny_config(block_size=16, n_layer=2)
    data = toy_data(1024)

    def run(mesh):
        tc = small_tc(grad_acc_steps=1)
        tr = Trainer(cfg, tc, mesh=mesh)
        rng = np.random.default_rng(1)
        for _ in range(3):
            x, y = data_loader.get_batch(data, tc.batch_size, tc.block_size, rng)
            tr.train_step(x[None], y[None])
        return jax.tree_util.tree_map(np.asarray, tr.params)

    base = run(None)
    sharded = run(make_mesh(axes, devices))
    flat_a = jax.tree_util.tree_leaves(base)
    flat_b = jax.tree_util.tree_leaves(sharded)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize(
    "axes",
    [{"dp": 1, "pp": 2}, {"dp": 2, "pp": 4}, {"dp": 2, "pp": 2, "tp": 2}],
)
def test_pp_training_matches_single_device(axes, devices):
    """GPipe pipeline-parallel training (stage-sharded blocks, microbatched
    ring) must produce the same params as unsharded training — the padded
    stage layers are exact identities and stay zero through AdamW.  The
    third case is 3D dp×pp×tp: ring manual over dp/pp, Megatron-sharded
    stage matmuls on the auto tp axis."""
    cfg = tiny_config(block_size=16, n_layer=5)
    data = toy_data(1024)
    n_dev = int(np.prod(list(axes.values())))
    batch = max(4, n_dev)  # each dp shard must split into pp microbatches

    def run(mesh):
        tc = small_tc(grad_acc_steps=1, batch_size=batch)
        tr = Trainer(cfg, tc, mesh=mesh)
        rng = np.random.default_rng(1)
        for _ in range(3):
            x, y = data_loader.get_batch(data, tc.batch_size, tc.block_size, rng)
            tr.train_step(x[None], y[None])
        return tr, jax.tree_util.tree_map(np.asarray, tr._standard_params())

    _, base = run(None)
    tr_pp, sharded = run(make_mesh(axes, devices[:n_dev]))
    flat_a, tree_a = jax.tree_util.tree_flatten(base)
    flat_b, tree_b = jax.tree_util.tree_flatten(sharded)
    assert tree_a == tree_b
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
    # eval path agrees too
    rng = np.random.default_rng(2)
    x, y = data_loader.get_batch(data, batch, 16, rng)
    ev_pp = float(tr_pp._eval(tr_pp.params, jnp.asarray(x), jnp.asarray(y)))
    base_tr = Trainer(cfg, small_tc(grad_acc_steps=1))
    # fresh single-device trainer with the PP-trained weights
    base_tr.params = jax.tree_util.tree_map(jnp.asarray, sharded)
    ev_sd = float(base_tr._eval(base_tr.params, jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(ev_pp, ev_sd, rtol=2e-4)


def test_pp_batch_divisibility_guard(devices):
    cfg = tiny_config(block_size=16, n_layer=4)
    with pytest.raises(ValueError, match="divide"):
        Trainer(
            cfg,
            small_tc(batch_size=5),
            mesh=make_mesh({"dp": 1, "pp": 2}, devices[:2]),
        )


def test_pp_save_resume(tmp_path, devices):
    """PP checkpoints are written in the standard stacked layout (interop
    with every other component) and resume repartitions them."""
    cfg = tiny_config(block_size=16, n_layer=4)
    data = toy_data(512)
    mesh = make_mesh({"dp": 1, "pp": 2}, devices[:2])
    tr = Trainer(cfg, small_tc(grad_acc_steps=1), mesh=mesh, out_dir=tmp_path)
    rng = np.random.default_rng(3)
    x, y = data_loader.get_batch(data, 4, 16, rng)
    tr.train_step(x[None], y[None])
    tr.save(tmp_path)
    # standard layout on disk: loadable by the plain checkpoint reader
    from mdi_llm_tpu.utils.checkpoint import load_checkpoint

    _, params = load_checkpoint(tmp_path)
    assert params["blocks"]["attn"]["qkv"]["weight"].shape[0] == cfg.n_layer

    tr2 = Trainer.resume(tmp_path, mesh=mesh)
    l1 = tr.train_step(x[None], y[None])
    l2 = tr2.train_step(x[None], y[None])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)

    # cross-layout resume: the on-disk opt state is standard, so the same
    # checkpoint resumes on NO mesh (and vice versa) with identical steps
    tr3 = Trainer.resume(tmp_path)  # single-device
    l3 = tr3.train_step(x[None], y[None])
    np.testing.assert_allclose(l1, l3, rtol=1e-4)


def test_save_resume_exact(tmp_path):
    cfg = tiny_config(block_size=16, n_layer=2)
    data = toy_data(1024)
    tc = small_tc(grad_acc_steps=1)
    tr = Trainer(cfg, tc, out_dir=tmp_path / "run")
    rng = np.random.default_rng(2)

    def batch():
        x, y = data_loader.get_batch(data, tc.batch_size, tc.block_size, rng)
        return x[None], y[None]

    for _ in range(3):
        tr.train_step(*batch())
    tr.save(tmp_path / "run")
    # continue 2 more steps on the original
    b4, b5 = batch(), batch()
    tr.train_step(*b4)
    l5_orig = tr.train_step(*b5)

    tr2 = Trainer.resume(tmp_path / "run")
    assert tr2.iter_num == 3
    tr2.train_step(*b4)
    l5_res = tr2.train_step(*b5)
    assert np.isclose(l5_orig, l5_res, rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(tr.params), jax.tree_util.tree_leaves(tr2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_fit_with_eval_and_early_ckpt(tmp_path):
    cfg = tiny_config(block_size=16, n_layer=2)
    tc = small_tc(max_iters=12, ckpt_interval=5, grad_acc_steps=1, patience=50)
    tr = Trainer(cfg, tc, out_dir=tmp_path / "run")
    data = toy_data(2048)
    train, val = data_loader.split_dataset(data)
    result = tr.fit(train, val)
    assert result["iter_num"] == 12
    assert any("val_loss" in h for h in result["history"])
    assert (tmp_path / "run" / "params").exists()


def test_data_loader_roundtrip(tmp_path):
    class FakeTok:
        def encode(self, text, bos=False):
            return np.asarray([ord(c) % 256 for c in text], np.int32)

    src = tmp_path / "corpus.txt"
    src.write_text("hello world " * 500)
    tp, vp = data_loader.prepare_bin(src, tmp_path / "data", FakeTok())
    train = data_loader.open_bin(tp)
    val = data_loader.open_bin(vp)
    assert len(train) > len(val) > 0
    x, y = data_loader.get_batch(train, 3, 8, np.random.default_rng(0))
    assert x.shape == (3, 8) and y.shape == (3, 8)
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
