"""`mdi-doctor` (`cli/doctor.py`): per-stage subprocess isolation under
hard timeouts (a fake wedged stage must come back as "timeout", fast,
with the tool alive), the JSON snapshot schema, and the real --quick
staged triage on the CPU backend — the tier-1 smoke that CI-gates the
tool bench leans on for backend forensics.
"""

import json
import time

import pytest

from mdi_llm_tpu.cli import doctor


def _stage(name, code, timeout=5.0):
    return {"name": name, "help": name, "timeout": timeout,
            "quick": True, "code": code}


# ---------------------------------------------------------------------------
# per-stage subprocess machinery
# ---------------------------------------------------------------------------


def test_wedged_stage_hits_its_timeout_without_hanging_the_tool():
    """THE reason the doctor exists: a stage that never answers (the
    wedged-libtpu shape) is killed at its own hard timeout and recorded
    as such — the tool returns promptly with the partial evidence."""
    wedge = _stage("wedge", "import time; time.sleep(30)", timeout=0.5)
    t0 = time.perf_counter()
    rec = doctor.run_stage(wedge)
    elapsed = time.perf_counter() - t0
    assert rec["status"] == "timeout"
    assert elapsed < 10.0, "the kill must not wait out the sleep"
    assert rec["timeout_s"] == 0.5
    assert "0.5" in rec["error"] and "killed" in rec["error"]
    assert rec["elapsed_s"] >= 0.5


def test_failed_stage_records_the_error_tail():
    rec = doctor.run_stage(_stage("boom", "raise RuntimeError('kaboom')"))
    assert rec["status"] == "failed"
    assert "kaboom" in rec["error"]


def test_skipped_stage_and_payload_parsing():
    rec = doctor.run_stage(
        _stage("skip", "import json; print(json.dumps({'skipped': 'n/a'}))")
    )
    assert rec["status"] == "skipped"
    ok = doctor.run_stage(
        _stage("ok", "import json; print('noise'); "
                     "print(json.dumps({'answer': 42}))")
    )
    assert ok["status"] == "ok" and ok["detail"]["answer"] == 42


def test_snapshot_schema_with_fake_stages():
    """collect_snapshot over an injected stage list: schema fields, the
    device identity lifted from the devices-style payload, and `ok`
    reflecting the worst stage — all without touching a backend."""
    stages = [
        _stage("dev", "import json; print(json.dumps({"
               "'platform': 'tpu', 'device_kind': 'TPU v5 lite',"
               " 'device_count': 4}))"),
        _stage("wedge", "import time; time.sleep(30)", timeout=0.5),
    ]
    snap = doctor.collect_snapshot(stages=stages)
    assert snap["schema"] == doctor.SCHEMA_VERSION
    assert snap["ok"] is False  # the wedge poisons overall health
    assert snap["device_kind"] == "TPU v5 lite"
    assert snap["backend"] == "tpu" and snap["device_count"] == 4
    assert [r["name"] for r in snap["stages"]] == ["dev", "wedge"]
    assert snap["stages"][1]["status"] == "timeout"
    assert "versions" in snap and "hostname" in snap and "env" in snap
    json.dumps(snap)  # the bench-embedded artifact must be JSON-clean
    # stage_timeout overrides the per-stage budgets
    t0 = time.perf_counter()
    snap2 = doctor.collect_snapshot(stages=[stages[1]], stage_timeout=0.3)
    assert snap2["stages"][0]["status"] == "timeout"
    assert snap2["stages"][0]["timeout_s"] == 0.3
    assert time.perf_counter() - t0 < 10.0


def test_provenance_is_cheap_and_probe_scoped():
    prov = doctor.provenance()
    assert prov["versions"].get("jax"), "importlib.metadata must see jax"
    assert prov["hostname"] and prov["python"]
    # only backend-relevant env keys are captured
    assert all(
        k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_", "PJRT_"))
        for k in prov["env"]
    )
    json.dumps(prov)


# ---------------------------------------------------------------------------
# the real staged triage on the CPU backend (tier-1 CI gate)
# ---------------------------------------------------------------------------


def test_quick_triage_healthy_on_cpu(tmp_path, capsys):
    """mdi-doctor --quick end-to-end: three real stage subprocesses on the
    CPU backend, healthy exit code, valid snapshot on stdout AND in the
    --json file — the smoke that keeps the tool itself CI-gated."""
    out_p = tmp_path / "doctor.json"
    rc = doctor.main(["--quick", "--device", "cpu", "--json", str(out_p)])
    assert rc == 0
    stdout = capsys.readouterr().out
    snap = json.loads(stdout.strip().splitlines()[-1])
    file_snap = json.loads(out_p.read_text())
    assert snap["ok"] is True and file_snap["ok"] is True
    assert [r["name"] for r in snap["stages"]] == [
        "import_jax", "devices", "matmul",
    ]
    assert all(r["status"] == "ok" for r in snap["stages"])
    assert snap["backend"] == "cpu" and snap["device_kind"] == "cpu"
    assert snap["versions"]["jax"] == snap["stages"][0]["detail"]["jax"]
    assert snap["stages"][2]["detail"]["correct"] is True


def test_threads_stage_runs_on_cpu():
    """The mdi-race stage end-to-end: a real subprocess runs the seeded
    explorer burst against a tiny CPU engine and reports parity-clean."""
    stage = next(s for s in doctor.STAGES if s["name"] == "threads")
    assert stage["quick"] is False  # too heavy for --quick triage
    rec = doctor.run_stage(stage)
    assert rec["status"] == "ok", rec
    assert rec["detail"]["ok"] is True
    assert rec["detail"]["mismatches"] == []
    assert rec["detail"]["yield_point_visits"] > 0


def test_unhealthy_snapshot_exits_nonzero(monkeypatch, capsys):
    monkeypatch.setattr(
        doctor, "STAGES", [_stage("boom", "raise SystemExit(3)")]
    )
    rc = doctor.main([])
    assert rc == 1
    snap = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert snap["ok"] is False


def test_pyproject_registers_console_script():
    from pathlib import Path

    txt = (Path(__file__).resolve().parents[1] / "pyproject.toml").read_text()
    assert 'mdi-doctor = "mdi_llm_tpu.cli.doctor:main"' in txt


def test_cli_surface():
    help_text = doctor.build_parser().format_help()
    for flag in ("--quick", "--stage-timeout", "--json", "--device",
                 "--list-stages"):
        assert flag in help_text, flag
    # the stage list is what --help/--list-stages document; pin the order
    names = [s["name"] for s in doctor.STAGES]
    assert names == ["import_jax", "devices", "matmul", "donation",
                     "profiler_trace", "collective", "threads"]
    assert [s["name"] for s in doctor.STAGES if s["quick"]] == [
        "import_jax", "devices", "matmul",
    ]
    assert doctor.main(["--list-stages"]) == 0
