"""mdi-lint: per-rule fixtures (every rule has a triggering and a passing
snippet), suppression + baseline workflow, the CLI surface, and the repo
self-check — `mdi-lint mdi_llm_tpu/` must exit clean against the committed
baseline, which makes this file the tier-1 CI gate the linter ships as.

Also pins the CompileGuard <-> sampling contract the linter's static rules
are paired with: `sample_traced` (traced float knobs, static mode) is
draw-identical to `sample`, and a decode loop re-run at a DIFFERENT
temperature must not retrace (the static-float-arg fix, measurable).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from mdi_llm_tpu.analysis import Baseline, RULES, lint_paths, lint_source
from mdi_llm_tpu.analysis.cli import main as lint_main

REPO = Path(__file__).resolve().parents[1]


def rules_of(findings):
    return sorted({f.rule for f in findings})


def lint_rule(src, rule):
    """Findings of one rule on a snippet (other rules can't interfere)."""
    return lint_source(src, path="ops/snippet.py" if rule == "missing-named-scope"
                       else "snippet.py", select=[rule])


# ---------------------------------------------------------------------------
# per-rule fixtures: each rule triggers on its bad snippet, stays silent on
# the good twin
# ---------------------------------------------------------------------------

BAD = {
    "host-sync-in-jit": """
import jax

@jax.jit
def f(x):
    y = x * 2
    return y.item()
""",
    "host-sync": """
import jax
import numpy as np

def collect(emits):
    for e in emits:
        out = jax.device_get(e)
    return out

def decode_loop(step, tok):
    toks = []
    for _ in range(8):
        tok_j = step(tok)
        tok = np.asarray(tok_j)  # per-token fetch of a device value
        toks.append(int(tok[0]))
    return toks
""",
    "tracer-branch": """
import jax

@jax.jit
def f(x, n):
    if n > 0:
        return x * n
    return x
""",
    "donation-after-use": """
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def step(kv, tok):
    return kv + tok

def loop(kv, tok):
    out = step(kv, tok)
    return kv.sum()
""",
    "static-float-arg": """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("temperature",))
def decode(x, temperature):
    return x / temperature
""",
    "jit-in-loop": """
import jax

def run(xs):
    for x in xs:
        f = jax.jit(lambda v: v * 2)
        x = f(x)
    return x
""",
    "lax-scalar-operand": """
from jax import lax

def f(x):
    return lax.add(x, 1.0)
""",
    "mutable-global-in-jit": """
import jax

TABLE = {"scale": 2.0}

@jax.jit
def f(x):
    return x * TABLE["scale"]
""",
    "timing-in-jit": """
import time
import jax

@jax.jit
def step(x):
    t0 = time.perf_counter()   # runs ONCE, at trace time
    y = x * 2
    return y, time.time() - t0
""",
    "missing-named-scope": """
import jax
import jax.numpy as jnp

def fused_kernel(q, k, v):
    s = jnp.einsum("bth,bsh->bts", q, k)
    s = s * jnp.asarray(0.125, s.dtype)
    s = s - jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    p = p.astype(v.dtype)
    o = jnp.einsum("bts,bsh->bth", p, v)
    o = jnp.tanh(o)
    return jnp.reshape(o, o.shape)
""",
    "unguarded-shared-state": """
import threading

class Frontend:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()

    def submit(self, rid, handle):
        with self._lock:
            self._handles[rid] = handle

    def _pump(self):
        return self._handles.get("r0")  # engine-role read, no lock
""",
    "blocking-in-event-loop": """
import time

class Server:
    async def handle(self, handle):
        time.sleep(0.1)        # parks every connection
        handle.done.wait()     # blocks the loop on another thread
        return handle
""",
    "lock-order-inversion": """
import threading

state_lock = threading.Lock()
io_lock = threading.Lock()

def flush():
    with state_lock:
        with io_lock:
            pass

def snapshot():
    with io_lock:
        with state_lock:  # opposite order: deadlock-capable
            pass
""",
    "loop-call-from-wrong-thread": """
import threading

class Bridge:
    def __init__(self, loop):
        self.loop = loop
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()

    def _pump(self):
        self.loop.call_soon(print, "tick")  # engine thread, unsafe API
""",
}

GOOD = {
    "host-sync-in-jit": """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return jnp.sum(x * 2)

def host_side(y):
    return y.item()  # outside jit: fine for this rule
""",
    "host-sync": """
import jax
import numpy as np

def collect(emits):
    return jax.device_get(emits)  # mdi-lint: disable=host-sync -- one batched fetch

def decode_chunks(chunk_fn, tok, prompts):
    toks = []
    for i, p in enumerate(prompts):
        batch = np.asarray(p, np.int32)  # host dtype conversion: not a fetch
        toks_j, tok = chunk_fn(batch, tok)  # K steps on device per dispatch
        chunk = np.asarray(toks_j)  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per K steps
        toks.extend(chunk.tolist())
    return toks
""",
    "tracer-branch": """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    if n > 0:          # static: fine
        return x * n
    if x.ndim == 2:    # shape check on a tracer: concrete, fine
        return x
    return x

@jax.jit
def g(x, y):
    if y is None:      # structure check: fine
        return x
    return x + y
""",
    "donation-after-use": """
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0,))
def step(kv, tok):
    return kv + tok

def loop(kv, tok):
    kv = step(kv, tok)   # rebound by the donating call itself
    return kv.sum()
""",
    "static-float-arg": """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode", "top_k"))
def decode(x, temperature, mode, top_k):
    return x / temperature   # temperature is traced; mode/top_k key the cache
""",
    "jit-in-loop": """
import jax

f = jax.jit(lambda v: v * 2)

def run(xs):
    for x in xs:
        x = f(x)
    return x
""",
    "lax-scalar-operand": """
import jax.numpy as jnp
from jax import lax

def f(x):
    return lax.add(x, jnp.asarray(1.0, x.dtype))
""",
    "mutable-global-in-jit": """
import jax

SCALE = 2.0  # immutable module constant: fine

@jax.jit
def f(x, table):
    return x * table["scale"] * SCALE
""",
    "timing-in-jit": """
import time
import jax

@jax.jit
def step(x):
    return x * 2

def timed_step(x):
    t0 = time.perf_counter()   # host side, at the dispatch boundary: fine
    y = step(x)
    return y, time.perf_counter() - t0
""",
    "missing-named-scope": """
import jax
import jax.numpy as jnp

def fused_kernel(q, k, v):
    with jax.named_scope("fused_kernel"):
        s = jnp.einsum("bth,bsh->bts", q, k)
        s = s * jnp.asarray(0.125, s.dtype)
        s = s - jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        p = p.astype(v.dtype)
        o = jnp.einsum("bts,bsh->bth", p, v)
        o = jnp.tanh(o)
        return jnp.reshape(o, o.shape)

def _private_helper(q, k, v):
    return fused_kernel(q, k, v)  # private: exempt
""",
    "unguarded-shared-state": """
import threading

class Frontend:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles = {}
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()

    def submit(self, rid, handle):
        with self._lock:
            self._handles[rid] = handle

    def _pump(self):
        with self._lock:
            return self._handles.get("r0")
""",
    "blocking-in-event-loop": """
import asyncio

class Server:
    async def handle(self, loop, handle):
        await asyncio.sleep(0.1)                          # awaited: fine
        await loop.run_in_executor(None, handle.done.wait)  # off-loop: fine
        return handle
""",
    "lock-order-inversion": """
import threading

state_lock = threading.Lock()
io_lock = threading.Lock()

def flush():
    with state_lock:
        with io_lock:
            pass

def snapshot():
    with state_lock:  # same global order everywhere
        with io_lock:
            pass
""",
    "loop-call-from-wrong-thread": """
import threading

class Bridge:
    def __init__(self, loop):
        self.loop = loop
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()

    def _pump(self):
        self.loop.call_soon_threadsafe(print, "tick")  # the sanctioned crossing
""",
}


def test_every_shipped_rule_has_fixtures():
    assert set(BAD) == set(RULES), "add fixtures for every registered rule"
    assert set(GOOD) == set(RULES)


@pytest.mark.parametrize("rule", sorted(BAD))
def test_rule_triggers_on_bad_fixture(rule):
    findings = lint_rule(BAD[rule], rule)
    assert findings, f"{rule} missed its bad fixture"
    assert all(f.rule == rule for f in findings)
    assert all(f.line >= 1 and f.message for f in findings)


@pytest.mark.parametrize("rule", sorted(GOOD))
def test_rule_passes_on_good_fixture(rule):
    assert lint_rule(GOOD[rule], rule) == [], f"{rule} false-positived"


def test_rule_registry_is_documented():
    for r in RULES.values():
        assert r.summary, f"{r.name} has no summary"


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_same_line_suppression_silences_only_that_rule():
    src = """
import jax

def collect(e):
    return jax.device_get(e)  # mdi-lint: disable=host-sync -- intended sync
"""
    assert lint_source(src, select=["host-sync"]) == []
    # a different rule name does NOT silence it
    src2 = src.replace("disable=host-sync", "disable=tracer-branch")
    assert rules_of(lint_source(src2, select=["host-sync"])) == ["host-sync"]


def test_disable_next_line_and_disable_all():
    src = """
import jax

def collect(e):
    # mdi-lint: disable-next-line=host-sync -- one batched fetch per chunk
    x = jax.device_get(e)
    y = jax.device_get(e)  # mdi-lint: disable=all
    return x, y
"""
    assert lint_source(src, select=["host-sync"]) == []


def test_unsuppressed_line_still_reported():
    src = """
import jax

def collect(e):
    x = jax.device_get(e)  # mdi-lint: disable=host-sync -- ok
    y = jax.device_get(e)
    return x, y
"""
    findings = lint_source(src, select=["host-sync"])
    assert len(findings) == 1 and findings[0].line == 6


# ---------------------------------------------------------------------------
# baseline workflow
# ---------------------------------------------------------------------------

VIOLATION = """
import jax

def collect(e):
    return jax.device_get(e)
"""

SECOND_VIOLATION = """
import jax

def collect(e):
    return jax.device_get(e)

def collect2(e):
    return jax.device_get(list(e))
"""


def test_baseline_grandfathers_then_new_violation_fails(tmp_path, capsys):
    mod = tmp_path / "pkg" / "mod.py"
    mod.parent.mkdir()
    mod.write_text(VIOLATION)
    baseline = tmp_path / "baseline.json"

    # --update-baseline grandfathers the existing finding -> clean exit
    rc = lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    assert rc == 0 and baseline.exists()
    rc = lint_main([str(mod), "--baseline", str(baseline)])
    assert rc == 0

    # adding a NEW violation (different line text) fails despite the baseline
    mod.write_text(SECOND_VIOLATION)
    rc = lint_main([str(mod), "--baseline", str(baseline)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "host-sync" in out and "grandfathered" in out


def test_update_baseline_round_trips(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(SECOND_VIOLATION)
    baseline = tmp_path / "baseline.json"
    rc = lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    assert rc == 0
    first = json.loads(baseline.read_text())
    # round-trip: updating again from the same tree is a fixed point…
    rc = lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    assert json.loads(baseline.read_text()) == first
    # …and the tree lints clean against it
    assert lint_main([str(mod), "--baseline", str(baseline)]) == 0
    # fixing the code then regenerating empties the baseline
    mod.write_text("x = 1\n")
    lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    assert json.loads(baseline.read_text())["findings"] == {}


def test_baseline_split_counts_per_key():
    findings = lint_source(SECOND_VIOLATION, select=["host-sync"])
    assert len(findings) == 2
    b = Baseline.from_findings(findings[:1])
    new, old = b.split(findings)
    assert len(old) == 1 and len(new) == 1


def test_lint_root_under_hidden_dir_still_lints(tmp_path):
    """Only dot-dirs BELOW the lint root are skipped — a checkout under
    ~/.cache (or a .claude worktree) must not lint vacuously clean."""
    root = tmp_path / ".hidden" / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "mod.py").write_text(VIOLATION)
    (root / "pkg" / ".venv").mkdir()
    (root / "pkg" / ".venv" / "skipme.py").write_text(VIOLATION)
    findings, errors = lint_paths([root / "pkg"], root=root)
    assert not errors
    assert [f.path for f in findings] == ["pkg/mod.py"]  # .venv skipped


def test_missing_path_is_an_error_not_clean(tmp_path, capsys):
    findings, errors = lint_paths([tmp_path / "no_such_pkg"])
    assert findings == [] and len(errors) == 1
    assert "no such file" in errors[0]
    rc = lint_main([str(tmp_path / "no_such_pkg")])
    assert rc == 2  # a typo'd CI invocation must not exit 0


def test_update_baseline_with_select_preserves_other_rules(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        VIOLATION + "\n"
        "import jax as j\n\n"
        "@j.jit\n"
        "def f(x):\n"
        "    return x.item()\n"
    )
    baseline = tmp_path / "baseline.json"
    lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    keys = set(json.loads(baseline.read_text())["findings"])
    assert {k.split("::")[0] for k in keys} == {"host-sync", "host-sync-in-jit"}
    # refreshing ONE rule must not discard the other rule's entries
    lint_main([str(mod), "--baseline", str(baseline),
               "--select", "host-sync", "--update-baseline"])
    assert set(json.loads(baseline.read_text())["findings"]) == keys
    assert lint_main([str(mod), "--baseline", str(baseline)]) == 0


def test_baseline_survives_line_shift(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(VIOLATION)
    baseline = tmp_path / "baseline.json"
    lint_main([str(mod), "--baseline", str(baseline), "--update-baseline"])
    # unrelated lines added above: same line TEXT, different line number
    mod.write_text("import os\n\n" + VIOLATION)
    assert lint_main([str(mod), "--baseline", str(baseline)]) == 0


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in RULES:
        assert name in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert lint_main(["--select", "no-such-rule"]) == 2


def test_cli_json_format(tmp_path, capsys):
    mod = tmp_path / "mod.py"
    mod.write_text(VIOLATION)
    rc = lint_main([str(mod), "--no-baseline", "--format", "json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert data["findings"] and data["findings"][0]["rule"] == "host-sync"


def test_cli_syntax_error_reported_not_crash(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    rc = lint_main([str(bad), "--no-baseline"])
    assert rc == 2
    assert "syntax error" in capsys.readouterr().err


def test_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "mdi_llm_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0 and "static-float-arg" in proc.stdout


# ---------------------------------------------------------------------------
# the CI gate: the repo itself lints clean against the committed baseline
# ---------------------------------------------------------------------------


def test_repo_lints_clean_against_committed_baseline():
    findings, errors = lint_paths([REPO / "mdi_llm_tpu"], root=REPO)
    assert not errors
    baseline = Baseline.load(REPO / ".mdi-lint-baseline.json")
    new, _ = baseline.split(findings)
    assert new == [], "new mdi-lint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_pyproject_registers_console_script():
    txt = (REPO / "pyproject.toml").read_text()
    assert 'mdi-lint = "mdi_llm_tpu.analysis.cli:main"' in txt
