"""mdi-ir: trace-level static analysis of the serving compile set.

Three layers under test:

1. the per-rule checkers — every rule has a PLANTED-bug fixture it must
   catch and a clean twin it must pass (the trip-wire style mdi-audit
   established: a check that can't fail proves nothing);
2. the enumeration seams — `ServingEngine.enumerate_executables()` must
   cover every `step()`-dispatchable signature (incl. spec_k verify and
   the pp ring variants) and the whole abstract pass must never touch a
   backend or a device;
3. the CLI — exit codes 0/1/2, `--format json`, suppression
   justifications, and the mdi-lint Baseline round-trip.

The repo self-check (registry model at single-device, tp=2, pp=2,
findings: none) runs here in tier-1, so a serving change that opens a
zero-recompile hole or drops a donation fails CI before any benchmark.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mdi_llm_tpu.analysis.core import Baseline
from mdi_llm_tpu.analysis.ir import (
    IR_RULES,
    IrReport,
    analyze_executables,
    enforce_ir_preflight,
    ir_detail,
    ir_preflight,
    main,
    reachable_serving_set,
    trace_serving,
)
from mdi_llm_tpu.config import Config, ServingConfig
from mdi_llm_tpu.obs.device import ExecutableSpec
from mdi_llm_tpu.parallel.mesh import make_mesh

sds = jax.ShapeDtypeStruct
f32, bf16, i32 = jnp.float32, jnp.bfloat16, jnp.int32

MODEL = "pythia-14m"  # the registry self-check model


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# per-rule planted-bug / clean fixtures
# ---------------------------------------------------------------------------


def test_dropped_donation_planted_and_clean():
    # planted: the donated (8,8) buffer matches NO output shape, so JAX
    # silently keeps both copies — exactly the bug the rule exists for
    bad = jax.jit(lambda a, b: jnp.sum(b), donate_argnums=(0,))
    spec = ExecutableSpec(
        "drop", (8,), bad, (sds((8, 8), f32), sds((8, 8), f32)), None, (0,)
    )
    findings, records = analyze_executables([spec], origin="t")
    assert rules_of(findings) == ["dropped-donation"]
    assert "2x HBM" in findings[0].message
    assert records[0]["donated"] == 1

    good = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    spec = ExecutableSpec(
        "ok", (8,), good, (sds((8, 8), f32), sds((8, 8), f32)), None, (0,)
    )
    findings, _ = analyze_executables([spec], origin="t")
    assert findings == []


def test_callback_in_executable_planted_and_clean():
    def with_print(a):
        jax.debug.print("tok {}", a.sum())
        return a * 2

    spec = ExecutableSpec(
        "cb", (), jax.jit(with_print), (sds((4,), f32),), None, ()
    )
    findings, _ = analyze_executables([spec], origin="t")
    assert rules_of(findings) == ["callback-in-executable"]
    assert "debug_callback" in findings[0].line_text

    spec = ExecutableSpec(
        "nocb", (), jax.jit(lambda a: a * 2), (sds((4,), f32),), None, ()
    )
    findings, _ = analyze_executables([spec], origin="t")
    assert findings == []


def test_baked_constant_bloat_planted_and_clean():
    big = jnp.arange(2048, dtype=jnp.float32)  # 8 KiB closure constant
    spec = ExecutableSpec(
        "bloat", (), jax.jit(lambda a: a + big), (sds((2048,), f32),),
        None, (),
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      max_const_bytes=1024)
    assert rules_of(findings) == ["baked-constant-bloat"]
    assert "float32" in findings[0].line_text
    # same executable, sane threshold: the constant is fine
    findings, _ = analyze_executables([spec], origin="t",
                                      max_const_bytes=1 << 20)
    assert findings == []


def test_dtype_promotion_leak_planted_and_clean():
    leak = jax.jit(lambda a, w: a.astype(f32) @ w.astype(f32))
    spec = ExecutableSpec(
        "leak", (), leak, (sds((4, 8), bf16), sds((8, 4), bf16)), None, ()
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      compute_dtype="bfloat16")
    assert rules_of(findings) == ["dtype-promotion-leak"]
    assert findings[0].line_text == "leak:bfloat16"
    # f32 params: upcasts are the compute dtype, not a leak
    findings, _ = analyze_executables([spec], origin="t",
                                      compute_dtype="float32")
    assert findings == []
    # bf16 straight through the matmul: clean
    spec = ExecutableSpec(
        "noleak", (), jax.jit(lambda a, w: a @ w),
        (sds((4, 8), bf16), sds((8, 4), bf16)), None, (),
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      compute_dtype="bfloat16")
    assert findings == []


def test_sharding_constraint_drift_planted_and_clean(devices):
    mesh = make_mesh({"tp": 2}, jax.devices()[:2])
    declared = NamedSharding(mesh, P(None, "tp"))
    drifted = NamedSharding(mesh, P("tp", None))
    kv = sds((4, 8), f32, sharding=declared)

    def pinned(sh):
        return jax.jit(
            lambda p, kv_: jax.lax.with_sharding_constraint(kv_, sh) * 1.0,
            donate_argnums=(1,),
        )

    spec = ExecutableSpec(
        "drift", (), pinned(drifted), (sds((2,), f32), kv), None, (1,)
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      check_donation=False)
    assert rules_of(findings) == ["sharding-constraint-drift"]
    assert "resharding" in findings[0].message

    spec = ExecutableSpec(
        "nodrift", (), pinned(declared), (sds((2,), f32), kv), None, (1,)
    )
    findings, _ = analyze_executables([spec], origin="t",
                                      check_donation=False)
    assert findings == []


def test_trace_failure_is_a_finding_not_a_crash():
    def explodes(a):
        raise RuntimeError("boom")

    spec = ExecutableSpec(
        "boom", (), jax.jit(explodes), (sds((4,), f32),), None, ()
    )
    findings, records = analyze_executables([spec], origin="t")
    assert rules_of(findings) == ["trace-failure"]
    assert "error" in records[0]


# ---------------------------------------------------------------------------
# compile-set closure + enumeration completeness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "serving,expect",
    [
        (dict(), {"mixed", "decode_chunk"}),
        (dict(decode_chunk=1), {"mixed", "decode"}),
        (dict(spec_k=3), {"mixed", "decode_chunk", "verify"}),
        (dict(spec_k=3, decode_chunk=1), {"mixed", "decode", "verify"}),
        # temperature>0 routes spec through the rejection-sampled verify
        (dict(spec_k=3, temperature=0.8),
         {"mixed", "decode_chunk", "verify_sample"}),
        # a draft model adds its mirror/catch-up scan executables
        (dict(spec_k=3, draft_model="pythia-14m"),
         {"mixed", "decode_chunk", "verify", "draft_mixed", "draft_scan"}),
        (dict(spec_k=3, temperature=0.8, top_p=0.95,
              draft_model="pythia-14m"),
         {"mixed", "decode_chunk", "verify_sample", "draft_mixed",
          "draft_scan"}),
    ],
)
def test_enumeration_covers_every_step_dispatch_path(serving, expect):
    """Every `step()` branch (mixed, chunked/plain decode, speculative
    verify) appears in the enumerated set, and the enumeration equals the
    independently re-derived reachable set — the closure proof."""
    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(**serving),
                           max_seq_length=256)
    specs = engine.enumerate_executables()
    assert {s.label for s in specs} == expect
    enumerated = {(s.label, tuple(s.key)) for s in specs}
    reachable = reachable_serving_set(
        engine.cfg, engine.scheduler.max_batch, engine.token_budget
    )
    assert enumerated == reachable
    # shape keys carry the config numbers, not defaults
    (mixed_key,) = [k for (lbl, k) in enumerated if lbl == "mixed"]
    assert mixed_key == (engine.scheduler.max_batch, engine.token_budget)


def test_pp_ring_engine_enumerates_the_same_compile_set(devices):
    """The pipelined engine inherits the enumeration seam: its staged-ring
    executables trace under the same labels/keys, so the closure rule
    covers pp serving too."""
    from mdi_llm_tpu.serving.pipeline import PipelinedServingEngine

    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(spec_k=3), pp=2,
                           max_seq_length=256)
    assert isinstance(engine, PipelinedServingEngine)
    specs = engine.enumerate_executables()
    assert {s.label for s in specs} == {"mixed", "decode_chunk", "verify"}
    # donation lowering rides the (tp,pp) self-check below; skip it here
    report = ir_preflight(engine, origin="pp-ring", check_donation=False)
    assert [f for f in report.findings
            if f.rule == "compile-set-closure"] == []


def test_planted_compile_set_hole_is_caught(monkeypatch):
    """An engine that forgets to warm the speculative verify path (the
    classic zero-recompile hole: first draft acceptance compiles
    MID-SERVE) must fail the closure rule."""
    from mdi_llm_tpu.serving.engine import ServingEngine

    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(spec_k=3),
                           max_seq_length=256)
    real = ServingEngine.enumerate_executables

    monkeypatch.setattr(
        ServingEngine, "enumerate_executables",
        lambda self: [s for s in real(self) if s.label != "verify"],
    )
    report = ir_preflight(engine, origin="holey", check_donation=False)
    holes = [f for f in report.findings if f.rule == "compile-set-closure"]
    assert len(holes) == 1
    assert holes[0].line_text.startswith("missing:verify")
    assert "MID-SERVE" in holes[0].message


def test_planted_dead_warmup_is_caught(monkeypatch):
    """The dual hole: enumerating an executable no step() branch reaches
    (here: a verify shape while spec_k=0) is dead warmup."""
    from mdi_llm_tpu.obs.device import abstractify
    from mdi_llm_tpu.serving.engine import ServingEngine

    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(), max_seq_length=256)
    real = ServingEngine.enumerate_executables

    def extra(self):
        specs = real(self)
        B = self.scheduler.max_batch
        args = (abstractify(self._params), sds((B, 5), i32),
                abstractify(self._kv),
                sds((B, self.max_blocks_per_seq), i32), sds((B,), i32))
        specs.append(ExecutableSpec(
            "verify", (B, 5), self._verify_fn(B, 5), args, None, (2,)
        ))
        return specs

    monkeypatch.setattr(ServingEngine, "enumerate_executables", extra)
    report = ir_preflight(engine, origin="dead", check_donation=False)
    dead = [f for f in report.findings if f.rule == "compile-set-closure"]
    assert len(dead) == 1
    assert dead[0].line_text.startswith("unreachable:verify")


def test_sequential_enumeration_covers_generate_paths():
    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(), max_seq_length=256)
    specs = engine.gen.enumerate_executables(
        batch_size=2, prompt_len=32, max_new_tokens=16, chunk_size=8,
        speculative=3,
    )
    labels = {s.label for s in specs}
    assert {"prefill", "decode_chunk", "verify"} <= labels
    findings, records = analyze_executables(
        specs, origin="seq", compute_dtype="bfloat16"
    )
    assert findings == []
    assert all("eqns" in r for r in records)


# ---------------------------------------------------------------------------
# the repo self-check: registry model, three meshes, zero device use
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tp,pp", [(1, 1), (2, 1), (1, 2)])
def test_self_check_clean_and_never_touches_a_backend(tp, pp, monkeypatch,
                                                      devices):
    """The acceptance gate: the full abstract pass (engine construction,
    enumeration, tracing, lowering, every rule) on the registry model is
    CLEAN at single-device, tp=2 and pp=2 — and a trip-wired
    backend_compile / device_put proves no rule ever compiles or places a
    buffer (the mdi-audit trip-wire style)."""
    from jax._src import compiler as jax_compiler

    def tripped(*a, **k):
        raise AssertionError("mdi-ir touched a backend/device")

    monkeypatch.setattr(jax_compiler, "backend_compile", tripped)
    monkeypatch.setattr(jax, "device_put", tripped)

    cfg = Config.from_name(MODEL)
    engine = trace_serving(
        cfg, ServingConfig(spec_k=3), tp=tp, pp=pp, max_seq_length=256
    )
    report = ir_preflight(engine, origin=f"self@tp{tp}pp{pp}")
    assert report.findings == [], report.render_text()
    assert len(report.executables) == 3  # mixed, decode_chunk, verify
    assert all(r["eqns"] > 0 and r["donated"] >= 1
               for r in report.executables)


# ---------------------------------------------------------------------------
# preflight gate + detail record (bench.py / mdi-serve wiring)
# ---------------------------------------------------------------------------


def test_enforce_ir_preflight_refuses_on_errors_allows_with_flag():
    cfg = Config.from_name(MODEL)
    engine = trace_serving(cfg, ServingConfig(), max_seq_length=256)
    report = ir_preflight(engine, origin="gate", check_donation=False)
    emitted = []
    assert enforce_ir_preflight(report, "bench", emit=emitted.append)
    assert emitted == []  # clean pass stays silent

    bad = jax.jit(lambda a, b: jnp.sum(b), donate_argnums=(0,))
    spec = ExecutableSpec(
        "drop", (8,), bad, (sds((8, 8), f32), sds((8, 8), f32)), None, (0,)
    )
    findings, records = analyze_executables([spec], origin="gate")
    broken = IrReport(origin="gate", findings=findings, executables=records)
    with pytest.raises(SystemExit, match="no-preflight"):
        enforce_ir_preflight(broken, "bench", emit=emitted.append)
    assert any("dropped-donation" in line for line in emitted)
    assert enforce_ir_preflight(broken, "bench", allow=True,
                                emit=emitted.append)

    d = ir_detail(broken)
    assert d["findings"] == 1 and d["warnings"] == 0
    assert "drop(8)" in d["executables"]


# ---------------------------------------------------------------------------
# CLI: exit codes, json, suppression, baseline round-trip, help
# ---------------------------------------------------------------------------


def test_cli_clean_self_check_exit_0(capsys):
    rc = main(["--model", MODEL, "--seq-len", "256"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "findings: none" in out and "mixed(8,136)" in out


def test_cli_findings_exit_1_and_json(monkeypatch, capsys):
    from mdi_llm_tpu.serving.engine import ServingEngine

    real = ServingEngine.enumerate_executables
    monkeypatch.setattr(
        ServingEngine, "enumerate_executables",
        lambda self: [s for s in real(self) if s.label != "verify"],
    )
    rc = main(["--model", MODEL, "--seq-len", "256", "--spec-k", "3",
               "--no-donation-check", "--format", "json"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["errors"] >= 1 and out["new_errors"] >= 1
    assert any(f["rule"] == "compile-set-closure" for f in out["findings"])
    assert all("severity" in f for f in out["findings"])


def test_cli_suppress_needs_known_rule_and_justification(monkeypatch,
                                                         capsys):
    assert main(["--model", MODEL, "--suppress", "not-a-rule=x"]) == 2
    assert main(["--model", MODEL, "--suppress",
                 "compile-set-closure="]) == 2
    capsys.readouterr()
    # a JUSTIFIED suppression turns the planted hole's exit 1 into 0 and
    # records the why
    from mdi_llm_tpu.serving.engine import ServingEngine

    real = ServingEngine.enumerate_executables
    monkeypatch.setattr(
        ServingEngine, "enumerate_executables",
        lambda self: [s for s in real(self) if s.label != "verify"],
    )
    rc = main(["--model", MODEL, "--seq-len", "256", "--spec-k", "3",
               "--no-donation-check", "--suppress",
               "compile-set-closure=known hole, tracked in #42"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "suppressed: compile-set-closure (known hole" in out


def test_cli_baseline_round_trip(tmp_path, monkeypatch, capsys):
    """mdi-lint's Baseline grandfathers mdi-ir findings: update-baseline
    on the planted hole, then the same run against that baseline exits
    0 while a text run still prints the finding."""
    from mdi_llm_tpu.serving.engine import ServingEngine

    real = ServingEngine.enumerate_executables
    monkeypatch.setattr(
        ServingEngine, "enumerate_executables",
        lambda self: [s for s in real(self) if s.label != "verify"],
    )
    base = tmp_path / "ir-baseline.json"
    planted = ["--model", MODEL, "--seq-len", "256", "--spec-k", "3",
               "--no-donation-check"]
    assert main(planted + ["--update-baseline", str(base)]) == 0
    assert main(planted + ["--baseline", str(base)]) == 0
    capsys.readouterr()
    # the baseline grandfathers; without it the same run still fails
    assert main(planted) == 1


def test_baseline_api_round_trip(tmp_path):
    bad = jax.jit(lambda a, b: jnp.sum(b), donate_argnums=(0,))
    spec = ExecutableSpec(
        "drop", (8,), bad, (sds((8, 8), f32), sds((8, 8), f32)), None, (0,)
    )
    findings, _ = analyze_executables([spec], origin="t")
    path = tmp_path / "b.json"
    Baseline.from_findings(findings).save(path)
    new, old = Baseline.load(path).split(findings)
    assert new == [] and old == findings


def test_cli_usage_errors_exit_2(capsys):
    assert main([]) == 2  # no --model/--config
    assert main(["--model", "no-such-model-xyz"]) == 2
    err = capsys.readouterr().err
    assert "mdi-ir:" in err


def test_cli_list_checks_covers_registry(capsys):
    assert main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for rule in IR_RULES:
        assert rule in out


def test_cli_help_covers_flags(capsys):
    with pytest.raises(SystemExit):
        main(["--help"])
    text = capsys.readouterr().out
    for flag in ("--model", "--config", "--tp", "--pp", "--seq-len",
                 "--dtype", "--quantize", "--block-size", "--max-batch",
                 "--prefill-chunk", "--token-budget", "--decode-chunk",
                 "--spec-k", "--kv-dtype", "--sequential", "--speculative",
                 "--max-const-bytes", "--no-donation-check", "--suppress",
                 "--baseline", "--update-baseline", "--format",
                 "--list-checks"):
        assert flag in text, f"{flag} missing from mdi-ir --help"
