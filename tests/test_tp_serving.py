"""Tensor-parallel serving: the paged-KV continuous-batching engine over a
tp GSPMD mesh must reproduce the single-device engine — and sequential
`Generator.generate` — token-for-token across every serving feature built
on top of it (unified mixed steps, chunked decode, speculative verify,
preemption/resume, prefix caching), with zero post-warmup recompiles and
the pool's KV-group axis actually sharded.

The engine's hot paths are plain jnp under GSPMD, so these tests run on
the virtual 8-device CPU platform like tests/test_tp_inference.py; only
the Pallas-kernel-under-mesh path needs `jax.shard_map` and its tests
skip cleanly on builds without it."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.utils.profiling import CompileGuard
from tests.test_model import CONFIG_VARIANTS, tiny_config

HAS_SHARD_MAP = hasattr(jax, "shard_map")


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def single_gen(model):
    cfg, params = model
    return Generator(cfg, params, cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def tp_gen(model, devices):
    cfg, params = model
    return Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"tp": 2}, devices[:2]),
    )


def _trace(cfg, lengths, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, int(n)).tolist() for n in lengths]


def _run_engine(gen, prompts, max_news, **knobs):
    engine = gen.serve(**knobs)
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    return results, stats, engine


def _sequential_greedy(gen, prompts, max_news):
    return [
        gen.generate([p], m, temperature=0.0)[0][0]
        for p, m in zip(prompts, max_news)
    ]


@pytest.mark.smoke
def test_tp_engine_matches_single_engine_and_generate(model, single_gen, tp_gen):
    """The acceptance contract: mixed-length trace with a token budget
    small enough that the 33-token prompt splits across several unified
    mixed steps — the sharded engine's streams equal BOTH the single-device
    engine's and sequential generate()'s."""
    cfg, _ = model
    prompts = _trace(cfg, (3, 9, 17, 5, 33))
    max_news = [8, 12, 6, 10, 7]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=16, token_budget=12)
    want_gen = _sequential_greedy(single_gen, prompts, max_news)
    want, _, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, engine = _run_engine(tp_gen, prompts, max_news, **knobs)
    for i in range(len(prompts)):
        assert got[f"r{i}"] == want[f"r{i}"], f"r{i} diverged from engine"
        assert got[f"r{i}"] == want_gen[i], f"r{i} diverged from generate()"
    assert stats.mixed_steps >= 4  # the long prompt split across steps
    assert stats.requests_finished == len(prompts)
    # the pool really is sharded: KV groups on tp, everything else resident
    spec = engine._kv["k"].sharding.spec
    assert "tp" in str(spec)
    assert engine.pool.used == 0


@pytest.mark.parametrize("chunk,buffered", [(4, True), (8, False)],
                         ids=["k4-buffered", "k8-nobuf"])
def test_tp_chunked_decode_token_identical(model, single_gen, tp_gen,
                                           chunk, buffered):
    """The multi-token serving step (K-step on-device scan, double-buffered
    or not) over the sharded pool: token-identical, same sync amortization."""
    cfg, _ = model
    prompts = _trace(cfg, (3, 9, 17))
    max_news = [8, 12, 6]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8,
                 decode_chunk=chunk, double_buffer=buffered)
    want, _, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, _ = _run_engine(tp_gen, prompts, max_news, **knobs)
    assert got == want
    assert stats.tokens_per_sync > 1.0  # chunking still amortizes under tp


def test_tp_speculative_serving_token_identical(model, single_gen, tp_gen):
    """Batched n-gram speculative verify (ONE ragged multi-query forward
    over the sharded pool) stays exact and still accepts drafts."""
    cyc = [np.random.default_rng(s).integers(1, tiny_config().vocab_size,
                                             5).tolist() for s in (5, 7, 0)]
    max_news = [30, 25, 20]
    knobs = dict(block_size=4, max_batch=3, decode_chunk=4, spec_k=4)
    want, _, _ = _run_engine(single_gen, cyc, max_news, **knobs)
    got, stats, _ = _run_engine(tp_gen, cyc, max_news, **knobs)
    assert got == want
    assert stats.spec_drafted > 0 and stats.spec_accepted > 0


def test_tp_draft_model_engine_token_identical(model, single_gen, devices):
    """Acceptance: the draft-model engine under tp=2 — target AND draft
    pools sharded on the same mesh — reproduces the sequential greedy
    streams token-for-token (the single-device draft engine is pinned to
    the same reference in tests/test_serving.py, so the two engines are
    transitively identical)."""
    cfg, params = model
    dcfg = tiny_config(name="test-tiny-draft", n_layer=1,
                       block_size=cfg.block_size)
    dparams = init_params(dcfg, jax.random.PRNGKey(1))
    cyc = [np.random.default_rng(s).integers(1, cfg.vocab_size, 5).tolist()
           for s in (5, 7, 0)]
    max_news = [20, 16, 12]
    want = _sequential_greedy(single_gen, cyc, max_news)
    mesh = make_mesh({"tp": 2}, devices[:2])
    gen = Generator(cfg, params, cache_dtype=jnp.float32, mesh=mesh)
    dgen = Generator(dcfg, dparams, cache_dtype=jnp.float32, mesh=mesh)
    engine = gen.serve(block_size=4, max_batch=3, decode_chunk=4, spec_k=4,
                       draft_model="test-tiny-draft", draft_gen=dgen)
    for i, (p, m) in enumerate(zip(cyc, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    for i in range(len(cyc)):
        assert results[f"r{i}"] == want[i], f"r{i} diverged under tp=2"
    assert stats.spec_drafted_model > 0
    assert engine.draft_pool.used == 0
    assert "tp" in str(engine._draft_kv["k"].sharding.spec)


def test_tp_preemption_resume_parity(model, single_gen, tp_gen):
    """A pool sized to force recompute preemption: victims resume and
    re-feed through the sharded mixed step, outputs exact, blocks drained."""
    cfg, _ = model
    prompts = _trace(cfg, (9, 13, 11), seed=9)
    max_news = [10, 10, 10]
    knobs = dict(block_size=4, max_batch=3, max_blocks=1 + 10,
                 prefix_caching=False, decode_chunk=4)
    want, _, _ = _run_engine(single_gen, prompts, max_news, **knobs)
    got, stats, engine = _run_engine(tp_gen, prompts, max_news, **knobs)
    assert stats.preemptions >= 1, "pool was sized to force preemption"
    assert got == want
    assert engine.pool.used == 0


def test_tp_prefix_cache_hits_parity(model, single_gen, tp_gen):
    """Copy-free prefix block reuse under tp: the shared head's blocks hold
    per-device head-slices, so reuse needs no byte movement on ANY device —
    hits fire and the output still matches the sequential run."""
    cfg, _ = model
    head = _trace(cfg, (21,), seed=7)[0]
    engine = tp_gen.serve(block_size=4, max_batch=2)
    engine.add_request("first", head, 6)
    engine.run()
    tail = head + [7, 8]
    engine.add_request("second", tail, 6)
    results, stats = engine.run()
    assert stats.prefix_cache_hits >= 5  # 21-token head -> 5 full blocks
    assert results["second"] == _sequential_greedy(single_gen, [tail], [6])[0]


def test_tp_gqa_groups_shard(devices):
    """GQA: G=2 KV groups split one per device at tp=2 — the narrowest
    shardable grouping — with streams identical to the unsharded engine."""
    cfg = tiny_config(block_size=128, n_layer=3, **CONFIG_VARIANTS["gqa"])
    params = init_params(cfg, jax.random.PRNGKey(1))
    prompts = _trace(cfg, (5, 12), seed=3)
    knobs = dict(block_size=4, max_batch=2, decode_chunk=4)
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _, _ = _run_engine(single, prompts, [8, 8], **knobs)
    tp = Generator(cfg, params, cache_dtype=jnp.float32,
                   mesh=make_mesh({"tp": 2}, devices[:2]))
    got, _, engine = _run_engine(tp, prompts, [8, 8], **knobs)
    assert got == want
    assert "tp" in str(engine._kv["k"].sharding.spec)


def test_tp_pool_bytes_match_audit_estimate(model, tp_gen, devices):
    """mdi-audit's per-device pool estimate must equal the LIVE sharded
    engine's per-device pool bytes exactly — both the analytic total/tp
    and the bytes actually resident on one device's shards."""
    from mdi_llm_tpu.analysis.audit import preflight
    from mdi_llm_tpu.config import ServingConfig

    cfg, _ = model
    sv = ServingConfig(block_size=4, max_batch=3, prefill_chunk=8)
    report = preflight(cfg, tp=2, batch=3, seq_len=128,
                       cache_dtype="float32", serving=sv)
    assert not report.errors
    pool = report.breakdown["kv_pool"]
    engine = tp_gen.serve(serving=sv)
    leaves = jax.tree_util.tree_leaves(engine._kv)
    live_total = sum(int(x.nbytes) for x in leaves)
    dev0 = devices[0]
    live_dev = sum(
        int(s.data.nbytes)
        for x in leaves for s in x.addressable_shards if s.device == dev0
    )
    assert pool["tp"] == 2
    assert pool["pool_bytes"] == live_total
    assert pool["pool_bytes_per_device"] == live_total // 2 == live_dev
    # the per-device HBM budget line uses the sharded number too
    assert report.breakdown["per_device"]["kv_bytes"] == live_dev


def test_audit_flags_bad_serving_mesh():
    """Static twins of the runtime refusals: indivisible KV groups under
    tp, and dp>1 serving."""
    from mdi_llm_tpu.analysis.audit import audit_plan
    from mdi_llm_tpu.analysis.plan import MeshSpec, PlanSpec
    from mdi_llm_tpu.config import ServingConfig

    cfg = tiny_config(block_size=128, n_layer=3, **CONFIG_VARIANTS["mqa"])
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"tp": 2}), tp_axis="tp",
        serving=ServingConfig(block_size=4),
    ))
    assert any(f.rule == "bad-serving-mesh" and "n_query_groups" in f.message
               for f in r.findings)
    # the byte estimate mirrors the runtime drop-indivisible rule: G=1
    # cannot shard, so per-device == whole pool (replicated), not /tp
    pool = r.breakdown["kv_pool"]
    assert pool["tp"] == 1
    assert pool["pool_bytes_per_device"] == pool["pool_bytes"]

    r = audit_plan(PlanSpec(
        cfg=tiny_config(), mesh=MeshSpec.from_dict({"dp": 2, "tp": 2}),
        tp_axis="tp", dp_axis="dp", serving=ServingConfig(block_size=4),
    ))
    assert any(f.rule == "bad-serving-mesh" and "dp" in f.message
               for f in r.findings)


def test_serve_rejects_unsupported_mesh_axes(model, devices):
    """Generator.serve() must refuse dp>1 and non-tp axes AT SERVE TIME,
    naming the offending axis — not deep inside engine init."""
    cfg, params = model
    for axes, name in (({"dp": 2}, "dp"), ({"ep": 2}, "ep"), ({"sp": 2}, "sp")):
        gen = Generator(cfg, params, cache_dtype=jnp.float32,
                        mesh=make_mesh(axes, devices[:2]))
        with pytest.raises(ValueError, match=name):
            gen.serve(block_size=4, max_batch=2)
    # size-1 extra axes are harmless: tp is still the only real sharding
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"dp": 1, "tp": 2}, devices[:2]))
    gen.serve(block_size=4, max_batch=2)


def test_tp_engine_zero_postwarmup_recompiles(model, devices):
    """The acceptance criterion's CompileGuard half: a warmup engine and
    its timed twin on ONE tp Generator share the jit cache, and the timed
    run builds no new executable — the sharding constraint pins the pool
    layout so donation round-trips never flip it."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"tp": 2}, devices[:2]))
    prompts = _trace(cfg, (3, 9, 17))
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8, decode_chunk=4)

    def drive(engine):
        for i, p in enumerate(prompts):
            engine.add_request(f"r{i}", p, 8)
        engine.run()

    guard = CompileGuard(label="tp-serve")
    with guard:
        drive(gen.serve(**knobs))
        guard.mark_warm()
        drive(gen.serve(**knobs))
    assert guard.traces_after_warmup == 0
    assert guard.backend_compiles_after_warmup == 0
    guard.expect_clean()


def test_cli_help_covers_tp_flags():
    """Both serving front-ends document the new tensor-parallel knob."""
    import bench
    from mdi_llm_tpu.cli.serve import build_parser as serve_parser

    serve_help = serve_parser().format_help()
    assert "--tp" in serve_help and "tensor-parallel" in serve_help
    bench_help = bench.build_parser().format_help()
    assert "--tp" in bench_help and "tokens/s/chip" in bench_help


# ---------------------------------------------------------------------------
# Pallas kernel path under the mesh (jax.shard_map manual region)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not HAS_SHARD_MAP,
                    reason="this jax build has no jax.shard_map (the Pallas "
                    "paged kernels cannot run per-shard without it)")
def test_sharded_kernel_matches_lax_fallback(devices):
    """The shard_map-wrapped decode kernel (interpreter mode) over tp=2
    must match the GSPMD lax fallback on the same sharded operands."""
    from tests.test_paged_attention import build_pool, rand_qkv
    from mdi_llm_tpu.ops.paged_attention import paged_attention

    H, G, B, hs, S, bs = 4, 2, 2, 16, 32, 4
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=3)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), bs)
    q_pos = jnp.asarray([[13], [29]], jnp.int32)
    mesh = make_mesh({"tp": 2}, devices[:2])
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True,
        shard_axes=(mesh, "tp"),
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


@pytest.mark.skipif(not HAS_SHARD_MAP,
                    reason="this jax build has no jax.shard_map (the Pallas "
                    "paged kernels cannot run per-shard without it)")
def test_sharded_kernel_wide_tq_matches_lax_fallback(devices):
    """Beyond-the-old-cap ragged width (Tq=33) through the shard_map-
    wrapped unified kernel under tp=2: the packed span metadata rides
    replicated, the q/pool head axes shard, and the head-packing factor
    folds down to the LOCAL group count — parity with the GSPMD fallback
    must survive all of it."""
    from tests.test_paged_attention import build_pool, rand_qkv
    from mdi_llm_tpu.ops.paged_attention import paged_attention

    H, G, B, hs, S, bs, Tq = 4, 2, 2, 16, 64, 8, 33
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=9)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), bs)
    q_pos = jnp.asarray([np.arange(Tq), np.arange(S - Tq, S)], jnp.int32)
    mesh = make_mesh({"tp": 2}, devices[:2])
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True,
        shard_axes=(mesh, "tp"),
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


@pytest.mark.skipif(HAS_SHARD_MAP,
                    reason="jax.shard_map present: the missing-dep refusal "
                    "gate does not apply on this build")
def test_kernel_under_mesh_refused_without_shard_map(model, devices):
    """On builds without jax.shard_map, an EXPLICIT use_kernel=True over a
    mesh must refuse at engine construction with an actionable message
    (auto use_kernel=None resolves to the lax fallback instead)."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"tp": 2}, devices[:2]))
    with pytest.raises(ValueError, match="shard_map"):
        gen.serve(block_size=4, max_batch=2, use_kernel=True)
    gen.serve(block_size=4, max_batch=2)  # auto: fine, lax fallback
