"""Scheduling-policy tests (serving/policy.py): fairness under a
starving tenant, priority inversion, TTFT-deadline prefill packing, the
policy seam's FCFS bit-compatibility, and the fake-clock open-loop SLO
sweep finding a known synthetic knee (server/loadgen.py).

Everything here is host-side — no device work, no jit: the policies
reorder lists the scheduler owns, and the sweep drives a synthetic
queueing model.  The engine-level contract (token streams identical
under every policy) lives in tests/test_server.py.
"""

import pytest

from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.policy import (
    POLICIES,
    DeadlinePolicy,
    FairSharePolicy,
    FCFSPolicy,
    PriorityPolicy,
    make_policy,
)
from mdi_llm_tpu.serving.scheduler import Request, Scheduler


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _sched(policy=None, num_blocks=65, block_size=4, max_batch=2,
           prefill_chunk=8, max_seq_length=64):
    pool = KVPool(num_blocks, block_size)
    return Scheduler(pool, max_batch, prefill_chunk, max_seq_length,
                     policy=policy)


def _req(rid, n_prompt=4, new=4, **kw):
    return Request(rid, list(range(1, n_prompt + 1)), new, **kw)


def _complete_prefill(entries):
    for seq, n in entries:
        if seq.needs_prefill:
            seq.fed += n
            if seq.fed >= seq.prefill_target and seq.next_tok is None:
                seq.next_tok = 7
                seq.tokens.append(7)


# ---------------------------------------------------------------------------
# registry / seam
# ---------------------------------------------------------------------------


def test_registry_and_make_policy():
    assert set(POLICIES) == {"fcfs", "priority", "fair", "deadline"}
    clk = FakeClock()
    for name, cls in POLICIES.items():
        p = make_policy(name, clk)
        assert isinstance(p, cls) and p.clock is clk
    assert isinstance(make_policy(None), FCFSPolicy)
    with pytest.raises(ValueError, match="unknown scheduling policy"):
        make_policy("lifo")


def test_default_scheduler_is_fcfs():
    sched = _sched()
    assert isinstance(sched.policy, FCFSPolicy)


def test_fcfs_order_matches_pre_policy_scheduler():
    """The FCFS policy reproduces the historical behavior exactly:
    head-of-line admission, admission-order prefill packing."""
    sched = _sched(FCFSPolicy(FakeClock()), max_batch=3)
    for i in range(4):
        sched.add(_req(f"r{i}"))
    kind, entries = sched.next_batch(token_budget=32)
    assert kind == "mixed"
    assert [s.req.rid for s, _ in entries] == ["r0", "r1", "r2"]
    assert [r.rid for r in sched.waiting] == ["r3"]


# ---------------------------------------------------------------------------
# priority
# ---------------------------------------------------------------------------


def test_priority_admission_beats_queue_position():
    """Priority inversion resolved: with a bulk request running and more
    bulk ahead of it in the queue, a late-arriving high-priority request
    takes the next free slot ahead of the whole bulk backlog."""
    sched = _sched(PriorityPolicy(FakeClock()), max_batch=1)
    sched.add(_req("bulk0", priority=0))
    sched.next_batch(token_budget=16)  # bulk0 seats (alone in the queue)
    sched.add(_req("bulk1", priority=0))
    sched.add(_req("urgent", priority=10))  # arrives LAST
    sched.retire(sched.running()[0])
    sched.next_batch(token_budget=16)
    assert [s.req.rid for s in sched.running()] == ["urgent"]
    assert [r.rid for r in sched.waiting] == ["bulk1"]


def test_priority_admits_highest_first_from_cold_queue():
    sched = _sched(PriorityPolicy(FakeClock()), max_batch=2)
    sched.add(_req("low", priority=-5))
    sched.add(_req("mid", priority=0))
    sched.add(_req("high", priority=3))
    kind, entries = sched.next_batch(token_budget=32)
    assert kind == "mixed"
    # high admits first, then mid; low waits.  Prefill packing follows
    # the same ranking: high's chunk leads the packed batch
    assert [s.req.rid for s, _ in entries] == ["high", "mid"]
    assert [r.rid for r in sched.waiting] == ["low"]


def test_priority_fcfs_within_class():
    sched = _sched(PriorityPolicy(FakeClock()), max_batch=3)
    for rid in ("a", "b", "c"):
        sched.add(_req(rid, priority=1))
    kind, entries = sched.next_batch(token_budget=32)
    assert [s.req.rid for s, _ in entries] == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# fair share
# ---------------------------------------------------------------------------


def test_fair_share_starving_tenant_gets_served():
    """Tenant A floods the queue; tenant B's single request must not
    starve — after A has accumulated usage, B's next request admits
    ahead of A's backlog."""
    clk = FakeClock()
    sched = _sched(FairSharePolicy(clk), max_batch=1)
    for i in range(4):
        sched.add(_req(f"a{i}", tenant="A"))
    # A's first request seats (B not yet arrived), generates, retires
    kind, entries = sched.next_batch(token_budget=16)
    assert [s.req.rid for s, _ in entries] == ["a0"]
    _complete_prefill(entries)
    sched.add(_req("b0", tenant="B"))  # B arrives BEHIND a1..a3
    sched.retire(sched.running()[0])  # a0 done: A's usage is on the books
    sched.next_batch(token_budget=16)
    # fair share: B (usage 0) wins the freed slot over A's backlog
    assert [s.req.rid for s in sched.running()] == ["b0"]


def test_fair_share_live_usage_counts():
    """A tenant's RUNNING request counts as usage: with A live in slot 0,
    a fresh B admission wins slot 1 over more A work."""
    sched = _sched(FairSharePolicy(FakeClock()), max_batch=2)
    sched.add(_req("a0", tenant="A", n_prompt=8))
    kind, entries = sched.next_batch(token_budget=16)  # a0 seats alone
    _complete_prefill(entries)  # a0's prompt is fed: 8 tokens of live usage
    sched.add(_req("a1", tenant="A"))
    sched.add(_req("b0", tenant="B"))
    sched.next_batch(token_budget=16)
    assert {s.req.rid for s in sched.running()} == {"a0", "b0"}


def test_fair_share_decay_forgives_history():
    p = FairSharePolicy(FakeClock())
    p.usage = {"A": 100.0, "B": 1.0}
    p.decay(0.5)
    assert p.usage["A"] == 50.0 and p.usage["B"] == 0.5
    p.decay(0.0)
    assert p.usage == {}


# ---------------------------------------------------------------------------
# deadline (TTFT EDF)
# ---------------------------------------------------------------------------


def test_deadline_admission_is_edf():
    clk = FakeClock()
    sched = _sched(DeadlinePolicy(clk), max_batch=1)
    sched.add(_req("relaxed", ttft_slo_s=100.0))
    sched.add(_req("urgent", ttft_slo_s=1.0))
    sched.add(_req("none"))  # no deadline ranks last
    sched.next_batch(token_budget=16)
    assert [s.req.rid for s in sched.running()] == ["urgent"]


def test_deadline_prefill_packing_prefers_least_slack():
    """Both requests admitted; the one nearing its TTFT deadline packs
    its prefill chunk FIRST, taking the step's leftover budget — a pure
    reordering of the same chunks."""
    clk = FakeClock()
    sched = _sched(DeadlinePolicy(clk), max_batch=2, prefill_chunk=8,
                   max_seq_length=64)
    sched.add(_req("early", n_prompt=20, ttft_slo_s=50.0))
    clk.advance(0.1)
    sched.add(_req("late", n_prompt=20, ttft_slo_s=5.0))
    # budget 9: the least-slack request ("late", deadline t=5.1 vs 50)
    # gets the full 8-token chunk; "early" gets the 1-token leftover
    kind, entries = sched.next_batch(token_budget=9)
    assert kind == "mixed"
    assert [(s.req.rid, n) for s, n in entries] == [("late", 8), ("early", 1)]
    # as "late"'s deadline passes and "early"'s nears, order holds by
    # slack — late is MORE overdue, still first
    clk.advance(10.0)
    kind, entries = sched.next_batch(token_budget=9)
    assert [s.req.rid for s, _ in entries][0] == "late"


def test_deadline_free_requests_fcfs_after_deadlines():
    clk = FakeClock()
    sched = _sched(DeadlinePolicy(clk), max_batch=3)
    sched.add(_req("n0"))
    sched.add(_req("n1"))
    sched.add(_req("d0", ttft_slo_s=10.0))
    kind, entries = sched.next_batch(token_budget=32)
    assert [s.req.rid for s, _ in entries] == ["d0", "n0", "n1"]


def test_policy_pick_that_cannot_fit_blocks_admission():
    """A policy pick that does not fit stops admission — it is NOT
    skipped in favor of later arrivals it outranks (conservative block
    accounting + no starvation of the pick)."""
    # pool sized so the big request cannot be seated while small ones run
    sched = _sched(PriorityPolicy(FakeClock()), num_blocks=7, block_size=4,
                   max_batch=2, max_seq_length=24)
    sched.add(_req("small", n_prompt=4, new=2, priority=0))
    sched.next_batch(token_budget=16)
    sched.add(_req("big", n_prompt=16, new=7, priority=5))
    sched.add(_req("small2", n_prompt=4, new=2, priority=0))
    sched.next_batch(token_budget=16)
    # big (priority 5) is the pick; it cannot fit -> small2 must NOT
    # bypass it into the free slot
    assert [s.req.rid for s in sched.running()] == ["small"]
    assert [r.rid for r in sched.waiting] == ["big", "small2"]


# ---------------------------------------------------------------------------
# fake-clock open-loop SLO sweep: synthetic knee
# ---------------------------------------------------------------------------


def test_sweep_finds_synthetic_knee():
    """The offered-load sweep against an M/M/1-style synthetic latency
    model: TTFT p99 ~ base / (1 - qps/capacity) blows past the SLO at a
    known utilization — the sweep must report the last passing grid
    point and the first failing one."""
    from mdi_llm_tpu.server.loadgen import sweep_offered_load

    capacity = 10.0
    base = 0.2

    def measure(qps):
        if qps >= capacity:
            return {"ttft_p99_s": float("inf"), "tpot_p99_s": 0.05,
                    "rejected": 0}
        return {"ttft_p99_s": base / (1.0 - qps / capacity),
                "tpot_p99_s": 0.05, "rejected": 0}

    # SLO 1.05 s: base/(1-u) crosses it between u=0.8 (1.0) and u=0.9
    # (2.0) — the knee sits between the 8 and 9 grid points (the ceiling
    # is 1.05, not 1.0, so the qps=8 point cannot flake on the float
    # rounding of 0.2/0.2)
    out = sweep_offered_load(
        measure, [2, 4, 6, 8, 9, 10], {"ttft_p99_s": 1.05, "tpot_p99_s": 0.5}
    )
    assert out["max_qps_ok"] == 8
    assert out["knee_qps"] == 9
    rows = {r["qps"]: r for r in out["rows"]}
    assert rows[8]["slo_ok"] and not rows[9]["slo_ok"]
    assert "ttft_p99_s" in rows[9]["slo_failures"][0]
    # the walk stopped at the first miss: qps=10 never measured
    assert 10 not in rows


def test_sweep_rejections_fail_slo():
    """A sweep point that sheds load misses its SLO by definition: a
    429'd arrival never got a first token, so the survivors' p99 alone
    must not declare the point healthy."""
    from mdi_llm_tpu.server.loadgen import sweep_offered_load

    def measure(qps):
        return {"ttft_p99_s": 0.1, "tpot_p99_s": 0.01,
                "rejected": 3 if qps > 5 else 0}

    out = sweep_offered_load(
        measure, [4, 6], {"ttft_p99_s": 1.0, "tpot_p99_s": 0.5}
    )
    assert out["max_qps_ok"] == 4 and out["knee_qps"] == 6
    assert "rejected=3" in out["rows"][-1]["slo_failures"]


def test_open_loop_runner_keeps_arrival_schedule():
    """Open loop on a fake clock: arrivals stick to their offsets (the
    sleep sequence is exactly the scheduled gaps), rejections count
    without raising, and completed handles are awaited."""
    import threading

    from mdi_llm_tpu.server.frontend import QueueFullError
    from mdi_llm_tpu.server.loadgen import ArrivalSpec, OpenLoopRunner

    clk = FakeClock()
    sleeps = []

    def sleep(dt):
        sleeps.append(round(dt, 6))
        clk.advance(dt)

    class StubHandle:
        def __init__(self):
            self.done = threading.Event()
            self.done.set()
            self.error = None
            self.cancelled = False

    class StubFrontend:
        def __init__(self):
            self.submitted = []

        def submit(self, prompt, max_new_tokens, rid=None, **kw):
            if rid == "rej":
                raise QueueFullError("full")
            self.submitted.append((rid, clk()))
            return StubHandle()

    front = StubFrontend()
    arrivals = [
        ArrivalSpec("a", [1], 1, at_s=0.5),
        ArrivalSpec("rej", [1], 1, at_s=1.25),
        ArrivalSpec("b", [1], 1, at_s=3.0),
    ]
    rep = OpenLoopRunner(front, arrivals, clock=clk, sleep=sleep).run()
    assert sleeps == [0.5, 0.75, 1.75]  # exactly the scheduled gaps
    assert [r for r, _ in front.submitted] == ["a", "b"]
    assert [t for _, t in front.submitted] == [0.5, 3.0]
    assert rep.offered == 3 and rep.accepted == 2 and rep.rejected == 1
    assert rep.completed == 2 and rep.errored == 0
    assert rep.offered_qps == pytest.approx(1.0)  # 3 arrivals / 3 s


def test_poisson_and_replay_arrival_builders():
    from mdi_llm_tpu.server.loadgen import poisson_arrivals, replay_arrivals

    trace = [(f"r{i}", [1, 2], 4) for i in range(50)]
    arr = poisson_arrivals(trace, qps=5.0, seed=3)
    assert len(arr) == 50
    gaps = [arr[0].at_s] + [
        b.at_s - a.at_s for a, b in zip(arr, arr[1:])
    ]
    assert all(g > 0 for g in gaps)
    # mean gap ~ 1/qps (loose 3-sigma-ish bound for n=50)
    assert 0.1 < sum(gaps) / len(gaps) < 0.4
    assert poisson_arrivals(trace, 5.0, seed=3)[10].at_s == arr[10].at_s
    with pytest.raises(ValueError):
        poisson_arrivals(trace, 0.0)

    rep = replay_arrivals([("a", [1], 2, 1.0), ("b", [1], 2, 3.0)], speed=2.0)
    assert [a.at_s for a in rep] == [0.5, 1.5]
    with pytest.raises(ValueError):
        replay_arrivals([], speed=0)
