"""bench.py suite-mode logic, offline: the orchestrator must be un-losable
(emit a headline JSON whatever the backend does).  Children are simulated
by monkeypatching bench._child, so these tests cover the scheduling /
retry / fallback / assembly logic without any device.
"""

import json
import types

import pytest

import bench


def _args(**kw):
    ns = types.SimpleNamespace(
        suite_budget=kw.pop("suite_budget", 600.0),
        rows=kw.pop("rows", None),
        probe_timeout=kw.pop("probe_timeout", 5.0),
        probe_retries=kw.pop("probe_retries", 1),
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def _probe_ok():
    return {"metric": "backend probe", "value": 1.0, "unit": "s",
            "vs_baseline": 1.0,
            "detail": {"backend": "tpu", "device": "TPU v5 lite0"}}


def _row(value, model="tiny-llama-1.1b", vs=None):
    return {
        "metric": f"decode tokens/sec/chip ({model})",
        "value": value, "unit": "tokens/s/chip",
        "vs_baseline": vs if vs is not None else round(value / 7.0, 2),
        "detail": {"config": {"model": model}},
    }


def run_suite_with(monkeypatch, child_fn, hardware=True, **args_kw):
    monkeypatch.setattr(bench, "_child", child_fn)
    monkeypatch.setattr(bench.time, "sleep", lambda *_: None)
    # the suite gates probing on host-local TPU hardware evidence (the r6
    # wedge fix); these orchestration tests simulate children, so claim
    # hardware unless the test IS about the no-hardware fast path
    monkeypatch.setattr(
        bench, "_tpu_hardware_evidence",
        lambda: {"present": hardware, "dev_accel": [], "dev_vfio": [],
                 "env": {"TPU_NAME": "sim"} if hardware else {}},
    )
    return bench.run_suite(_args(**args_kw))


def test_happy_path_all_rows(monkeypatch):
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return _probe_ok(), None
        if "Llama-3-8B-Instruct" in argv:
            return _row(500.0, "Llama-3-8B-Instruct", vs=12.5), None
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child)
    assert out["value"] == 2700.0
    assert out["detail"]["north_star"]["met"] is True
    assert out["detail"]["north_star"]["vs_jetson_8b"] == 12.5
    assert set(out["detail"]["rows"]) == {r["name"] for r in bench.SUITE_ROWS}
    json.dumps(out)  # the artifact must be serializable


def _effective_batch(argv):
    """argparse semantics: the LAST --batch occurrence wins."""
    idx = max(i for i, a in enumerate(argv) if a == "--batch")
    return argv[idx + 1]


def test_ladder_walks_down_on_error(monkeypatch):
    tried = []

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return _probe_ok(), None
        b = _effective_batch(argv)
        tried.append(b)
        if b == "24":  # headline config OOMs; the ladder rung succeeds
            return None, "error: RESOURCE_EXHAUSTED"
        return _row(2283.0), None

    out = run_suite_with(monkeypatch, child, rows="tinyllama-bf16")
    row = out["detail"]["rows"]["tinyllama-bf16"]
    assert "error" not in row
    assert row["value"] == 2283.0
    assert tried == ["24", "16"]  # walked exactly one rung down


def test_backend_drop_retries_same_config_first(monkeypatch):
    seen = []

    def child(argv, timeout, env=None):
        flat = " ".join(argv)
        if "--probe" in argv:
            return _probe_ok(), None
        seen.append(flat)
        # first attempt at the intended config drops; the retry succeeds
        if len(seen) == 1:
            return None, "backend: Unable to initialize backend 'axon'"
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child, rows="tinyllama-bf16")
    assert out["value"] == 2700.0
    # the retry reran the SAME flags rather than degrading the ladder
    assert seen[0] == seen[1]
    assert "--batch 24" in seen[1]


def test_timeout_marks_wedged_and_skips_rest(monkeypatch):
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return _probe_ok(), None
        if "Llama-3-8B-Instruct" in " ".join(argv):
            return None, "timeout"
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child)
    rows = out["detail"]["rows"]
    assert out["value"] == 2700.0  # the already-banked headline survives
    assert "wedged" in rows["llama3-8b-int8"]["error"]
    # everything after the wedge is skipped, not attempted
    assert rows["ring-pipeline-m16"]["error"].startswith("skipped")
    assert rows["llama3-8b-int4"]["error"].startswith("skipped")
    assert out["detail"]["north_star"]["met"] is False


def test_tpu_never_up_falls_back_to_cpu(monkeypatch):
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return None, "timeout"
        assert "--backend" in argv and "cpu" in argv
        return _row(0.7), None

    out = run_suite_with(monkeypatch, child)
    assert out["value"] == 0.7
    assert "cpu-fallback" in " ".join(out["detail"]["rows"]).lower() or \
        "tinyllama-bf16-cpu-fallback" in out["detail"]["rows"]


def test_north_star_picks_better_8b_row(monkeypatch):
    def child(argv, timeout, env=None):
        flat = " ".join(argv)
        if "--probe" in argv:
            return _probe_ok(), None
        if "int8" in flat and "Llama" in flat:
            return _row(40.0, "Llama-3-8B-Instruct", vs=1.0), None
        if "int4" in flat and "Llama" in flat:
            return _row(80.0, "Llama-3-8B-Instruct", vs=2.0), None
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child)
    ns = out["detail"]["north_star"]
    assert ns["met"] is True and ns["vs_jetson_8b"] == 2.0


def test_everything_fails_still_emits(monkeypatch):
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return _probe_ok(), None
        return None, "error: boom"

    out = run_suite_with(monkeypatch, child)
    assert out["unit"] == "tokens/s/chip"
    assert out["value"] == 0.0
    json.dumps(out)


def test_baseline_for_routes_by_model():
    assert bench.baseline_for("Llama-3-8B-Instruct") == bench.JETSON_8B_TOKENS_PER_S
    assert bench.baseline_for("tiny-llama-1.1b") == bench.REFERENCE_TOKENS_PER_S


def test_probe_budget_env_overrides(monkeypatch):
    """The probe budget is configurable without editing flags (driver-run
    suites only control the environment): MDI_BENCH_PROBE_TIMEOUT /
    MDI_BENCH_PROBE_RETRIES feed the parser defaults."""
    monkeypatch.setenv("MDI_BENCH_PROBE_TIMEOUT", "33.5")
    monkeypatch.setenv("MDI_BENCH_PROBE_RETRIES", "3")
    args = bench.build_parser().parse_args([])
    assert args.probe_timeout == 33.5
    assert args.probe_retries == 3
    # explicit flags still win over the env defaults
    args = bench.build_parser().parse_args(
        ["--probe-timeout", "7", "--probe-retries", "0"]
    )
    assert args.probe_timeout == 7.0 and args.probe_retries == 0


def test_probe_failures_respect_retry_budget(monkeypatch):
    """BENCH_r05 burned 900 s on probe timeouts: with N retries the suite
    must launch exactly N+1 probes before the CPU fallback, not a fixed 4."""
    probes = []

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            probes.append(timeout)
            return None, "error: no backend"
        return _row(0.7), None

    out = run_suite_with(monkeypatch, child, probe_retries=2)
    assert len(probes) == 3
    assert "tinyllama-bf16-cpu-fallback" in out["detail"]["rows"]

    probes.clear()
    run_suite_with(monkeypatch, child, probe_retries=0)
    assert len(probes) == 1  # zero-retry budget: one attempt, straight to CPU

    # a raised budget is honored for TIMEOUT failures too (the slow-tunnel
    # bring-up case the env knob exists for)
    def child_timeout(argv, timeout, env=None):
        if "--probe" in argv:
            probes.append(timeout)
            return None, "timeout"
        return _row(0.7), None

    probes.clear()
    run_suite_with(monkeypatch, child_timeout, probe_retries=4)
    assert len(probes) == 5


def test_probe_attempt_diagnostics_in_suite_json(monkeypatch):
    """Every probe attempt records backend/error/elapsed in detail.probe:
    the r03–r05 TPU→CPU fallback wedge was undiagnosable from the suite
    artifact alone (events only said "attempt N failed") — the artifact
    must now carry WHY each attempt failed."""
    calls = []

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            calls.append(timeout)
            if len(calls) == 1:
                return None, "timeout"
            return _probe_ok(), None
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child, probe_retries=1)
    probe = out["detail"]["probe"]
    assert probe["tpu_ok"] is True
    assert probe["budget_s"] == 5.0 and probe["retries_allowed"] == 1
    a1, a2 = probe["attempts"]
    assert a1["attempt"] == 1 and a1["ok"] is False
    assert a1["error"] == "timeout" and a1["backend"] is None
    assert "elapsed_s" in a1
    assert a2["ok"] is True and a2["backend"] == "tpu"
    assert a2["device"] == "TPU v5 lite0" and a2["error"] is None
    json.dumps(out)

    # the never-up path banks its failed attempts too
    calls.clear()

    def child_dead(argv, timeout, env=None):
        if "--probe" in argv:
            return None, "backend: Unable to initialize backend"
        return _row(0.7), None

    out = run_suite_with(monkeypatch, child_dead, probe_retries=0)
    probe = out["detail"]["probe"]
    assert probe["tpu_ok"] is False
    assert len(probe["attempts"]) == 1
    assert "Unable to initialize" in probe["attempts"][0]["error"]


def test_probe_budget_is_a_hard_total_cap(monkeypatch):
    """BENCH_r05 burned 900 s because each probe attempt got the full
    budget again (events showed attempts still starting at t=420 s and
    t=900 s despite the 180 s default).  The budget is TOTAL: an attempt
    runs against the remaining window, retry sleeps draw from the same
    budget, and the CPU fallback starts the moment it expires."""
    clock = [0.0]
    monkeypatch.setattr(bench.time, "perf_counter", lambda: clock[0])
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: clock.__setitem__(0, clock[0] + s)
    )
    # this test drives run_suite directly (fake clock), so it claims
    # hardware evidence itself — the no-hardware path never probes at all
    monkeypatch.setattr(
        bench, "_tpu_hardware_evidence",
        lambda: {"present": True, "dev_accel": [], "dev_vfio": [],
                 "env": {"TPU_NAME": "sim"}},
    )
    probes = []

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            probes.append(timeout)
            clock[0] += timeout  # the probe hangs for its whole window
            return None, "timeout"
        return _row(0.7), None

    monkeypatch.setattr(bench, "_child", child)
    out = bench.run_suite(_args(probe_timeout=180.0, probe_retries=5))
    # one attempt consumed the entire budget: no retry may start after it
    assert len(probes) == 1 and probes[0] <= 180.0
    assert sum(probes) <= 180.0
    assert "tinyllama-bf16-cpu-fallback" in out["detail"]["rows"]

    # a half-budget hang leaves room for exactly one shorter retry (minus
    # the 60 s sleep), never a fresh full-length attempt
    probes.clear()
    clock[0] = 0.0

    def child_half(argv, timeout, env=None):
        if "--probe" in argv:
            probes.append(timeout)
            clock[0] += min(timeout, 90.0)
            return None, "error: no backend"
        return _row(0.7), None

    monkeypatch.setattr(bench, "_child", child_half)
    bench.run_suite(_args(probe_timeout=180.0, probe_retries=5))
    assert len(probes) == 2
    assert probes[0] <= 180.0 and probes[1] <= 180.0 - 90.0
    assert sum(probes) <= 180.0 + 60.0  # sleeps bounded by the budget too


def test_costly_compiles_run_after_every_decode_row():
    # the ring row has the costliest compile in the suite (its r5 cold
    # compile blew a 900 s timeout and wedged the tunnel); it and the
    # train row must come after every decode row so a timeout cannot skip
    # a north-star measurement
    names = [r["name"] for r in bench.SUITE_ROWS]
    assert names[-2:] == ["tinyllama-train-2k", "ring-pipeline-m16"]


def test_train_mode_smoke():
    # a few real optimizer steps on a registry model, loss finite, MFU in
    # (0, 1).  run_train is called directly (bypassing run_direct's
    # --backend handling): conftest.py already pins the CPU platform for
    # every test process, so no backend flag is needed here
    ap = bench.build_parser()
    args = ap.parse_args(
        ["--direct", "--mode", "train",
         "--model", "pythia-14m", "--batch", "2", "--seq-len", "64",
         "--train-steps", "2"]
    )
    out = bench.run_train(args)
    assert out["unit"] == "tokens/s/chip"
    assert out["value"] > 0
    assert 0 < out["vs_baseline"] < 1
    assert out["detail"]["final_loss"] == out["detail"]["final_loss"]  # not NaN
    # the MFU peak routes through the obs/roofline.py table now: on the
    # CPU backend it falls back to the assumed v5e reference, labelled
    d = out["detail"]
    assert d["mfu"] == out["vs_baseline"]
    assert d["peak_tflops_per_s"] == 197.0
    assert "assumed" in d["peak_source"]


def test_kernel_mode_smoke():
    # the paged-attention microbench (suite row kernel-paged) on the CPU
    # backend: kernel timings need a TPU so they record null, but the
    # fallback/dense grid must land for all three dispatch shapes at BOTH
    # pool dtypes — the acceptance contract that the in-kernel dequant
    # cost is measured per dtype, not asserted
    ap = bench.build_parser()
    args = ap.parse_args(
        ["--direct", "--mode", "kernel",
         "--model", "pythia-14m", "--batch", "2", "--seq-len", "128"]
    )
    out = bench.run_kernel(args)
    assert out["unit"] == "us" and out["value"] > 0
    grid = out["detail"]["grid"]
    assert set(grid) == {f"{op}-{t}" for op in ("decode", "ragged", "prefill")
                         for t in ("fp", "int8")}
    for row in grid.values():
        assert row["fallback_us"] > 0
    for op in ("decode", "ragged", "prefill"):
        assert grid[f"{op}-fp"]["dense_us"] > 0
        assert grid[f"{op}-int8"]["kernel_us"] is None  # CPU: no Pallas
        assert grid[f"{op}-int8"]["kernel_default_us"] is None
    # tuned-vs-default provenance rides the row even off-TPU: no table,
    # no device -> the conservative resolution, fully-resolved params
    tuning = out["detail"]["tuning"]
    for tag in ("fp", "int8"):
        assert tuning[tag]["tuned"] is False
        assert tuning[tag]["table_source"] == "conservative"
        assert tuning[tag]["params"]["kv_step"] >= 1
        assert tuning[tag]["default_params"] == {
            "kv_step": None, "q_pack": None, "scratch_width": 128}
    assert tuning["int8"]["key"].split("/")[1] == "int8"


def test_serve_pool_mib_doubles_int8_blocks():
    # the acceptance ratio through the engine-facing path: at the same
    # --serve-pool-mib byte budget, the int8 pool's max_blocks (and so the
    # resident sequences a block-bound pool holds) >= 1.8x the fp pool's
    from mdi_llm_tpu.config import Config

    cfg = Config.from_name("tiny-llama-1.1b")
    ap = bench.build_parser()
    blocks = {}
    for dtype in ("auto", "int8"):
        args = ap.parse_args(
            ["--direct", "--mode", "serve", "--model", "tiny-llama-1.1b",
             "--batch", "8", "--seq-len", "2048", "--kv-dtype", dtype,
             "--serve-pool-mib", "24"]
        )
        blocks[dtype] = bench._serve_config(args, cfg).max_blocks
    assert blocks["int8"] >= 1.8 * blocks["auto"]


def test_suite_has_int8_and_kernel_rows():
    rows = {r["name"]: r for r in bench.SUITE_ROWS}
    q8 = rows["serving-cb-int8"]
    assert "--kv-dtype" in q8["flags"] and "int8" in q8["flags"]
    # fixed pool bytes: the row pins --serve-pool-mib so its fp_reference
    # twin compares capacity at EQUAL budget, and the last ladder rung
    # falls back to the fp pool
    assert "--serve-pool-mib" in q8["flags"]
    assert q8["ladder"][-1] == ["--kv-dtype", "auto"]
    assert rows["kernel-paged"]["flags"][1] == "kernel"


def test_suite_embeds_provenance_header(monkeypatch):
    """Every suite artifact carries a provenance header (toolchain
    versions, host, probe-relevant env) — trajectory JSONs from different
    environments become diffable.  Captured via importlib.metadata, so it
    lands even when the backend never comes up."""
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return None, "timeout"  # dead backend: header must still land
        return _row(0.7), None

    out = run_suite_with(monkeypatch, child)
    prov = out["detail"]["provenance"]
    assert prov["versions"]["jax"], "jax version must come from metadata"
    assert prov["hostname"] and prov["python"]
    assert all(
        k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_", "PJRT_"))
        for k in prov["env"]
    )
    json.dumps(out)


def test_doctor_flag_embeds_snapshot(monkeypatch):
    """bench --doctor runs the staged mdi-doctor --quick triage and embeds
    the snapshot as detail.doctor, alongside (not replacing) the probe."""
    import mdi_llm_tpu.cli.doctor as doctor_mod

    fake_snap = {"schema": 1, "ok": False, "quick": True,
                 "stages": [{"name": "devices", "status": "timeout"}]}
    monkeypatch.setattr(
        doctor_mod, "collect_snapshot",
        lambda quick=False, **kw: dict(fake_snap, quick=quick),
    )

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return _probe_ok(), None
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child, doctor=True,
                         rows="tinyllama-bf16")
    assert out["detail"]["doctor"]["ok"] is False
    assert out["detail"]["doctor"]["quick"] is True
    assert out["detail"]["probe"]["tpu_ok"] is True  # probe still decides
    # an UNHEALTHY doctor is diagnostic, not fatal: the row still ran
    assert out["detail"]["rows"]["tinyllama-bf16"]["value"] == 2700.0
    # without the flag the suite makes no doctor call and embeds nothing
    out2 = run_suite_with(monkeypatch, child, rows="tinyllama-bf16")
    assert "doctor" not in out2["detail"]


def test_probe_detail_carries_device_provenance():
    """run_probe's detail now records device_kind + toolchain versions —
    the suite-side key into the obs/roofline.py peak table and the other
    half of the r03-wedge forensics."""
    out = bench.run_probe()
    d = out["detail"]
    assert d["device_kind"] == "cpu"  # conftest pins the CPU platform
    assert d["device_count"] >= 1
    assert d["versions"]["jax"]
    json.dumps(out)


def test_doctor_flag_in_help():
    help_text = bench.build_parser().format_help()
    assert "--doctor" in help_text


def test_banked_artifacts_attached_to_suite_output(monkeypatch):
    """Committed bench_results/ JSONs must surface in every suite output —
    including a CPU-fallback run on a dead backend — so the hardware
    record is never lost from the round artifact."""
    def child(argv, timeout, env=None):
        if "--probe" in argv:
            return None, "timeout"
        return _row(14.0), None

    out = run_suite_with(monkeypatch, child)
    banked = out["detail"].get("banked_artifacts")
    assert banked, "bench_results/ exists in this repo; summary missing"
    runs = banked["runs"]
    assert "r5_manual_suite_run1.json" in runs
    r5 = runs["r5_manual_suite_run1.json"]
    assert r5["tinyllama-bf16"]["value"] == 2727.11
    assert "TPU" in r5["llama3-8b-int8"]["device"]


# ---------------------------------------------------------------------------
# open-system serving row + the r6 probe-wedge fix
# ---------------------------------------------------------------------------


def test_suite_has_serving_open_row():
    rows = {r["name"]: r for r in bench.SUITE_ROWS}
    so = rows["serving-open"]
    assert so["flags"][1] == "serve-open"
    # the ladder shrinks the sweep, never abandons the open-system shape
    assert all("--serve-open-requests" in rung or "--batch" in rung
               for rung in so["ladder"])


def test_serve_open_flags_in_help():
    help_text = bench.build_parser().format_help()
    for flag in ("--serve-open-qps", "--serve-open-requests",
                 "--slo-ttft-ms", "--slo-tpot-ms"):
        assert flag in help_text, f"{flag} missing from bench --help"
    assert "serve-open" in help_text


def test_sampled_spec_flags_in_help_and_suite_row():
    """The rejection-sampled speculative knobs are documented on bench
    --help and the suite carries the spec-vs-sampling head-to-head rung
    (same seed, per-step sampling fallback in the ladder)."""
    help_text = bench.build_parser().format_help()
    for flag in ("--spec-k", "--temperature", "--top-k", "--top-p",
                 "--draft-model"):
        assert flag in help_text, f"{flag} missing from bench --help"
    rows = {r["name"]: r for r in bench.SUITE_ROWS}
    spec = rows["serving-cb-spec"]
    assert "--spec-k" in spec["flags"] and "--temperature" in spec["flags"]
    # the ladder degrades to plain sampled serving, never drops the row
    assert ["--spec-k", "0", "--temperature", "0.7"] in spec["ladder"]


def test_no_hardware_skips_probe_and_banks_serving_fallbacks(monkeypatch):
    """The r6 wedge fix: with no host-local TPU evidence the suite never
    probes (libtpu's metadata retry storm burned the whole r03–r05 probe
    budget on hosts with nothing to find), falls back in milliseconds,
    and the CPU fallback now banks SERVING rows too — serving-cb/open had
    never had an in-suite number on any backend."""
    calls = []

    def child(argv, timeout, env=None):
        calls.append(list(argv))
        assert "--probe" not in argv, "probed despite no hardware evidence"
        if "serve-open" in argv:
            return {"metric": "serving max QPS", "value": 3.2,
                    "unit": "req/s@slo", "vs_baseline": 1.0, "detail": {}}, None
        if "serve" in argv:
            return {"metric": "serving tokens/sec/chip", "value": 30.0,
                    "unit": "tokens/s/chip", "vs_baseline": 4.3,
                    "detail": {}}, None
        return _row(2.0), None

    out = run_suite_with(monkeypatch, child, hardware=False)
    probe = out["detail"]["probe"]
    assert probe["attempts"] == [] and probe["tpu_ok"] is False
    assert probe["hardware"]["present"] is False
    rows = out["detail"]["rows"]
    assert rows["tinyllama-bf16-cpu-fallback"]["value"] == 2.0
    assert rows["serving-cb-cpu-fallback"]["value"] == 30.0
    assert rows["serving-open-cpu-fallback"]["value"] == 3.2
    # every fallback child was forced onto the CPU backend
    assert all("cpu" in c[c.index("--backend") + 1] for c in calls)


def test_mds_wedge_signature_triggers_skip_retry(monkeypatch):
    """A probe failure carrying libtpu's metadata-retry-storm signature
    makes the NEXT attempt run with TPU_SKIP_MDS_QUERY=1 — fail fast
    with a named cause instead of burning the budget on 30x-retry URL
    fetches."""
    envs = []

    def child(argv, timeout, env=None):
        if "--probe" in argv:
            envs.append(env)
            if len(envs) == 1:
                return None, ("timeout: Failed to get TPU metadata "
                              "(tpu-env) ... 30 tries (http status: 403)")
            return _probe_ok(), None
        return _row(2700.0), None

    out = run_suite_with(monkeypatch, child, rows="tinyllama-bf16",
                         probe_retries=1, probe_timeout=600.0)
    assert envs[0] is None
    assert envs[1] == {"TPU_SKIP_MDS_QUERY": "1"}
    attempts = out["detail"]["probe"]["attempts"]
    assert attempts[0]["env"] is None and "metadata" in attempts[0]["error"]
    assert attempts[1]["env"] == {"TPU_SKIP_MDS_QUERY": "1"}
    assert out["detail"]["probe"]["tpu_ok"] is True
    assert out["value"] == 2700.0


def test_tpu_hardware_evidence_is_local_and_fast():
    ev = bench._tpu_hardware_evidence()
    assert set(ev) == {"dev_accel", "dev_vfio", "env", "present"}
    assert isinstance(ev["present"], bool)
    json.dumps(ev)


def test_child_timeout_keeps_stderr_tail(monkeypatch):
    """TimeoutExpired diagnosis: the child's dying stderr rides the error
    string (the r03–r05 'timeout' told nothing; the storm signature was
    in the killed child's output all along)."""
    import subprocess

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(
            cmd, kw.get("timeout"),
            stderr=b"noise\nFailed to get TPU metadata (tpu-env) x\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    res, err = bench._child(["--probe"], timeout=1.0)
    assert res is None
    assert err.startswith("timeout:")
    assert bench._MDS_WEDGE_SIGNATURE in err
