"""CLI end-to-end tests: sample (single + pipeline), prepare_data, train,
prepare_model partitioning, plot overlay.  Uses a tiny HF llama checkpoint +
word-level tokenizer built on the fly."""

import json

import numpy as np
import pytest

import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    """A full checkpoint dir: converted weights + tokenizer + configs."""
    torch = pytest.importorskip("torch")
    from tokenizers import Tokenizer as HFTok
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from transformers import LlamaConfig, LlamaForCausalLM

    from mdi_llm_tpu.utils.checkpoint import convert_hf_checkpoint

    d = tmp_path_factory.mktemp("ckpt") / "tiny-llama-test"
    hf_cfg = LlamaConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf_cfg).save_pretrained(d)

    words = "the quick brown fox jumps over lazy dog and cat runs far".split()
    vocab = {"<s>": 0, "</s>": 1, "<unk>": 2}
    for w in words:
        vocab[w] = len(vocab)
    t = HFTok(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    t.save(str(d / "tokenizer.json"))
    (d / "tokenizer_config.json").write_text(
        json.dumps({"bos_token": "<s>", "eos_token": "</s>", "add_bos_token": False})
    )
    convert_hf_checkpoint(d, dtype=jnp.float32)
    return d


def test_sample_cli_single_device(tiny_ckpt, tmp_path, capsys):
    from mdi_llm_tpu.cli.sample import main

    outs = main(
        [
            "--ckpt", str(tiny_ckpt),
            "--dtype", "float32",
            "--n-samples", "2",
            "--n-tokens", "6",
            "--prompt", "the quick brown fox",
            "--greedy",
            "--plots",
            "--time-run", str(tmp_path / "stats.csv"),
            "--logs-dir", str(tmp_path / "logs"),
        ]
    )
    assert len(outs) == 2 and all(len(o) > 4 for o in outs)
    captured = capsys.readouterr()
    assert "sample 0" in captured.out and "sample 1" in captured.out
    csvs = list((tmp_path / "logs").glob("tokens_time_samples_1nodes_*_2samples.csv"))
    assert len(csvs) == 1
    assert (tmp_path / "stats.csv").exists()
    assert csvs[0].with_suffix(".png").exists()


def test_sample_cli_pipeline_matches_single(tiny_ckpt, tmp_path, devices):
    from mdi_llm_tpu.cli.sample import main

    common = [
        "--ckpt", str(tiny_ckpt),
        "--dtype", "float32",
        "--n-samples", "2",
        "--n-tokens", "5",
        "--prompt", "lazy dog runs",
        "--greedy",
    ]
    single = main(common)
    piped = main(common + ["--pipeline-stages", "3"])
    assert piped == single


def test_sample_cli_sp_matches_single(tiny_ckpt, devices):
    from mdi_llm_tpu.cli.sample import main

    common = [
        "--ckpt", str(tiny_ckpt),
        "--dtype", "float32",
        "--n-samples", "2",
        "--n-tokens", "5",
        "--prompt", "lazy dog runs",
        "--greedy",
    ]
    single = main(common)
    sp = main(common + ["--sp-devices", "2"])
    assert sp == single
    # quantized sp (int8 weights + sequence-sharded KV) matches quantized
    # single-device decode — delivered in r5, was a SystemExit before
    single_q = main(common + ["--quantize", "int8"])
    sp_q = main(common + ["--sp-devices", "2", "--quantize", "int8"])
    assert sp_q == single_q
    with pytest.raises(SystemExit):
        main(common + ["--sp-devices", "2", "--pipeline-stages", "2"])


def test_prepare_data_and_train_cli(tiny_ckpt, tmp_path):
    from mdi_llm_tpu.cli.prepare_data import main as prep_main
    from mdi_llm_tpu.cli.train import main as train_main

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over lazy dog " * 400)
    prep_main(
        ["--dataset", str(corpus), "--ckpt", str(tiny_ckpt), "--out", str(tmp_path / "data")]
    )
    assert (tmp_path / "data" / "train.bin").exists()

    out_dir = tmp_path / "run"
    out_dir.mkdir()
    # copy model config so the trainer builds the tiny architecture
    (out_dir / "model_config.yaml").write_text(
        (tiny_ckpt / "model_config.yaml").read_text()
    )
    result = train_main(
        [
            "--ckpt", str(out_dir),
            "--dataset", str(tmp_path / "data"),
            "--dtype", "float32",
            "--batch-size", "2",
            "--block-size", "16",
            "--max-iters", "4",
            "--ckpt-interval", "2",
            "--eval-iters", "1",
            "--log-interval", "2",
            "--no-remat",
        ]
    )
    assert result["iter_num"] == 4
    assert (out_dir / "params").exists()
    # resume path
    result2 = train_main(
        [
            "--ckpt", str(out_dir),
            "--dataset", str(tmp_path / "data"),
            "--init", "resume",
            "--max-iters", "6",
        ]
    )
    assert result2["iter_num"] == 6


def test_prepare_model_cli_stages(tiny_ckpt):
    from mdi_llm_tpu.cli.prepare_model import main

    out = main([str(tiny_ckpt), "--n-stages", "3", "--dtype", "float32"])
    chunk_dir = out / "chunks" / "3stages"
    assert (chunk_dir / "stage_map.json").exists()
    for i in range(3):
        assert (chunk_dir / f"stage_{i}" / "params").exists()
    manifest = json.loads((chunk_dir / "stage_map.json").read_text())
    assert sum(manifest["stage_layers"]) == 3


def test_chat_cli_scripted(tiny_ckpt, monkeypatch, capsys):
    from mdi_llm_tpu.cli import chat

    inputs = iter(["the quick brown", ""])
    monkeypatch.setattr("builtins.input", lambda *_: next(inputs))
    chat.main(["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens", "5"])
    out = capsys.readouterr().out
    assert "Chatting with" in out


def test_chat_cli_two_turns_then_eof(tiny_ckpt, monkeypatch, capsys):
    """Drive the chat REPL with scripted stdin (two turns then EOF)."""
    from mdi_llm_tpu.cli import chat

    lines = iter(["the quick brown", "fox jumps over"])

    def fake_input(prompt=""):
        try:
            return next(lines)
        except StopIteration:
            raise EOFError

    monkeypatch.setattr("builtins.input", fake_input)
    rc = chat.main(
        ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens", "6",
         "--temperature", "0.0"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Chatting with" in out


def test_chat_cli_tp_mesh(tiny_ckpt, monkeypatch, capsys):
    """Streaming chat over a tp=2 GSPMD mesh (tiny ckpt, CPU devices)."""
    from mdi_llm_tpu.cli import chat

    inputs = iter(["the quick brown", ""])
    monkeypatch.setattr("builtins.input", lambda *_: next(inputs))
    rc = chat.main(
        ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens", "4",
         "--tp-devices", "2", "--temperature", "0.0"]
    )
    assert rc == 0
    assert "Chatting with" in capsys.readouterr().out


def test_sample_cli_tp_quantized(tiny_ckpt, devices):
    """--tp-devices composes with --quantize through the CLI (the pre-r5
    make_tp_mesh guard is gone): same tokens as single-device quantized."""
    from mdi_llm_tpu.cli.sample import main

    common = [
        "--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-samples", "2",
        "--n-tokens", "5", "--prompt", "lazy dog runs", "--greedy",
        "--quantize", "int8",
    ]
    single_q = main(common)
    tp_q = main(common + ["--tp-devices", "2"])
    assert tp_q == single_q


def test_chat_cli_sp_mesh(tiny_ckpt, monkeypatch, capsys):
    """Streaming chat over a 2-way sequence-parallel mesh (VERDICT r4
    missing #3: chat could not drive the sp backend), plus quantize —
    the long-context serving shape end to end through the REPL."""
    from mdi_llm_tpu.cli import chat

    inputs = iter(["the quick brown", ""])
    monkeypatch.setattr("builtins.input", lambda *_: next(inputs))
    rc = chat.main(
        ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens", "4",
         "--sp-devices", "2", "--sp-chunk", "2", "--quantize", "int8",
         "--temperature", "0.0"]
    )
    assert rc == 0
    assert "Chatting with" in capsys.readouterr().out


def test_stop_prefix_filter_unit():
    """StopPrefixFilter invariants, directly: multi-token stops are never
    emitted (not even partially), interleaved near-miss prefixes are
    released once disambiguated, and flush() drains only stop-free tails."""
    from mdi_llm_tpu.generation import StopPrefixFilter

    def run(stops, tokens, flush=True):
        out = []
        f = StopPrefixFilter(stops, out.append)
        for t in tokens:
            f.push(t)
        if flush:
            f.flush()
        return out, f.stopped

    # full stop sequence suppressed entirely
    out, stopped = run([[8, 9]], [1, 2, 8, 9, 3])
    assert out == [1, 2] and stopped
    # near-miss prefix (8 not followed by 9) is eventually released
    out, stopped = run([[8, 9]], [1, 8, 2, 3])
    assert out == [1, 8, 2, 3] and not stopped
    # longest stop sets the hold-back; shorter stop still detected
    out, stopped = run([[7], [8, 9]], [1, 2, 7])
    assert out == [1, 2] and stopped
    # no stops at all: everything streams immediately (hold == 0)
    out, stopped = run([], [4, 5, 6], flush=False)
    assert out == [4, 5, 6]
    # tokens after the stop are ignored
    out, stopped = run([[9]], [1, 9, 5, 6])
    assert out == [1] and stopped


def test_sample_cli_ep_devices_validation(tiny_ckpt):
    """--ep-devices rejects non-MoE configs and other parallelism flags
    (the happy path is pinned at the Generator level in test_expert.py)."""
    from mdi_llm_tpu.cli.sample import main

    with pytest.raises(SystemExit, match="MoE config"):
        main(["--ckpt", str(tiny_ckpt), "--dtype", "float32",
              "--ep-devices", "2", "--n-tokens", "2"])
    with pytest.raises(SystemExit, match="standalone expert-parallel"):
        main(["--ckpt", str(tiny_ckpt), "--dtype", "float32",
              "--ep-devices", "2", "--tp-devices", "2", "--n-tokens", "2"])
    with pytest.raises(SystemExit, match="at least 2 devices"):
        main(["--ckpt", str(tiny_ckpt), "--dtype", "float32",
              "--ep-devices", "-1", "--n-tokens", "2"])


def test_chat_cli_pipeline_ring(tiny_ckpt, monkeypatch, capsys):
    """Streaming chat over a 2-stage recurrent pipeline ring (virtual CPU
    mesh): the reply must stream and match what the REPL records."""
    from mdi_llm_tpu.cli import chat

    inputs = iter(["the quick brown", ""])
    monkeypatch.setattr("builtins.input", lambda *_: next(inputs))
    rc = chat.main(
        ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens", "5",
         "--pipeline-stages", "2", "--temperature", "0.0"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Chatting with" in out
    # reply text itself may be empty (random weights can emit an immediate
    # stop token); token-level parity is pinned by
    # test_chat_cli_pipeline_matches_single


def test_chat_cli_pipeline_matches_single(tiny_ckpt, monkeypatch, capsys):
    """Greedy pipeline chat reply text equals the single-device reply."""
    from mdi_llm_tpu.cli import chat

    def run(extra):
        inputs = iter(["the quick brown", ""])
        monkeypatch.setattr("builtins.input", lambda *_: next(inputs))
        rc = chat.main(
            ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--n-tokens",
             "6", "--temperature", "0.0"] + extra
        )
        assert rc == 0
        return capsys.readouterr().out

    single = run([])
    piped = run(["--pipeline-stages", "2"])
    assert single.split("Chatting with", 1)[1] == piped.split("Chatting with", 1)[1]


def test_starter_stream_flag(tiny_ckpt, tmp_path, capsys):
    """--stream prints sample 0's text live; output must equal the final
    decoded sample text (same filter+trim contract as chat)."""
    import json as _json

    from mdi_llm_tpu.cli.starter import main as starter_main

    cfg_p = tmp_path / "standalone.json"
    cfg_p.write_text(_json.dumps({"nodes": {"starter": {"addr": "127.0.0.1",
        "communication": {"port": 1}}, "secondary": []}}))
    outs = starter_main(
        ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--nodes-config",
         str(cfg_p), "--n-tokens", "5", "--prompt", "the quick", "--greedy",
         "--pipeline-stages", "2", "--stream"]
    )
    assert len(outs) == 1 and len(outs[0]) >= 3
    captured = capsys.readouterr().out
    # the streamed prefix (printed before report_run's '--- sample 0'
    # header) must equal the decoded trimmed generation of sample 0 —
    # incl. any tail the filter held back until finish()
    from mdi_llm_tpu.utils.tokenizer import Tokenizer

    tok = Tokenizer(tiny_ckpt)
    n_prompt = len(tok.encode("the quick").tolist())
    expected = tok.decode(np.asarray(outs[0][n_prompt:]))
    streamed = captured.split("--- sample 0")[0].strip()
    # (with this fixture the greedy continuation may decode to "" — the
    # printer's emission/flush logic itself is pinned deterministically by
    # test_stream_printer_unit)
    assert streamed == expected.strip()


def test_stream_printer_unit(capsys):
    """StreamPrinter end-to-end with a fake tokenizer: incremental decode
    prints only stabilized suffixes, the stop filter holds prefixes, and
    finish() reconciles with the authoritative trimmed list."""
    import sys

    from mdi_llm_tpu.generation import StreamPrinter

    class FakeTok:
        def decode(self, ids):
            return " ".join(f"w{int(i)}" for i in ids)

    # no stop: everything streams; finish adds the tail the stream missed
    p = StreamPrinter(FakeTok(), [], out=sys.stdout)
    for t in (1, 2):
        p.push(t)
    assert p.finish([1, 2, 3]) == [1, 2, 3]
    assert capsys.readouterr().out == "w1 w2 w3"

    # stop sequence [8, 9]: held prefix never printed, finish is a no-op
    p = StreamPrinter(FakeTok(), [[8, 9]], out=sys.stdout)
    for t in (1, 8, 9, 5):
        p.push(t)
    assert p.finish([1]) == [1]
    assert capsys.readouterr().out == "w1"

    # budget end with a held near-miss prefix: finish flushes it
    p = StreamPrinter(FakeTok(), [[8, 9]], out=sys.stdout)
    for t in (1, 8):
        p.push(t)  # 8 held back as a possible stop prefix
    assert p.finish([1, 8]) == [1, 8]
    assert capsys.readouterr().out == "w1 w8"


def test_starter_debug_writes_role_log(tiny_ckpt, tmp_path):
    import json as _json
    import logging

    from mdi_llm_tpu.cli.starter import main as starter_main

    cfg_p = tmp_path / "standalone.json"
    cfg_p.write_text(_json.dumps({"nodes": {"starter": {"addr": "127.0.0.1",
        "communication": {"port": 1}}, "secondary": []}}))
    try:
        starter_main(
            ["--ckpt", str(tiny_ckpt), "--dtype", "float32", "--nodes-config",
             str(cfg_p), "--n-tokens", "4", "--prompt", "the quick", "--debug",
             "--logs-dir", str(tmp_path / "logs"), "--pipeline-stages", "1"]
        )
    finally:
        # drop the file handler so later tests don't write here
        log = logging.getLogger("mdi_llm_tpu")
        for h in list(log.handlers):
            log.removeHandler(h)
    assert (tmp_path / "logs" / "logs_starter.log").exists()
