"""Core model correctness: shapes, KV-cache decode parity, config registry.

The decisive test is `test_kv_cache_decode_matches_full_forward`: feeding a
sequence token-by-token through the cached decode path must reproduce the
logits of one full uncached forward — this pins down RoPE indexing, cache
scatter offsets, and the position-based causal mask all at once (the
reference has no such test; SURVEY.md §4 calls for adding it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import (
    forward,
    init_params,
    init_kv_cache,
    count_params,
)


def tiny_config(**kw):
    base = dict(
        name="test-tiny",
        block_size=64,
        vocab_size=128,
        padded_vocab_size=128,
        n_layer=3,
        n_head=4,
        n_embd=32,
        n_query_groups=4,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMLP",
        intermediate_size=64,
    )
    base.update(kw)
    return Config(**base)


CONFIG_VARIANTS = {
    "llama": {},
    "gqa": dict(n_query_groups=2),
    "mqa": dict(n_query_groups=1),
    "neox": dict(
        parallel_residual=True,
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
        rotary_percentage=0.25,
    ),
    "shared-norm": dict(
        parallel_residual=True,
        shared_attention_norm=True,
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
    ),
    "gpt2": dict(
        rotary_percentage=0.0,
        pos_embedding="learned",
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
        tie_embeddings=True,
    ),
    "moe": dict(
        mlp_class_name="LLaMAMoE",
        n_expert=4,
        n_expert_per_token=2,
    ),
    "gemma": dict(
        name="Gemma-test",
        mlp_class_name="GemmaMLP",
        scale_embeddings=True,
        tie_embeddings=True,
        gelu_approximate="tanh",
    ),
}


@pytest.mark.parametrize("variant", list(CONFIG_VARIANTS))
def test_forward_shapes(variant):
    cfg = tiny_config(**CONFIG_VARIANTS[variant])
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.arange(10, dtype=jnp.int32).reshape(1, 10) % cfg.vocab_size
    logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))
    assert logits.shape == (1, 10, cfg.padded_vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("variant", ["llama", "gqa", "neox", "gpt2", "moe"])
def test_kv_cache_decode_matches_full_forward(variant):
    cfg = tiny_config(**CONFIG_VARIANTS[variant])
    params = init_params(cfg, jax.random.PRNGKey(1))
    T = 12
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, cfg.vocab_size)

    full_logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))

    kv = init_kv_cache(cfg, batch_size=1, max_seq_length=32, dtype=jnp.float32)
    step_logits = []
    for t in range(T):
        lg, kv = forward(
            cfg,
            params,
            tokens[:, t : t + 1],
            jnp.array([t], jnp.int32),
            kv=kv,
        )
        step_logits.append(lg[:, 0])
    step_logits = jnp.stack(step_logits, axis=1)

    np.testing.assert_allclose(
        np.asarray(full_logits), np.asarray(step_logits), rtol=2e-4, atol=2e-4
    )


def test_prefill_then_decode_matches_full_forward():
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(3))
    T_prompt, T_total = 8, 14
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (1, T_total), 0, cfg.vocab_size
    )

    full_logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))

    kv = init_kv_cache(cfg, 1, 32, dtype=jnp.float32)
    prefill_logits, kv = forward(
        cfg, params, tokens[:, :T_prompt], jnp.zeros((1,), jnp.int32), kv=kv
    )
    np.testing.assert_allclose(
        np.asarray(full_logits[:, :T_prompt]),
        np.asarray(prefill_logits),
        rtol=2e-4,
        atol=2e-4,
    )
    for t in range(T_prompt, T_total):
        lg, kv = forward(
            cfg, params, tokens[:, t : t + 1], jnp.array([t], jnp.int32), kv=kv
        )
        np.testing.assert_allclose(
            np.asarray(full_logits[:, t]), np.asarray(lg[:, 0]), rtol=2e-4, atol=2e-4
        )


def test_batched_decode_with_per_sample_positions():
    """Two samples at different sequence offsets in one batched step must
    each match their own single-sample decode (the batched analog of the
    reference's per-sample rotating KV caches, gptserver.py:751-784)."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(5))
    S = 32
    t0 = jax.random.randint(jax.random.PRNGKey(6), (1, 5), 0, cfg.vocab_size)
    t1 = jax.random.randint(jax.random.PRNGKey(7), (1, 9), 0, cfg.vocab_size)

    # individual runs
    refs = []
    for toks in (t0, t1):
        kv = init_kv_cache(cfg, 1, S, dtype=jnp.float32)
        lg, kv = forward(cfg, params, toks, jnp.zeros((1,), jnp.int32), kv=kv)
        refs.append(np.asarray(lg[:, -1]))

    # batched: right-pad prompts to a common length, per-sample input_pos=0,
    # gather each sample's last-valid logit
    Tp = 9
    batch = jnp.concatenate(
        [
            jnp.pad(t0, ((0, 0), (0, Tp - t0.shape[1]))),
            t1,
        ],
        axis=0,
    )
    kv = init_kv_cache(cfg, 2, S, dtype=jnp.float32)
    lg, kv = forward(cfg, params, batch, jnp.zeros((2,), jnp.int32), kv=kv)
    np.testing.assert_allclose(refs[0], np.asarray(lg[0:1, t0.shape[1] - 1]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(refs[1], np.asarray(lg[1:2, t1.shape[1] - 1]), rtol=2e-4, atol=2e-4)


def test_uncached_chunk_at_offset_is_causal():
    """A no-cache forward of a chunk at nonzero input_pos must still be
    causal within the chunk (regression: key positions were assumed to start
    at 0, making every key visible to every query)."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(8))
    toks = jax.random.randint(jax.random.PRNGKey(9), (1, 6), 0, cfg.vocab_size)
    lg_a, _ = forward(cfg, params, toks, jnp.array([3], jnp.int32))
    # perturb the last token: earlier logits must not change
    toks_b = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
    lg_b, _ = forward(cfg, params, toks_b, jnp.array([3], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(lg_a[:, :-1]), np.asarray(lg_b[:, :-1]), rtol=1e-6, atol=1e-6
    )


def test_param_count_matches_estimate():
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    est = cfg.estimate_params()
    actual = count_params(params)
    assert abs(est - actual) / actual < 0.01


def test_registry_basics():
    cfg = Config.from_name("tiny-llama-1.1b")
    assert cfg.n_layer == 22 and cfg.n_embd == 2048 and cfg.n_query_groups == 4
    cfg3 = Config.from_name("Llama-3-8B-Instruct")
    assert cfg3.padded_vocab_size == 128256 and cfg3.rope_base == 500000
    g = Config.from_name("gpt2-large")
    assert g.n_layer == 36 and g.pos_embedding == "learned"
    n = Config.from_name("NanoLlama")
    assert 2.5e8 < n.estimate_params() < 3.5e8


def test_config_yaml_roundtrip(tmp_path):
    cfg = Config.from_name("tiny-llama-1.1b")
    cfg.save(tmp_path)
    cfg2 = Config.from_file(tmp_path / "model_config.yaml")
    assert cfg2.asdict() == cfg.asdict()
