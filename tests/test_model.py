"""Core model correctness: shapes, KV-cache decode parity, config registry.

The decisive test is `test_kv_cache_decode_matches_full_forward`: feeding a
sequence token-by-token through the cached decode path must reproduce the
logits of one full uncached forward — this pins down RoPE indexing, cache
scatter offsets, and the position-based causal mask all at once (the
reference has no such test; SURVEY.md §4 calls for adding it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import (
    forward,
    init_params,
    init_kv_cache,
    count_params,
)


def tiny_config(**kw):
    base = dict(
        name="test-tiny",
        block_size=64,
        vocab_size=128,
        padded_vocab_size=128,
        n_layer=3,
        n_head=4,
        n_embd=32,
        n_query_groups=4,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMLP",
        intermediate_size=64,
    )
    base.update(kw)
    return Config(**base)


CONFIG_VARIANTS = {
    "llama": {},
    "gqa": dict(n_query_groups=2),
    "mqa": dict(n_query_groups=1),
    "neox": dict(
        parallel_residual=True,
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
        rotary_percentage=0.25,
    ),
    "shared-norm": dict(
        parallel_residual=True,
        shared_attention_norm=True,
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
    ),
    "gpt2": dict(
        rotary_percentage=0.0,
        pos_embedding="learned",
        bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        intermediate_size=None,
        tie_embeddings=True,
    ),
    "moe": dict(
        mlp_class_name="LLaMAMoE",
        n_expert=4,
        n_expert_per_token=2,
    ),
    "gemma": dict(
        name="Gemma-test",
        mlp_class_name="GemmaMLP",
        scale_embeddings=True,
        tie_embeddings=True,
        gelu_approximate="tanh",
    ),
}


def test_scan_unroll_parity():
    """unroll > 1 is a pure scheduling change: logits must be identical."""
    cfg = tiny_config(n_layer=4)
    params = init_params(cfg, jax.random.PRNGKey(3))
    toks = jnp.arange(8, dtype=jnp.int32).reshape(1, 8) % cfg.vocab_size
    pos0 = jnp.zeros((1,), jnp.int32)
    kv1 = init_kv_cache(cfg, 1, 32, dtype=jnp.float32)
    kv2 = init_kv_cache(cfg, 1, 32, dtype=jnp.float32)
    l1, _ = forward(cfg, params, toks, pos0, kv=kv1)
    l2, _ = forward(cfg, params, toks, pos0, kv=kv2, unroll=2)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


@pytest.mark.parametrize("variant", list(CONFIG_VARIANTS))
def test_forward_shapes(variant):
    cfg = tiny_config(**CONFIG_VARIANTS[variant])
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.arange(10, dtype=jnp.int32).reshape(1, 10) % cfg.vocab_size
    logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))
    assert logits.shape == (1, 10, cfg.padded_vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("variant", ["llama", "gqa", "neox", "gpt2", "moe"])
def test_kv_cache_decode_matches_full_forward(variant):
    cfg = tiny_config(**CONFIG_VARIANTS[variant])
    params = init_params(cfg, jax.random.PRNGKey(1))
    T = 12
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, cfg.vocab_size)

    full_logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))

    kv = init_kv_cache(cfg, batch_size=1, max_seq_length=32, dtype=jnp.float32)
    step_logits = []
    for t in range(T):
        lg, kv = forward(
            cfg,
            params,
            tokens[:, t : t + 1],
            jnp.array([t], jnp.int32),
            kv=kv,
        )
        step_logits.append(lg[:, 0])
    step_logits = jnp.stack(step_logits, axis=1)

    np.testing.assert_allclose(
        np.asarray(full_logits), np.asarray(step_logits), rtol=2e-4, atol=2e-4
    )


def test_prefill_then_decode_matches_full_forward():
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(3))
    T_prompt, T_total = 8, 14
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (1, T_total), 0, cfg.vocab_size
    )

    full_logits, _ = forward(cfg, params, tokens, jnp.zeros((1,), jnp.int32))

    kv = init_kv_cache(cfg, 1, 32, dtype=jnp.float32)
    prefill_logits, kv = forward(
        cfg, params, tokens[:, :T_prompt], jnp.zeros((1,), jnp.int32), kv=kv
    )
    np.testing.assert_allclose(
        np.asarray(full_logits[:, :T_prompt]),
        np.asarray(prefill_logits),
        rtol=2e-4,
        atol=2e-4,
    )
    for t in range(T_prompt, T_total):
        lg, kv = forward(
            cfg, params, tokens[:, t : t + 1], jnp.array([t], jnp.int32), kv=kv
        )
        np.testing.assert_allclose(
            np.asarray(full_logits[:, t]), np.asarray(lg[:, 0]), rtol=2e-4, atol=2e-4
        )


def test_batched_decode_with_per_sample_positions():
    """Two samples at different sequence offsets in one batched step must
    each match their own single-sample decode (the batched analog of the
    reference's per-sample rotating KV caches, gptserver.py:751-784)."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(5))
    S = 32
    t0 = jax.random.randint(jax.random.PRNGKey(6), (1, 5), 0, cfg.vocab_size)
    t1 = jax.random.randint(jax.random.PRNGKey(7), (1, 9), 0, cfg.vocab_size)

    # individual runs
    refs = []
    for toks in (t0, t1):
        kv = init_kv_cache(cfg, 1, S, dtype=jnp.float32)
        lg, kv = forward(cfg, params, toks, jnp.zeros((1,), jnp.int32), kv=kv)
        refs.append(np.asarray(lg[:, -1]))

    # batched: right-pad prompts to a common length, per-sample input_pos=0,
    # gather each sample's last-valid logit
    Tp = 9
    batch = jnp.concatenate(
        [
            jnp.pad(t0, ((0, 0), (0, Tp - t0.shape[1]))),
            t1,
        ],
        axis=0,
    )
    kv = init_kv_cache(cfg, 2, S, dtype=jnp.float32)
    lg, kv = forward(cfg, params, batch, jnp.zeros((2,), jnp.int32), kv=kv)
    np.testing.assert_allclose(refs[0], np.asarray(lg[0:1, t0.shape[1] - 1]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(refs[1], np.asarray(lg[1:2, t1.shape[1] - 1]), rtol=2e-4, atol=2e-4)


def test_uncached_chunk_at_offset_is_causal():
    """A no-cache forward of a chunk at nonzero input_pos must still be
    causal within the chunk (regression: key positions were assumed to start
    at 0, making every key visible to every query)."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(8))
    toks = jax.random.randint(jax.random.PRNGKey(9), (1, 6), 0, cfg.vocab_size)
    lg_a, _ = forward(cfg, params, toks, jnp.array([3], jnp.int32))
    # perturb the last token: earlier logits must not change
    toks_b = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
    lg_b, _ = forward(cfg, params, toks_b, jnp.array([3], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(lg_a[:, :-1]), np.asarray(lg_b[:, :-1]), rtol=1e-6, atol=1e-6
    )


def test_param_count_matches_estimate():
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    est = cfg.estimate_params()
    actual = count_params(params)
    assert abs(est - actual) / actual < 0.01


def test_registry_basics():
    cfg = Config.from_name("tiny-llama-1.1b")
    assert cfg.n_layer == 22 and cfg.n_embd == 2048 and cfg.n_query_groups == 4
    cfg3 = Config.from_name("Llama-3-8B-Instruct")
    assert cfg3.padded_vocab_size == 128256 and cfg3.rope_base == 500000
    g = Config.from_name("gpt2-large")
    assert g.n_layer == 36 and g.pos_embedding == "learned"
    n = Config.from_name("NanoLlama")
    assert 2.5e8 < n.estimate_params() < 3.5e8


# Every named model the reference registry exposes (src/sub/config.py:175-1669,
# name_to_config keys incl. expanded {} templates).  Full-surface parity: a
# reference user must be able to `Config.from_name` any of these.
REFERENCE_REGISTRY_NAMES = [
    "Camel-Platypus2-13B", "Camel-Platypus2-70B", "CodeGemma-7b-it",
    "CodeLlama-13b-Instruct-hf", "CodeLlama-13b-Python-hf", "CodeLlama-13b-hf",
    "CodeLlama-34b-Instruct-hf", "CodeLlama-34b-Python-hf", "CodeLlama-34b-hf",
    "CodeLlama-70b-Instruct-hf", "CodeLlama-70b-Python-hf", "CodeLlama-70b-hf",
    "CodeLlama-7b-Instruct-hf", "CodeLlama-7b-Python-hf", "CodeLlama-7b-hf",
    "Danube2-1.8b-chat", "FreeWilly2", "Gemma-2b", "Gemma-2b-it", "Gemma-7b",
    "Gemma-7b-it", "LLaMA-2-7B-32K", "Llama-2-13b-chat-hf", "Llama-2-13b-hf",
    "Llama-2-70b-chat-hf", "Llama-2-70b-hf", "Llama-2-7b-chat-hf",
    "Llama-2-7b-chat-hf-function-calling-v2", "Llama-2-7b-hf", "Llama-3-70B",
    "Llama-3-70B-Instruct", "Llama-3-8B", "Llama-3-8B-Instruct",
    "Mistral-7B-Instruct-v0.1", "Mistral-7B-Instruct-v0.2",
    "Mistral-7B-Instruct-v0.3", "Mistral-7B-v0.1", "Mistral-7B-v0.2",
    "Mistral-7B-v0.3", "Mixtral-8x7B-Instruct-v0.1", "Mixtral-8x7B-v0.1",
    "Nous-Hermes-13b", "Nous-Hermes-Llama2-13b", "Nous-Hermes-llama-2-7b",
    "Platypus-30B", "Platypus2-13B", "Platypus2-70B", "Platypus2-70B-instruct",
    "Platypus2-7B", "RedPajama-INCITE-7B-Base", "RedPajama-INCITE-7B-Chat",
    "RedPajama-INCITE-7B-Instruct", "RedPajama-INCITE-Base-3B-v1",
    "RedPajama-INCITE-Base-7B-v0.1", "RedPajama-INCITE-Chat-3B-v1",
    "RedPajama-INCITE-Chat-7B-v0.1", "RedPajama-INCITE-Instruct-3B-v1",
    "RedPajama-INCITE-Instruct-7B-v0.1", "Stable-Platypus2-13B", "dolly-v2-12b",
    "dolly-v2-3b", "dolly-v2-7b", "falcon-180B", "falcon-180B-chat",
    "falcon-40b", "falcon-40b-instruct", "falcon-7b", "falcon-7b-instruct",
    "longchat-13b-16k", "longchat-7b-16k", "open_llama_13b", "open_llama_3b",
    "open_llama_7b", "phi-1_5", "phi-2", "pythia-1.4b", "pythia-1.4b-deduped",
    "pythia-12b", "pythia-12b-deduped", "pythia-14m", "pythia-160m",
    "pythia-160m-deduped", "pythia-1b", "pythia-1b-deduped", "pythia-2.8b",
    "pythia-2.8b-deduped", "pythia-31m", "pythia-410m", "pythia-410m-deduped",
    "pythia-6.9b", "pythia-6.9b-deduped", "pythia-70m", "pythia-70m-deduped",
    "stable-code-3b", "stablecode-completion-alpha-3b",
    "stablecode-completion-alpha-3b-4k", "stablecode-instruct-alpha-3b",
    "stablelm-3b-4e1t", "stablelm-base-alpha-3b", "stablelm-base-alpha-7b",
    "stablelm-tuned-alpha-3b", "stablelm-tuned-alpha-7b", "stablelm-zephyr-3b",
    "tiny-llama-1.1b", "tiny-llama-1.1b-chat", "vicuna-13b-v1.3",
    "vicuna-13b-v1.5", "vicuna-13b-v1.5-16k", "vicuna-33b-v1.3",
    "vicuna-7b-v1.3", "vicuna-7b-v1.5", "vicuna-7b-v1.5-16k",
]


def test_registry_covers_every_reference_model():
    missing = []
    for name in REFERENCE_REGISTRY_NAMES:
        try:
            cfg = Config.from_name(name)
        except Exception:
            missing.append(name)
            continue
        assert cfg.n_layer > 0 and cfg.padded_vocab_size % 2 == 0
    assert not missing, f"unresolvable reference model names: {missing}"


def test_registry_spot_facts():
    assert Config.from_name("pythia-14m").block_size == 512
    assert Config.from_name("pythia-31m").block_size == 1024
    k32 = Config.from_name("LLaMA-2-7B-32K")
    assert k32.rope_condense_ratio == 8 and k32.block_size == 32768
    # positional-interpolation long-context variants (reference
    # config.py:666,700,735,757)
    for nm in ("longchat-7b-16k", "longchat-13b-16k"):
        lc = Config.from_name(nm)
        assert lc.rope_condense_ratio == 8 and lc.norm_eps == 1e-6
    for nm in ("vicuna-7b-v1.5-16k", "vicuna-13b-v1.5-16k"):
        vc = Config.from_name(nm)
        assert vc.rope_condense_ratio == 4 and vc.norm_eps == 1e-5
    assert Config.from_name("vicuna-7b-v1.5").rope_condense_ratio == 1
    sc = Config.from_name("stable-code-3b")
    assert sc.mlp_class_name == "LLaMAMLP" and sc.padded_vocab_size == 50304
    mx = Config.from_name("Mixtral-8x7B-v0.1")
    assert mx.n_expert == 8 and mx.n_expert_per_token == 2
    # deliberate divergences from reference-registry quirks, matching the
    # actual HF checkpoints instead:
    assert Config.from_name("Platypus2-70B").n_query_groups == 8  # GQA, not MHA
    assert Config.from_name("Gemma-7b").block_size == 8192
    assert Config.from_name("CodeLlama-13b-Instruct-hf").block_size == 16384


def test_config_yaml_roundtrip(tmp_path):
    cfg = Config.from_name("tiny-llama-1.1b")
    cfg.save(tmp_path)
    cfg2 = Config.from_file(tmp_path / "model_config.yaml")
    assert cfg2.asdict() == cfg.asdict()
