"""Tooling CLIs: inspect_ckpt, model_surgery, convert_to_hf, prepare_owt.

≡ reference dev/maintenance tools: `src/scripts/inspect_lit.py`,
`old/GPT2/model_surgery.py`, `sub/utils/convert_lit_checkpoint.py`,
`src/prepare_owt.py`.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models.transformer import init_params
from mdi_llm_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


@pytest.fixture(scope="module")
def saved_ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("tools") / "toy"
    cfg = Config(
        name="toy-llama",
        block_size=64,
        vocab_size=96,
        padded_vocab_size=96,
        n_layer=4,
        n_head=4,
        n_embd=32,
        n_query_groups=2,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMLP",
        intermediate_size=64,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    save_checkpoint(params, cfg, d)
    return d


def test_inspect_ckpt(saved_ckpt, capsys):
    from mdi_llm_tpu.cli.inspect_ckpt import main

    main(["--ckpt", str(saved_ckpt), "--n-stages", "2"])
    out = capsys.readouterr().out
    assert "toy-llama" in out and "n_layer=4" in out
    assert "stage split over 2 stages" in out
    assert "wte" in out and "lm_head" in out


def test_model_surgery_set_and_dry_run(saved_ckpt, capsys):
    from mdi_llm_tpu.cli.model_surgery import main

    main(["--ckpt", str(saved_ckpt), "--set", "block_size=32", "--dry-run"])
    cfg, _ = load_checkpoint(saved_ckpt)
    assert cfg.block_size == 64  # dry run: unchanged

    main(["--ckpt", str(saved_ckpt), "--set", "block_size=32"])
    cfg, _ = load_checkpoint(saved_ckpt)
    assert cfg.block_size == 32

    with pytest.raises(SystemExit):
        main(["--ckpt", str(saved_ckpt), "--set", "nonsense_field=1"])

    # restore for later tests sharing the module-scoped fixture
    main(["--ckpt", str(saved_ckpt), "--set", "block_size=64"])


def test_convert_to_hf_roundtrip(saved_ckpt, tmp_path):
    from mdi_llm_tpu.cli.convert_to_hf import main
    from mdi_llm_tpu.utils.checkpoint import convert_to_hf_state_dict

    out = tmp_path / "export"
    main(["--ckpt", str(saved_ckpt), "--out", str(out)])
    files = list(out.iterdir())
    assert len(files) == 1 and files[0].suffix in (".safetensors", ".bin")

    cfg, params = load_checkpoint(saved_ckpt)
    sd = convert_to_hf_state_dict(cfg, params)
    assert "model.embed_tokens.weight" in sd
    assert any(k.startswith("model.layers.3.") for k in sd)


def test_prepare_owt_local_dataset(tmp_path):
    datasets = pytest.importorskip("datasets")
    from tokenizers import Tokenizer as HFTok
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    import json

    from mdi_llm_tpu.cli.prepare_owt import main
    from mdi_llm_tpu.utils.data_loader import open_bin

    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    words = "alpha beta gamma delta epsilon zeta".split()
    vocab = {"<s>": 0, "</s>": 1, "<unk>": 2}
    for w in words:
        vocab[w] = len(vocab)
    t = HFTok(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Whitespace()
    t.save(str(tok_dir / "tokenizer.json"))
    (tok_dir / "tokenizer_config.json").write_text(
        json.dumps({"bos_token": "<s>", "eos_token": "</s>", "add_bos_token": False})
    )

    docs = [" ".join(np.random.default_rng(i).choice(words, 20)) for i in range(40)]
    ds = datasets.Dataset.from_dict({"text": docs})
    ds_dir = tmp_path / "ds"
    ds.save_to_disk(str(ds_dir))

    out = tmp_path / "bins"
    main([
        "--dataset", str(ds_dir), "--ckpt", str(tok_dir), "--out", str(out),
        "--num-proc", "1", "--val-frac", "0.1",
    ])
    train = open_bin(out / "train.bin")
    val = open_bin(out / "val.bin")
    assert len(train) > len(val) > 0
    assert int(np.max(train)) < len(vocab)


def test_console_utils(capsys):
    import io

    from mdi_llm_tpu.utils.console import get_obj_size, loading_bar, waiting_animation

    assert loading_bar(0, 10) == "[" + " " * 20 + "]"
    assert loading_bar(10, 10) == "[" + "=" * 20 + "]"
    mid = loading_bar(5, 10)
    assert mid.count("=") == 9 and ">" in mid

    buf = io.StringIO()  # not a tty: spinner must stay silent
    with waiting_animation("busy", stream=buf):
        pass
    assert buf.getvalue() == ""

    small, big = get_obj_size([1]), get_obj_size([list(range(100)), "x" * 1000])
    assert big > small > 0


def test_catch_loop_errors():
    from mdi_llm_tpu.utils.context_managers import LoopInterrupted, catch_loop_errors

    # KeyboardInterrupt is swallowed, partial results survive
    collected = []
    cleaned = []
    with catch_loop_errors(on_stop=lambda: cleaned.append(1)) as guard:
        collected.append(1)
        raise KeyboardInterrupt
    assert guard.interrupted and collected == [1] and cleaned == [1]

    with catch_loop_errors() as guard:
        pass
    assert not guard.interrupted

    # real errors still propagate (after cleanup)
    cleaned.clear()
    with pytest.raises(ValueError):
        with catch_loop_errors(on_stop=lambda: cleaned.append(1)):
            raise ValueError("boom")
    assert cleaned == [1]

    with pytest.raises(ValueError):  # cleanup failure must not mask the error
        with catch_loop_errors(on_stop=lambda: 1 / 0):
            raise ValueError("boom")


def test_generator_interrupt_returns_partial():
    import jax

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models.transformer import init_params

    cfg = Config(
        name="tiny", block_size=64, vocab_size=64, padded_vocab_size=64,
        n_layer=2, n_head=2, n_embd=16, rotary_percentage=1.0,
        parallel_residual=False, bias=False, norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMLP", intermediate_size=32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    g = Generator(cfg, params, rng_seed=1)

    hits = []

    def boom(b, t):
        hits.append(t)
        if len(hits) >= 3:
            raise KeyboardInterrupt

    outs, stats = g.generate(
        [[1, 2, 3]], 20, temperature=0.0, stream_cb=boom, chunk_size=2
    )
    assert 3 <= len(outs[0]) - 3 < 20  # partial, not full
    assert stats.interrupted


def test_evaluate_cli(saved_ckpt, tmp_path, capsys):
    import json

    from mdi_llm_tpu.cli.evaluate import main

    rng = np.random.default_rng(0)
    data_dir = tmp_path / "bins"
    data_dir.mkdir()
    for split, n in (("train", 4096), ("val", 2048)):
        rng.integers(0, 96, n).astype(np.uint16).tofile(data_dir / f"{split}.bin")

    rc = main([
        "--ckpt", str(saved_ckpt), "--dataset", str(data_dir), "--split", "val",
        "--eval-iters", "2", "--batch-size", "2", "--block-size", "32",
        "--dtype", "float32",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    # random tokens vs random-ish weights: loss near ln(96)
    assert 2.0 < rec["loss"] < 8.0
    assert rec["perplexity"] > 1.0 and rec["split"] == "val"


def test_prepare_model_quantized_checkpoint(saved_ckpt):
    """--quantize writes a pre-quantized sibling checkpoint that loads and
    generates with no further flags (quantize once at prepare time).  The
    dtype-casting load path must preserve the integer weights and the f32
    scales (a blanket cast silently de-quantizes int8 and crashes int4)."""
    from mdi_llm_tpu.cli.prepare_model import main as prep_main
    from mdi_llm_tpu.generation import Generator

    prep_main([str(saved_ckpt), "--quantize", "int8", "--n-stages", "2"])
    q_dir = saved_ckpt.parent / f"{saved_ckpt.name}-int8"
    # the engine-CLI load path casts to a compute dtype
    cfg, qp = load_checkpoint(q_dir, dtype=jnp.float32)
    leaf = qp["blocks"]["attn"]["qkv"]
    assert leaf["weight_q"].dtype == jnp.int8
    assert leaf["scale"].dtype == jnp.float32
    eng = Generator(cfg, jax.device_put(qp), cache_dtype=jnp.float32)
    outs, _ = eng.generate([[5, 9, 2]], 4, temperature=0.0)
    assert len(outs[0]) == 7
    # pipeline deployments get pre-quantized stage chunks in the sibling
    chunk = q_dir / "chunks" / "2stages"
    assert (chunk / "stage_map.json").exists()
    _, st0 = load_checkpoint(chunk / "stage_0", dtype=jnp.float32, cfg=cfg)
    assert st0["blocks"]["attn"]["qkv"]["weight_q"].dtype == jnp.int8


def test_prepare_model_int4_checkpoint_generates(saved_ckpt):
    """int4 sibling survives the casting load path (packed nibbles stay
    int8) and drives the Generator end to end."""
    from mdi_llm_tpu.cli.prepare_model import main as prep_main
    from mdi_llm_tpu.generation import Generator

    prep_main([str(saved_ckpt), "--quantize", "int4"])
    q_dir = saved_ckpt.parent / f"{saved_ckpt.name}-int4"
    cfg, qp = load_checkpoint(q_dir, dtype=jnp.float32)
    assert qp["blocks"]["attn"]["qkv"]["weight_q4"].dtype == jnp.int8
    eng = Generator(cfg, jax.device_put(qp), cache_dtype=jnp.float32)
    outs, _ = eng.generate([[7, 1, 3]], 4, temperature=0.0)
    assert len(outs[0]) == 7
