"""Node-topology configs and the starter/secondary multi-process CLIs.

The two-process test is the TPU-native analog of the reference's de-facto
integration harness — localhost loopback node configs running the full
distributed stack as N processes on one host (SURVEY.md §4,
`settings_distr/configuration.json`) — with golden-token equality against
the single-device engine instead of eyeballing.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from mdi_llm_tpu.parallel.nodes import parse_nodes_config

REPO = Path(__file__).resolve().parent.parent


def _write(tmp_path, payload):
    p = tmp_path / "nodes.json"
    p.write_text(json.dumps(payload))
    return p


def test_parse_reference_schema(tmp_path):
    p = _write(
        tmp_path,
        {
            "nodes": {
                "starter": {
                    "addr": "10.0.0.1",
                    "communication": {"port": 8088},
                    "inference": {"port_in": 8090, "port_out": 8091},
                    "device": "tpu",
                },
                "secondary": [
                    {
                        "addr": "10.0.0.2",
                        "communication": {"starter_addr": "10.0.0.1", "port": 8089},
                        "inference": {"port_in": 8092, "port_out": 8093},
                    },
                    {"addr": "10.0.0.3", "communication": {"port": 8090}},
                ],
            }
        },
    )
    cfg = parse_nodes_config(p)
    assert cfg.n_nodes == 3
    assert cfg.coordinator == "10.0.0.1:8088"
    assert cfg.starter.device == "tpu"
    assert cfg.secondary[1].addr == "10.0.0.3"


def test_parse_standalone_schema(tmp_path):
    p = _write(
        tmp_path,
        {"nodes": {"starter": {"addr": "127.0.0.1", "communication": {"port": 1}}, "secondary": []}},
    )
    cfg = parse_nodes_config(p)
    assert cfg.n_nodes == 1


def test_parse_mesh_schema(tmp_path):
    p = _write(
        tmp_path,
        {"coordinator": "host0:8476", "num_processes": 2, "pipeline_stages": 16},
    )
    cfg = parse_nodes_config(p)
    assert cfg.n_nodes == 2
    assert cfg.coordinator == "host0:8476"
    assert cfg.pipeline_stages == 16


def _extract_samples(stdout: str):
    """Pull the printed token-id lists out of starter/sample stdout."""
    out = []
    grab = False
    for line in stdout.splitlines():
        if line.startswith("--- sample"):
            grab = True
            continue
        if grab and line.startswith("["):
            out.append([int(x) for x in re.findall(r"-?\d+", line)])
            grab = False
    return out


MODEL = "pythia-14m"
COMMON = ["--model", MODEL, "--device", "cpu", "--greedy", "--n-tokens", "8",
          "--n-samples", "2", "--seed", "10137"]


@pytest.mark.slow
def test_two_process_pipeline_matches_single_device(tmp_path):
    cfg_path = _write(
        tmp_path,
        {
            "nodes": {
                "starter": {"addr": "127.0.0.1", "communication": {"port": 19917}},
                "secondary": [
                    {"addr": "127.0.0.1", "communication": {"port": 19918}}
                ],
            }
        },
    )
    single = subprocess.run(
        [sys.executable, "-m", "mdi_llm_tpu.cli.sample", *COMMON],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    assert single.returncode == 0, single.stderr[-2000:]
    want = _extract_samples(single.stdout)
    assert len(want) == 2 and all(len(w) > 8 for w in want)

    sec = subprocess.Popen(
        [sys.executable, "-m", "mdi_llm_tpu.cli.secondary", *COMMON,
         "--pipeline-stages", "2", "--nodes-config", str(cfg_path), "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=REPO,
    )
    try:
        sta = subprocess.run(
            [sys.executable, "-m", "mdi_llm_tpu.cli.starter", *COMMON,
             "--pipeline-stages", "2", "--nodes-config", str(cfg_path)],
            capture_output=True, text=True, cwd=REPO, timeout=600,
        )
    finally:
        try:
            sec.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            sec.kill()
            sec.communicate()
    assert sta.returncode == 0, sta.stderr[-2000:]
    got = _extract_samples(sta.stdout)
    assert got == want, f"distributed tokens diverge\nwant {want}\ngot  {got}"
