"""mdi-race static analysis (`analysis/threads.py`): thread-role
inference (seeds, propagation, annotation pinning) and the four
concurrency rules, beyond the bad/good fixture pairs in test_lint.py.

The repo self-check in test_lint.py already gates `mdi-lint
mdi_llm_tpu/` clean with these rules enabled; this file pins the
SEMANTICS — which code shapes seed which role, what counts as a write,
what the lock-guard scoping is — so a refactor of the inference can't
silently hollow the rules out.
"""

import ast

import pytest

from mdi_llm_tpu.analysis import lint_source
from mdi_llm_tpu.analysis.core import Baseline, ModuleInfo
from mdi_llm_tpu.analysis.cli import main as lint_main
from mdi_llm_tpu.analysis.threads import thread_model

THREAD_RULES = (
    "unguarded-shared-state",
    "blocking-in-event-loop",
    "lock-order-inversion",
    "loop-call-from-wrong-thread",
)


def roles(src):
    """{function_name: sorted role list} for a snippet."""
    model = thread_model(ModuleInfo("snippet.py", src))
    return {i.name: sorted(i.roles) for i in model.funcs.values()}


def lint(src, rule):
    return lint_source(src, path="snippet.py", select=[rule])


# ---------------------------------------------------------------------------
# role inference
# ---------------------------------------------------------------------------


def test_seeds_cover_the_three_entry_shapes():
    src = """
import threading

def sink(event):
    pass

class Front:
    def __init__(self, loop):
        self.loop = loop
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()
        self.loop.call_soon_threadsafe(sink, "hello")

    def _pump(self):
        pass

    async def respond(self):
        pass
"""
    r = roles(src)
    assert r["_pump"] == ["engine"], "Thread(target=...) seeds engine"
    assert r["sink"] == ["loop"], "call_soon_threadsafe target runs on-loop"
    assert r["respond"] == ["any", "loop"], "async def + public spawner method"
    assert r["start"] == ["any"], "public method of a thread-spawning class"
    assert r["__init__"] == [], "construction happens-before publication"


def test_roles_propagate_through_calls_callbacks_and_properties():
    src = """
import threading

class Front:
    def __init__(self, engine):
        self.engine = engine
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._pump)
        self._thread.start()

    @property
    def idle(self):
        return True

    def drain(self):
        return self.idle           # property read: role reaches idle

    def _on_token(self, tok):
        pass

    def _collect(self):
        pass

    def _pump(self):
        self._collect()                              # direct call
        self.engine.run(stream_cb=self._on_token)    # callback handoff
"""
    r = roles(src)
    assert "engine" in r["_collect"], "self.m() call propagates"
    assert "engine" in r["_on_token"], "callback argument propagates"
    assert "any" in r["idle"], "self.prop read propagates"
    # the Thread target handoff must NOT leak the caller's any-role into
    # the engine cone: _pump runs only on the spawned thread
    assert r["_pump"] == ["engine"]


def test_annotation_pins_and_overrides():
    src = """
import threading

class Front:
    def start(self):
        t = threading.Thread(target=self._pump)
        t.start()

    def _pump(self):
        self.report()

    def report(self):  # mdi-thread: any
        pass

    # mdi-thread: engine
    def helper(self):
        pass
"""
    r = roles(src)
    assert r["report"] == ["any"], "pinned: engine must not propagate in"
    assert r["helper"] == ["engine"], "annotation on the line above the def"


def test_unknown_annotation_role_is_itself_a_finding():
    src = """
def f():  # mdi-thread: gpu
    pass
"""
    fs = lint(src, "unguarded-shared-state")
    assert len(fs) == 1 and "unknown thread role" in fs[0].message


# ---------------------------------------------------------------------------
# unguarded-shared-state semantics
# ---------------------------------------------------------------------------

SPAWNER = """
import threading

class Front:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.limit = 8
        self.items = []

    def start(self):
        t = threading.Thread(target=self._pump)
        t.start()

    def submit(self, x):
        {submit_body}

    def _pump(self):
        {pump_body}
"""


def spawner(submit_body="pass", pump_body="pass"):
    return SPAWNER.format(submit_body=submit_body, pump_body=pump_body)


def test_cross_role_unguarded_write_fires_once_per_attribute():
    src = spawner("self.items.append(x)",
                  "batch = self.items\n        self.items = []")
    fs = lint(src, "unguarded-shared-state")
    assert len(fs) == 1, "one finding per (class, attr), not per access"
    assert "self.items" in fs[0].message
    # anchored at the first unguarded access OUTSIDE __init__ (the
    # construction write is exempt: publication is the happens-before)
    assert fs[0].line_text.strip() == "self.items.append(x)"
    assert "_pump" in fs[0].message, "the other racing site is named"


def test_guarded_accesses_are_clean_and_with_scoping_is_lexical():
    guarded = spawner(
        "with self._lock:\n            self.items.append(x)",
        "with self._lock:\n            self.items.clear()",
    )
    assert lint(guarded, "unguarded-shared-state") == []
    # the with-block must cover the access lexically; a lock taken and
    # RELEASED earlier in the function is not a guard
    released = spawner(
        "with self._lock:\n            pass\n        self.items.append(x)",
        "with self._lock:\n            self.items.clear()",
    )
    assert len(lint(released, "unguarded-shared-state")) == 1


def test_single_role_and_read_only_attrs_are_exempt():
    # written + read on the engine role only: no cross-role sharing
    engine_only = spawner("pass", "self.items.append(1)\n        self.items.clear()")
    assert lint(engine_only, "unguarded-shared-state") == []
    # read from both roles but written only in __init__: config constant
    reads = spawner("n = self.limit", "n = self.limit")
    assert lint(reads, "unguarded-shared-state") == []


def test_sync_primitives_are_exempt_by_type():
    # an Event is MEANT to be shared; flagging it would force absurd locks
    src = spawner("self._stop.set()", "self._stop.wait()")
    assert lint(src, "unguarded-shared-state") == []


@pytest.mark.parametrize("write", [
    "self.items = [x]",          # rebind
    "self.items += [x]",         # aug-assign RMW
    "self.items.append(x)",      # in-place mutator
])
def test_every_write_shape_is_detected(write):
    src = spawner(write, "n = len(self.items)")
    assert len(lint(src, "unguarded-shared-state")) == 1, write


def test_suppression_with_justification_silences_the_attr():
    src = spawner(
        "with self._lock:\n            self.items.append(x)",
        "# mdi-lint: disable-next-line=unguarded-shared-state -- GIL-atomic len\n"
        "        n = len(self.items)",
    )
    assert lint(src, "unguarded-shared-state") == []


# ---------------------------------------------------------------------------
# blocking-in-event-loop semantics
# ---------------------------------------------------------------------------


def test_blocking_shapes_inside_async_def():
    src = """
import time
import subprocess

class S:
    async def handle(self, lock, q):
        time.sleep(0.5)
        lock.acquire()
        subprocess.run(["ls"])
"""
    fs = lint(src, "blocking-in-event-loop")
    assert len(fs) == 3


def test_awaited_and_off_loop_shapes_are_clean():
    src = """
import asyncio

class S:
    async def handle(self, loop, handle, conn):
        await asyncio.sleep(0.1)
        await handle.done_event.wait()
        await loop.run_in_executor(None, handle.done.wait)
        await loop.run_in_executor(None, lambda: conn.lock.acquire())
        parts = ", ".join(["a", "b"])   # str.join is not Thread.join
        return parts
"""
    assert lint(src, "blocking-in-event-loop") == []


def test_nested_sync_def_inside_async_is_not_the_loop():
    src = """
class S:
    async def stream(self, loop, q):
        def sink(event):       # runs on the ENGINE thread
            q.lock.acquire()   # fine there
            q.lock.release()
        return sink
"""
    assert lint(src, "blocking-in-event-loop") == []


def test_thread_join_in_async_def_is_flagged():
    src = """
class S:
    async def shutdown(self):
        self.engine_thread.join()
"""
    fs = lint(src, "blocking-in-event-loop")
    assert len(fs) == 1 and ".join()" in fs[0].message


# ---------------------------------------------------------------------------
# lock-order-inversion semantics
# ---------------------------------------------------------------------------


def test_single_statement_with_items_count_as_an_order():
    src = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def f():
    with a_lock, b_lock:
        pass

def g():
    with b_lock:
        with a_lock:
            pass
"""
    fs = lint(src, "lock-order-inversion")
    assert fs, "with a, b acquires left-to-right"
    assert all(f.rule == "lock-order-inversion" for f in fs)


def test_consistent_order_and_single_lock_are_clean():
    src = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def f():
    with a_lock:
        with b_lock:
            pass

def g():
    with a_lock, b_lock:
        pass

def h():
    with a_lock:
        pass
"""
    assert lint(src, "lock-order-inversion") == []


# ---------------------------------------------------------------------------
# loop-call-from-wrong-thread semantics
# ---------------------------------------------------------------------------


def test_loop_role_and_roleless_functions_are_clean():
    src = """
class S:
    async def handle(self, loop):
        loop.create_task(self.work())   # on the loop: fine

    async def work(self):
        pass

def helper(loop):
    loop.call_soon(print)   # no inferred role: can't judge, stay silent
"""
    assert lint(src, "loop-call-from-wrong-thread") == []


def test_annotated_engine_function_is_flagged():
    src = """
class Bridge:
    def push(self, loop, event):  # mdi-thread: engine
        loop.call_soon(print, event)
"""
    fs = lint(src, "loop-call-from-wrong-thread")
    assert len(fs) == 1 and "call_soon_threadsafe" in fs[0].message


# ---------------------------------------------------------------------------
# registry / CLI / baseline integration
# ---------------------------------------------------------------------------


def test_thread_rules_are_registered_and_listed(capsys):
    from mdi_llm_tpu.analysis import RULES

    assert set(THREAD_RULES) <= set(RULES)
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in THREAD_RULES:
        assert rule in out


def test_baseline_round_trip_grandfathers_a_thread_finding(tmp_path):
    bad = spawner("self.items.append(x)", "self.items.clear()")
    p = tmp_path / "mod.py"
    p.write_text(bad)
    base = tmp_path / "base.json"
    # first run: finding reported, exit 1; --update-baseline records it
    assert lint_main([str(p), "--baseline", str(base),
                      "--select", "unguarded-shared-state"]) == 1
    assert lint_main([str(p), "--baseline", str(base),
                      "--select", "unguarded-shared-state",
                      "--update-baseline"]) == 0
    keys = Baseline.load(base).counts
    assert any(k.startswith("unguarded-shared-state::") for k in keys)
    # grandfathered: clean now, and still reported with --no-baseline
    assert lint_main([str(p), "--baseline", str(base),
                      "--select", "unguarded-shared-state"]) == 0
    assert lint_main([str(p), "--no-baseline",
                      "--select", "unguarded-shared-state"]) == 1


def test_repo_is_clean_under_thread_rules_alone():
    """The concurrency self-check in isolation (the all-rules gate lives
    in test_lint.py): zero unsuppressed findings across the package."""
    from mdi_llm_tpu.analysis import lint_paths

    repo = __import__("pathlib").Path(__file__).resolve().parents[1]
    findings, errors = lint_paths([repo / "mdi_llm_tpu"], root=repo,
                                  select=list(THREAD_RULES))
    assert errors == []
    assert findings == [], [f"{f.path}:{f.line} {f.rule}" for f in findings]
