"""Prompt styles, dispatch rules, persistence, FILE: loader; tokenizer
round-trip with a generated tokenizer.json fixture."""

import json

import numpy as np
import pytest

from mdi_llm_tpu.utils.prompts import (
    PromptStyle,
    get_user_prompt,
    has_prompt_style,
    load_prompt_style,
    save_prompt_style,
    style_for_model,
    styles,
)
from mdi_llm_tpu.utils.tokenizer import Tokenizer


def test_style_dispatch_rules():
    assert style_for_model("Llama-3-8B-Instruct").name == "llama3"
    assert style_for_model("Llama-2-7b-chat-hf").name == "llama2"
    assert style_for_model("tiny-llama-1.1b-chat").name == "tinyllama"
    assert style_for_model("Mistral-7B-Instruct-v0.2").name == "codellama"
    assert style_for_model("falcon-7b-instruct").name == "falcon"
    assert style_for_model("NanoLlama").name == "no-prompt"
    assert style_for_model("gpt2-medium").name == "default"
    assert style_for_model("Gemma-2b-it").name == "gemma"


def test_templates_wrap_prompt():
    for name, st in styles.items():
        out = st.apply("HELLO_WORLD")
        if name == "no-prompt":
            assert out == "\n"
        else:
            assert "HELLO_WORLD" in out, name


def test_llama3_template_markers():
    out = styles["llama3"].apply("hi")
    assert out.startswith("<|begin_of_text|>")
    assert "<|start_header_id|>assistant<|end_header_id|>" in out


def test_persistence(tmp_path):
    save_prompt_style("llama3", tmp_path)
    assert has_prompt_style(tmp_path)
    assert load_prompt_style(tmp_path).name == "llama3"
    with pytest.raises(ValueError):
        save_prompt_style("nope", tmp_path)


def test_get_user_prompt_file(tmp_path):
    f = tmp_path / "prompts.txt"
    f.write_text("first prompt\n\nsecond prompt\n\n\nthird prompt\n")
    got = get_user_prompt(f"FILE:{f}", 2)
    assert got == ["first prompt", "second prompt"]
    got = get_user_prompt(f"FILE:{f}", 5)
    assert got == ["first prompt", "second prompt", "third prompt", "first prompt", "second prompt"]
    got = get_user_prompt("plain", 3)
    assert got == ["plain"] * 3


@pytest.fixture(scope="module")
def hf_tok_dir(tmp_path_factory):
    """Build a tiny word-level tokenizer.json + config files."""
    from tokenizers import Tokenizer as HFTok
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    d = tmp_path_factory.mktemp("tok")
    vocab = {"<s>": 0, "</s>": 1, "hello": 2, "world": 3, "the": 4, "cat": 5}
    t = HFTok(WordLevel(vocab, unk_token="</s>"))
    t.pre_tokenizer = Whitespace()
    t.save(str(d / "tokenizer.json"))
    (d / "tokenizer_config.json").write_text(
        json.dumps({"bos_token": "<s>", "eos_token": "</s>", "add_bos_token": True})
    )
    return d


def test_tokenizer_roundtrip(hf_tok_dir):
    tok = Tokenizer(hf_tok_dir)
    assert tok.backend == "huggingface"
    assert tok.bos_id == 0 and tok.eos_id == 1
    ids = tok.encode("hello world the cat")
    assert ids.dtype == np.int32
    assert ids.tolist() == [0, 2, 3, 4, 5]  # bos prepended
    assert tok.encode("hello", bos=False).tolist() == [2]
    assert tok.encode("hello", bos=False, eos=True).tolist() == [2, 1]
    assert tok.encode("hello world", bos=False, max_length=1).tolist() == [2]
    assert "hello" in tok.decode(np.array([2, 3]))


def test_tokenizer_stop_sequences(hf_tok_dir):
    tok = Tokenizer(hf_tok_dir)
    st = styles["default"]
    seqs = st.stop_tokens(tok)
    assert seqs == ([1],)


def test_tokenizer_missing_dir():
    with pytest.raises(NotADirectoryError):
        Tokenizer("/nonexistent/path")
