"""Sequence-parallel inference (ring-attention prefill + distributed
flash-decode) golden-token tests on the virtual 8-device CPU mesh.

The decisive invariants: (1) sp generation reproduces single-device greedy
generation token-for-token; (2) the per-device KV cache really is a 1/P
shard — context beyond one device's cache budget works (SURVEY.md §5.7,
new design territory vs the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.sp_inference import SPGenerator
from tests.test_model import tiny_config, CONFIG_VARIANTS


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=256, n_layer=3)
    params = init_params(cfg, jax.random.PRNGKey(5))
    return cfg, params


PROMPTS = [[3, 1, 4, 1, 5, 9, 2], [2, 7]]


@pytest.mark.smoke
@pytest.mark.parametrize("n_devices", [2, 4])
def test_sp_generation_matches_single_device(model, n_devices, devices):
    cfg, params = model
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate(PROMPTS, 12, temperature=0.0)
    sp = SPGenerator(
        cfg, params, devices=devices[:n_devices], cache_dtype=jnp.float32
    )
    got, stats = sp.generate(PROMPTS, 12, temperature=0.0)
    assert got == want
    assert stats.tokens_generated == 24


def test_sp_stop_sequences(model, devices):
    cfg, params = model
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    free, _ = single.generate(PROMPTS[:1], 12, temperature=0.0)
    stop = [free[0][len(PROMPTS[0]) + 4 : len(PROMPTS[0]) + 6]]
    want, _ = single.generate(PROMPTS[:1], 12, temperature=0.0, stop_sequences=stop)
    sp = SPGenerator(cfg, params, devices=devices[:2], cache_dtype=jnp.float32)
    got, _ = sp.generate(PROMPTS[:1], 12, temperature=0.0, stop_sequences=stop)
    assert got == want


def test_sp_long_context_beyond_one_shard(model, devices):
    """The whole sequence exceeds any single device's cache shard: per-device
    cache C < prompt+generated, so no device could have held the context
    alone at this budget."""
    cfg, params = model
    n_dev, new = 4, 16
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 50, 120).tolist()
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate([prompt], new, temperature=0.0)
    sp = SPGenerator(cfg, params, devices=devices[:n_dev], cache_dtype=jnp.float32)
    got, _ = sp.generate([prompt], new, temperature=0.0)
    assert got == want
    # per-device shard budget really is ~1/P of the sequence
    from mdi_llm_tpu.generation import _bucket

    Tl = -(-_bucket(len(prompt)) // n_dev)
    C = Tl + -(-new // n_dev)
    assert C < len(prompt) + new


def test_sp_prompt_shorter_than_mesh(model, devices):
    """Prompt with fewer tokens than sp devices: most devices hold ONLY
    sentinel (empty) cache slots after prefill — masking must keep them
    invisible and the last-token gather must find the right owner."""
    cfg, params = model
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate([[7, 3, 2]], 10, temperature=0.0)
    sp = SPGenerator(cfg, params, devices=devices, cache_dtype=jnp.float32)  # 8-way
    got, _ = sp.generate([[7, 3, 2]], 10, temperature=0.0)
    assert got == want


@pytest.mark.parametrize("new", [16, 17, 15])
def test_sp_cache_full_boundary(model, new, devices):
    """Round-robin append up to the very last local cache slot: max_new set
    so the final written slot is exactly C-1 (new % P == 0), one past a row
    boundary (new % P == 1), and one short of it (new % P == P-1)."""
    cfg, params = model
    n_dev = 4
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate(PROMPTS[:1], new, temperature=0.0)
    sp = SPGenerator(cfg, params, devices=devices[:n_dev], cache_dtype=jnp.float32)
    got, _ = sp.generate(PROMPTS[:1], new, temperature=0.0)
    assert got == want
    # direct observable (VERDICT r4 #6): the slot→position map must equal
    # the round-robin owner math exactly — prefill slot j<Tl on device d
    # holds gpos=d*Tl+j (or sentinel past the prompt); decode slot j>=Tl on
    # device d holds position len + (j-Tl)*P + d for every step that ran
    # (the final sampled token is never appended).  A regression in the
    # owner/row arithmetic fails HERE, independent of logit tolerance.
    from mdi_llm_tpu.parallel.sp_inference import POS_SENTINEL
    from mdi_llm_tpu.generation import _bucket

    kp = sp.slot_owner_map()
    L = len(PROMPTS[0])
    Tl = -(-_bucket(L) // n_dev)
    C = Tl + -(-new // n_dev)
    n_written = new - 1  # positions L .. L+new-2
    want_map = np.full((n_dev, C), int(POS_SENTINEL), np.int64)
    for d in range(n_dev):
        for j in range(C):
            if j < Tl:
                gpos = d * Tl + j
                if gpos < L:
                    want_map[d, j] = gpos
            else:
                s = (j - Tl) * n_dev + d
                if s < n_written:
                    want_map[d, j] = L + s
    np.testing.assert_array_equal(kp[0].astype(np.int64), want_map)


def test_sp_mixed_length_batch(model, devices):
    """Samples whose last prompt tokens live on different sp devices (the
    per-sample owner gather in prefill) generate in one batch correctly."""
    cfg, params = model
    prompts = [[5] * 2, [6] * 19, [7] * 11]
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate(prompts, 8, temperature=0.0)
    sp = SPGenerator(cfg, params, devices=devices[:4], cache_dtype=jnp.float32)
    got, _ = sp.generate(prompts, 8, temperature=0.0)
    assert got == want


def test_sp_prefill_use_flash_traces_kernel(model, devices):
    """SPGenerator(use_flash=True) routes ring prefill through the Pallas
    kernel once the LOCAL chunk clears flash_min_len (trace-level check;
    execution needs a TPU); short chunks stay on the XLA path; the default
    is off (explicit opt-in until validated on hardware)."""
    cfg, params = model

    def trace(sp, Tl):
        B, C = 1, Tl + 4
        prefill = sp._get_prefill(B, Tl, C, 0.0, None, None)
        toks = jnp.zeros((B, Tl * 2), jnp.int32)
        kv = sp._init_kv(B, C)
        return str(jax.make_jaxpr(
            lambda p, r, t, l, kv_, k_: prefill(p, r, t, l, kv_, k_)
        )(sp.params, sp.rope, toks, jnp.asarray([3], jnp.int32), kv,
          jax.random.PRNGKey(0)))

    sp = SPGenerator(
        cfg, params, devices=devices[:2], cache_dtype=jnp.float32,
        use_flash="force", flash_min_len=8,
    )
    assert "pallas_call" in trace(sp, 8)
    # same engine, chunk below the gate → XLA path
    assert "pallas_call" not in trace(sp, 4)
    # default stays off (opt-in until a real-TPU run validates the path)
    assert SPGenerator(
        cfg, params, devices=devices[:2], cache_dtype=jnp.float32
    ).use_flash is False
    # plain True soft-gates on the backend: no TPU here → warn + fall back
    # instead of dying in Pallas lowering (ADVICE r4)
    assert SPGenerator(
        cfg, params, devices=devices[:2], cache_dtype=jnp.float32,
        use_flash=True,
    ).use_flash is False


def test_sp_gqa_variant(devices):
    cfg = tiny_config(block_size=128, n_layer=3, **CONFIG_VARIANTS["gqa"])
    params = init_params(cfg, jax.random.PRNGKey(6))
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = single.generate([[4, 8, 15, 16, 23, 42]], 10, temperature=0.0)
    sp = SPGenerator(cfg, params, devices=devices[:4], cache_dtype=jnp.float32)
    got, _ = sp.generate([[4, 8, 15, 16, 23, 42]], 10, temperature=0.0)
    assert got == want


def test_sp_quantized_decode_parity(model, devices):
    """Quantized weights over an sp mesh (VERDICT r4 missing #4: int8
    weights + sequence-sharded KV is the realistic long-context 8B serving
    shape) reproduce single-device quantized greedy decode."""
    cfg, params = model
    single = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int8")
    want, _ = single.generate(PROMPTS, 10, temperature=0.0)
    sp = SPGenerator(
        cfg, params, devices=devices[:4], cache_dtype=jnp.float32,
        quantize="int8",
    )
    got, _ = sp.generate(PROMPTS, 10, temperature=0.0)
    assert got == want
    # unknown mode still rejected
    with pytest.raises(ValueError, match="quantize"):
        SPGenerator(cfg, params, devices=devices[:2], quantize="int3")


def test_sp_generate_chat_streams_same_tokens(model, devices):
    """SPGenerator.generate_chat yields exactly the greedy generate() tail
    (same contract as Generator.generate_chat), including stop filtering."""
    cfg, params = model
    prompt = [3, 1, 4, 1, 5]
    sp = SPGenerator(
        cfg, params, devices=devices[:4], cache_dtype=jnp.float32,
        decode_chunk=3,  # force several chunked dispatches mid-stream
    )
    want, _ = sp.generate([prompt], 11, temperature=0.0)
    got = list(sp.generate_chat(prompt, 11, temperature=0.0))
    assert got == want[0][len(prompt):]

    # stop sequences: the stream must cut exactly where generate() cuts
    stop = [want[0][len(prompt) + 3 : len(prompt) + 5]]
    want_stop, _ = sp.generate([prompt], 11, temperature=0.0, stop_sequences=stop)
    got_stop = list(sp.generate_chat(prompt, 11, temperature=0.0, stop_sequences=stop))
    assert got_stop == want_stop[0][len(prompt):]


# ---------------------------------------------------------------------------
# SPChatSession: cross-turn sequence-sharded KV reuse
# ---------------------------------------------------------------------------


def _single_baseline(cfg, params, history, turn, n, stop=()):
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    return list(gen.generate_chat(history + turn, n, temperature=0.0,
                                  stop_sequences=stop))


@pytest.mark.parametrize("n_devices", [2, 4])
def test_sp_chat_session_matches_single_device(model, n_devices, devices):
    """Turn appends through the round-robin decode path must keep every
    turn token-identical to single-device full-history re-prefill."""
    cfg, params = model
    sp = SPGenerator(
        cfg, params, devices=devices[:n_devices], cache_dtype=jnp.float32
    )
    sess = sp.chat_session()
    history: list[int] = []
    for turn in ([3, 1, 4, 1, 5], [9, 2], [6, 5, 3, 5]):
        want = _single_baseline(cfg, params, history, turn, 8)
        got = list(sess.send(turn, 8, temperature=0.0))
        assert got == want, f"turn {turn} diverged"
        history += turn + want
        assert sess.history == history


def test_sp_chat_session_stop_rollback_clears_kp(model, devices):
    """A stop-trimmed reply must clear the rolled-back slots' kp stamps —
    under kp-masked sp attention a stale stamp would be attendable — and
    later turns must stay token-identical."""
    cfg, params = model
    free = _single_baseline(cfg, params, [], [9, 9, 1], 10)
    stop = [[free[3]]]
    sp = SPGenerator(cfg, params, devices=devices[:2], cache_dtype=jnp.float32)
    sess = sp.chat_session()
    history: list[int] = []
    for turn, st in (([9, 9, 1], stop), ([4, 2, 8], ()), ([1, 3], stop)):
        want = _single_baseline(cfg, params, history, turn, 10, st)
        got = list(sess.send(turn, 10, temperature=0.0, stop_sequences=st))
        assert got == want
        history += turn + want
        assert sess.history == history


def test_sp_chat_session_window_rebuild(model, devices):
    """Outgrowing max_seq_length must rebuild via ring prefill over the
    slid window and keep matching a stateless run over that window."""
    cfg, params = model
    sp = SPGenerator(
        cfg, params, devices=devices[:2], max_seq_length=64,
        cache_dtype=jnp.float32,
    )
    sess = sp.chat_session()
    for i in range(5):  # 5 x (4 + 8) tokens overflows 64
        turn = [2 + i, 3 + i, 5 + i, 7 + i]
        got = list(sess.send(turn, 8, temperature=0.0))
        # authoritative check: session history must match a stateless
        # single-device run over the exact window the session kept (the
        # window always ends with the full turn: its size cap-max_new-1
        # exceeds any turn here)
        prompt = sess.history[: len(sess.history) - len(got)]
        assert prompt[-len(turn):] == turn
        want = _single_baseline(cfg, params, prompt[: -len(turn)], turn, 8)
        assert got == want, f"turn {i} diverged"
    assert len(sess.history) <= 64


def test_sp_chat_session_rollback(model, devices):
    cfg, params = model
    sp = SPGenerator(cfg, params, devices=devices[:2], cache_dtype=jnp.float32)
    sess = sp.chat_session()
    _ = list(sess.send([5, 6, 7], 6, temperature=0.0))
    pre = sess.history[:]
    it = sess.send([11, 2], 8, temperature=0.0)
    partial = [next(it), next(it)]
    sess.rollback(pre + [11, 2] + partial)
    want = _single_baseline(cfg, params, pre + [11, 2] + partial, [4, 4], 6)
    got = list(sess.send([4, 4], 6, temperature=0.0))
    assert got == want


@pytest.mark.parametrize("n_devices", [2, 4])
def test_sp_chat_session_speculative_matches_plain(model, n_devices, devices):
    """Speculative sp chat must be token-identical to the plain sp session
    (greedy), across turns so drafting draws on earlier turns."""
    cfg, params = model
    plain = SPGenerator(
        cfg, params, devices=devices[:n_devices], cache_dtype=jnp.float32
    ).chat_session()
    spec = SPGenerator(
        cfg, params, devices=devices[:n_devices], cache_dtype=jnp.float32
    ).chat_session()
    for turn in ([5, 6, 7, 5, 6], [5, 6, 7, 5], [9, 1, 5, 6]):
        want = list(plain.send(turn, 9, temperature=0.0))
        got = list(spec.send(turn, 9, temperature=0.0, speculative=3))
        assert got == want, f"turn {turn} diverged"
        assert len(got) <= 9
        assert spec.history == plain.history


def test_sp_chat_session_speculative_stop_rollback(model, devices):
    """A speculative burst trimmed by a stop marker must clear both the
    rejected-draft slots and the stop-trimmed slots, keeping later turns
    identical to the plain session."""
    cfg, params = model
    free = list(
        SPGenerator(cfg, params, devices=devices[:2], cache_dtype=jnp.float32)
        .chat_session().send([9, 9, 1], 10, temperature=0.0)
    )
    stop = [[free[3]]]
    plain = SPGenerator(
        cfg, params, devices=devices[:2], cache_dtype=jnp.float32
    ).chat_session()
    spec = SPGenerator(
        cfg, params, devices=devices[:2], cache_dtype=jnp.float32
    ).chat_session()
    for turn, st in (([9, 9, 1], stop), ([4, 2, 8], ())):
        want = list(plain.send(turn, 10, temperature=0.0, stop_sequences=st))
        got = list(spec.send(turn, 10, temperature=0.0, stop_sequences=st,
                             speculative=4))
        assert got == want
        assert spec.history == plain.history
