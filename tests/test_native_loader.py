"""Native C++ data loader: build, correctness (y = shift(x)), determinism,
agreement with the file contents."""

import numpy as np
import pytest

from mdi_llm_tpu.utils import native_loader


@pytest.fixture(scope="module")
def bin_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("bins")
    data = (np.arange(5000) * 7 % 1000).astype(np.uint16)
    p = d / "train.bin"
    data.tofile(p)
    return p, data


@pytest.fixture(scope="module")
def native_ok():
    if not native_loader.is_available():
        pytest.skip("no C++ toolchain / native build failed")


def test_open_len_read(bin_file, native_ok):
    p, data = bin_file
    ds = native_loader.NativeBinDataset(p)
    assert len(ds) == len(data)
    got = ds.read(100, 50)
    np.testing.assert_array_equal(got, data[100:150].astype(np.int32))
    ds.close()


def test_batch_windows_are_real_slices(bin_file, native_ok):
    p, data = bin_file
    ds = native_loader.NativeBinDataset(p, seed=42)
    x, y = ds.get_batch(8, 32)
    assert x.shape == (8, 32) and x.dtype == np.int32
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    # every row must be a contiguous slice of the corpus
    for row in x:
        start = row[0]
        idxs = np.where(data == start)[0]
        assert any(
            np.array_equal(data[i : i + 32].astype(np.int32), row)
            for i in idxs
            if i + 32 <= len(data)
        )


def test_deterministic_given_seed(bin_file, native_ok):
    p, _ = bin_file
    a = native_loader.NativeBinDataset(p, seed=7)
    b = native_loader.NativeBinDataset(p, seed=7)
    for _ in range(3):
        xa, ya = a.get_batch(4, 16)
        xb, yb = b.get_batch(4, 16)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    # different seed → different sample
    c = native_loader.NativeBinDataset(p, seed=8)
    xc, _ = c.get_batch(4, 16)
    assert not np.array_equal(xa, xc)


def test_missing_file_raises(native_ok, tmp_path):
    with pytest.raises(FileNotFoundError):
        native_loader.NativeBinDataset(tmp_path / "nope.bin")


def test_trainer_accepts_native_dataset(bin_file, native_ok):
    from mdi_llm_tpu.training import Trainer
    from tests.test_model import tiny_config
    from tests.test_training import small_tc

    p, _ = bin_file
    ds = native_loader.NativeBinDataset(p, seed=1)
    tr = Trainer(tiny_config(block_size=16, n_layer=2), small_tc(max_iters=2, grad_acc_steps=1))
    result = tr.fit(ds, max_iters=2)
    assert result["iter_num"] == 2
