"""mdi-audit: static plan auditing — fixture pairs (one bad plan per checker
family, each producing exactly one finding with the expected code, plus a
good-plan zero-findings pass), registry-wide spec-coverage and self-check
properties, memory-estimate sanity against live arrays, the no-JAX-backend
guarantee (backend trip-wire), the CLI surface, and the mesh/partition
validation satellites.  This file is the tier-1 CI gate mdi-audit ships as,
mirroring tests/test_lint.py.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from mdi_llm_tpu.analysis.audit import (
    AUDIT_RULES,
    audit_detail,
    audit_plan,
    main as audit_main,
    preflight,
)
from mdi_llm_tpu.analysis.core import Baseline
from mdi_llm_tpu.analysis.plan import (
    MeshSpec,
    PlanSpec,
    abstract_params,
    iter_leaves,
    ring_permutation,
    tree_bytes,
)
from mdi_llm_tpu.config import Config, ServingConfig, dtype_bytes, name_to_config

REPO = Path(__file__).resolve().parents[1]


def tiny():
    return Config.from_name("pythia-14m")


def codes(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# fixture pairs: one bad plan per checker, exactly one expected finding
# ---------------------------------------------------------------------------


def test_good_default_plan_is_clean():
    report = audit_plan(PlanSpec(cfg=Config.from_name("tiny-llama-1.1b")))
    assert report.findings == []


def test_good_pipeline_tp_serving_plan_is_clean():
    cfg = Config.from_name("tiny-llama-1.1b")
    r = preflight(cfg, n_stages=4, tp=2, n_samples=8, samples_per_slot=2,
                  seq_len=2048, hbm_gb=16)
    assert r.findings == []
    assert r.breakdown["stage_layers"] == [4, 6, 6, 6]
    assert r.breakdown["bubble_fraction"] == 0.0
    r2 = audit_plan(PlanSpec(
        cfg=cfg, serving=ServingConfig(max_batch=8), hbm_gb=16,
    ))
    assert r2.findings == []


def test_bad_plan_unknown_mesh_axis():
    cfg = tiny()
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"data": 8}), tp_axis="tp",
    ))
    assert codes(r) == ["unknown-mesh-axis"]
    assert "silently replicate" in r.findings[0].message


def test_bad_mesh_axis_size_is_an_error_not_green(capsys):
    """A 0/negative axis size must not audit clean (every divisibility
    check is vacuous at size <= 1 — the runtime's make_mesh rejects it)."""
    for mesh in ("tp=0", "tp=-2", "pipe=4,tp=-1"):
        rc = audit_main(["--model", "tiny-llama-1.1b", "--mesh", mesh])
        out = capsys.readouterr().out
        assert rc == 1 and "bad-mesh-axis" in out, (mesh, out)
    r = audit_plan(PlanSpec(
        cfg=tiny(), mesh=MeshSpec.from_dict({"tp": 0}), tp_axis="tp",
    ))
    assert "bad-mesh-axis" in codes(r)


def test_bad_plan_non_divisible_sharded_dim():
    cfg = Config.from_name("tiny-llama-1.1b")  # n_head=32, G=4, I=5632
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"tp": 3}), tp_axis="tp",
    ))
    assert codes(r) == ["indivisible-dim"]
    assert "'tp' (size 3)" in r.findings[0].message
    # semantic head-count divisibility fires even when every fused leaf dim
    # happens to divide (G=4 cannot split 8 ways; qkv rows 2560 % 8 == 0)
    r = audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"tp": 8}), tp_axis="tp",
    ))
    assert codes(r) == ["indivisible-dim"]
    assert "n_query_groups=4" in r.findings[0].message


def test_bad_plan_over_budget_kv_pool():
    cfg = Config.from_name("tiny-llama-1.1b")
    r = audit_plan(PlanSpec(
        cfg=cfg, serving=ServingConfig(block_size=16, max_batch=64),
        hbm_gb=0.5,
    ))
    assert codes(r) == ["hbm-over-budget"]
    assert "exceeds the 0.5 GiB budget" in r.findings[0].message
    assert "max_pool_blocks" in r.breakdown["fits"]


def test_bad_plan_unmatched_ring_permute():
    cfg = tiny()  # 6 layers: a 4-stage split is valid
    perm = tuple((i, (i + 1) % 4) for i in range(3))  # stage 3 never sends
    r = audit_plan(PlanSpec(cfg=cfg, n_stages=4, n_samples=8, ring_perm=perm))
    assert codes(r) == ["unmatched-permute"]
    msg = r.findings[0].message
    assert "rank 3 never sends" in msg and "rank 0 never receives" in msg


# ---------------------------------------------------------------------------
# additional checker coverage
# ---------------------------------------------------------------------------


def test_broken_ring_two_cycles():
    # bijection, but two disjoint 2-cycles: stage 0's orbit never reaches 2/3
    perm = ((0, 1), (1, 0), (2, 3), (3, 2))
    r = audit_plan(PlanSpec(cfg=tiny(), n_stages=4, n_samples=8, ring_perm=perm))
    assert codes(r) == ["broken-ring"]


def test_schedule_divergence_across_ranks():
    ring = [("ppermute", "pipe", ring_permutation(2))] * 4
    diverged = list(ring)
    diverged[2] = ("psum", "pipe", None)
    r = audit_plan(PlanSpec(
        cfg=tiny(), n_stages=2, n_samples=4, rank_programs=[ring, diverged],
    ))
    assert codes(r) == ["schedule-divergence"]
    assert "step 2" in r.findings[0].message


def test_pipeline_underfill_is_a_warning_with_bubble_fraction():
    r = preflight(tiny(), n_stages=4, n_samples=2)
    assert codes(r) == ["pipeline-underfill"]
    assert r.errors == [] and len(r.warnings) == 1
    assert r.breakdown["bubble_fraction"] == 0.5
    assert "50%" in r.warnings[0].message


def test_bad_stage_split_rejected():
    r = preflight(tiny(), n_stages=7, n_samples=8)  # 6 layers over 7 stages
    assert codes(r) == ["bad-stage-split"]
    assert "n_stages <= 6" in r.findings[0].message


def test_duplicate_axis_use_rejected(monkeypatch):
    from jax.sharding import PartitionSpec as P

    import mdi_llm_tpu.parallel.sharding as sharding

    real = sharding.param_specs

    def doubled(cfg, tp_axis="tp", ep_axis=None):
        specs = real(cfg, tp_axis, ep_axis)
        specs["blocks"]["attn"]["qkv"]["weight"] = P(None, tp_axis, tp_axis)
        return specs

    monkeypatch.setattr(sharding, "param_specs", doubled)
    r = audit_plan(PlanSpec(
        cfg=tiny(), mesh=MeshSpec.from_dict({"tp": 2}), tp_axis="tp",
    ))
    assert "duplicate-axis" in codes(r)


def test_missing_spec_is_an_error_not_silent_replication(monkeypatch):
    import mdi_llm_tpu.parallel.sharding as sharding

    real = sharding.param_specs

    def dropped(cfg, tp_axis="tp", ep_axis=None):
        specs = real(cfg, tp_axis, ep_axis)
        del specs["ln_f"]
        return specs

    monkeypatch.setattr(sharding, "param_specs", dropped)
    r = audit_plan(PlanSpec(cfg=tiny()))
    assert set(codes(r)) == {"missing-spec"}
    assert any("ln_f.weight" in f.message for f in r.findings)


def test_bad_serving_config_rejected():
    r = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(block_size=16, max_blocks=1),
    ))
    assert codes(r) == ["bad-serving-config"]
    # a zero/negative block width must yield the finding, not a crash in
    # the memory checker's pool_bytes call
    r = audit_plan(PlanSpec(cfg=tiny(), serving=ServingConfig(block_size=0)))
    assert "bad-serving-config" in codes(r)
    assert r.breakdown["per_device"]["kv_bytes"] == 0


def test_bad_server_config_rejected():
    """The open-system sizing check (bad-server-config): an admission
    queue that rejects everything, and a queue-backed server whose pool
    cannot hold every slot's chunk-reservation headroom at once (the
    saturated steady state would be preemption thrash).  Replay configs
    (admission_queue=None) never trip it."""
    # queue bound rejects every arrival
    r = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(block_size=16, admission_queue=0),
    ))
    assert codes(r) == ["bad-server-config"]
    assert "rejects every arrival" in r.findings[0].message
    # saturated-slots headroom: per-slot headroom for decode_chunk=16,
    # double_buffer, block 4 is 9 blocks; 8 slots need 72, a 40-block
    # pool holds all slots' FIRST writes (one-slot replay bound passes:
    # 39 usable >= 9+1) but not the saturated reservation demand
    sv_open = ServingConfig(block_size=4, decode_chunk=16, max_batch=8,
                            max_blocks=40, admission_queue=32)
    r = audit_plan(PlanSpec(cfg=tiny(), serving=sv_open))
    assert codes(r) == ["bad-server-config"]
    assert "preemption thrash" in r.findings[0].message
    # the SAME pool with no admission queue is the replay config mdi-serve
    # runs — it must stay clean (one-slot headroom suffices there)
    sv_replay = ServingConfig(block_size=4, decode_chunk=16, max_batch=8,
                              max_blocks=40)
    assert codes(audit_plan(PlanSpec(cfg=tiny(), serving=sv_replay))) == []
    # a well-sized open config is clean and the breakdown carries the bound
    sv_ok = ServingConfig(block_size=4, decode_chunk=16, max_batch=8,
                          admission_queue=32)
    r = audit_plan(PlanSpec(cfg=tiny(), serving=sv_ok))
    assert codes(r) == []
    assert r.breakdown["kv_pool"]["admission_queue"] == 32
    # resolved default: 4 x max_batch (shared with ServingFrontend)
    assert sv_replay.resolved_admission_queue() == 32
    assert sv_open.resolved_admission_queue() == 32


def test_serving_chunk_headroom_budgeted():
    """The pool-sizing audit accounts for K-step reservation headroom: a
    hand-sized max_blocks pool that cannot hold even one slot's chunk
    reservation is refused, while the full-coverage default stays clean
    and the kv_pool breakdown reports the headroom."""
    sv = ServingConfig(block_size=4, decode_chunk=16, double_buffer=True)
    # per-slot headroom: ceil(2*16/4)+1 = 9 blocks; a 5-block pool fails
    assert sv.reserve_headroom_blocks() == 9
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=4, decode_chunk=16, max_blocks=6),
    ))
    assert "bad-serving-config" in codes(r)
    assert any("chunk reservation" in f.message for f in r.findings)
    # full-coverage default: clean, and the breakdown carries the knobs
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=4, decode_chunk=16, spec_k=0),
    ))
    assert "bad-serving-config" not in codes(r)
    pool = r.breakdown["kv_pool"]
    assert pool["decode_chunk"] == 16 and pool["reserve_headroom_blocks"] == 9
    # temperature>0 + spec is legal now (rejection-sampled verify); only
    # the pinned exact-match path (spec_sampled=False) is refused
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=4, spec_k=4, temperature=0.8),
    ))
    assert "bad-serving-config" not in codes(r)
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=4, spec_k=4, temperature=0.8,
                              spec_sampled=False),
    ))
    assert "bad-serving-config" in codes(r)
    assert any("spec_sampled" in f.message for f in r.findings)


def test_bad_token_budget_rejected():
    """The unified-step token budget must exceed max_batch (decode lanes
    pack first; anything at or below starves prefill forever): exactly one
    finding with a suggested value, both for budget == max_batch and
    budget < max_batch."""
    r = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(max_batch=8, token_budget=8),
    ))
    assert codes(r) == ["bad-token-budget"]
    assert "token_budget >= 136" in r.findings[0].message  # 8 + 128
    r = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(max_batch=8, token_budget=3),
    ))
    assert codes(r) == ["bad-token-budget"]
    # the default (None -> max_batch + prefill_chunk) is always clean, and
    # the kv_pool breakdown reports the resolved budget
    r = audit_plan(PlanSpec(cfg=tiny(), serving=ServingConfig()))
    assert "bad-token-budget" not in codes(r)
    assert r.breakdown["kv_pool"]["token_budget"] == 8 + 128
    # an explicit healthy budget passes and is reported as-is
    r = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(max_batch=4, token_budget=64),
    ))
    assert "bad-token-budget" not in codes(r)
    assert r.breakdown["kv_pool"]["token_budget"] == 64


def test_kernel_tuning_breakdown_fields_always_present():
    """Every serving plan's kv_pool breakdown records the kernel route and
    tuning provenance — auto dispatch with no table resolves the
    conservative entry, tuned=False."""
    r = audit_plan(PlanSpec(cfg=tiny(), serving=ServingConfig(block_size=4)))
    pool = r.breakdown["kv_pool"]
    assert pool["kernel_variant"] == "auto"
    assert pool["tuned"] is False
    assert pool["kernel_table_source"] == "conservative"
    assert pool["kernel_params"]["kv_step"] == 4  # resolved: whole block
    r2 = audit_plan(PlanSpec(
        cfg=tiny(), serving=ServingConfig(block_size=4, use_kernel=False)
    ))
    assert r2.breakdown["kv_pool"]["kernel_variant"] == "fallback"


def test_bad_kernel_tuning_table_entry(tmp_path, monkeypatch):
    """A user tuning table whose entry cannot run on the plan's geometry
    (kv_step not dividing block_size) is an ERROR before anything compiles
    — even under auto dispatch, because tuned entries are on the route."""
    from mdi_llm_tpu.ops.tuning import TUNE_TABLE_ENV, save_tuning_table

    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {"*": {"kv_step": 5}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    r = audit_plan(PlanSpec(cfg=tiny(), serving=ServingConfig(block_size=4)))
    assert "bad-kernel-tuning" in codes(r)
    assert any("kv_step=5" in f.message for f in r.findings)
    assert r.breakdown["kv_pool"]["tuned"] is True


def test_bad_kernel_tuning_vmem_overage(tmp_path, monkeypatch):
    """A tuned scratch_width whose VMEM estimate exceeds the device budget
    errors with the budget named — before the kernel ever compiles."""
    from mdi_llm_tpu.ops.tuning import TUNE_TABLE_ENV, save_tuning_table

    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {"*": {"scratch_width": 1 << 22}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=4, use_kernel=True),
    ))
    assert "bad-kernel-tuning" in codes(r)
    assert any("VMEM" in f.message for f in r.findings)


def test_unreadable_tuning_table_is_loud(tmp_path, monkeypatch):
    """MDI_TUNE_TABLE pointing at a missing/corrupt file is a finding, not
    a silent fall-through to defaults the user did not ask for."""
    from mdi_llm_tpu.ops.tuning import TUNE_TABLE_ENV

    monkeypatch.setenv(TUNE_TABLE_ENV, str(tmp_path / "missing.json"))
    r = audit_plan(PlanSpec(cfg=tiny(), serving=ServingConfig(block_size=4)))
    assert "bad-kernel-tuning" in codes(r)
    assert any("cannot be read" in f.message for f in r.findings)


def test_pool_estimate_byte_exact_vs_live_engine_with_chunk_reservations():
    """The audited kv_pool bytes must equal the live engine's allocated
    pool byte-for-byte when chunked decode / speculative verify are on —
    chunk reservation changes which blocks are HELD, never how many the
    pool allocates (`ServingConfig.num_pool_blocks` is shared by both)."""
    import jax

    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import transformer

    cfg = tiny()
    sv = ServingConfig(
        block_size=8, max_batch=4, decode_chunk=8, spec_k=4,
        double_buffer=True,
    )
    seq_len = 64
    r = audit_plan(PlanSpec(cfg=cfg, serving=sv, max_seq_length=seq_len,
                            cache_dtype="float32"))
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    engine = Generator(
        cfg, params, max_seq_length=seq_len, cache_dtype="float32"
    ).serve(serving=sv)
    live = sum(int(x.nbytes) for x in jax.tree_util.tree_leaves(engine._kv))
    assert r.breakdown["kv_pool"]["pool_bytes"] == live
    assert r.breakdown["kv_pool"]["num_blocks"] == engine.pool.num_blocks


def test_draft_pool_estimate_byte_exact_vs_live_engine():
    """The `draft_*` kv_pool breakdown must equal the live draft pool's
    allocated bytes exactly — `num_draft_blocks`/`draft_pool_bytes` are
    the same formulas `_init_draft` allocates from, so the estimator and
    the engine can never disagree on the carve-out."""
    import jax

    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import transformer

    cfg = tiny()
    sv = ServingConfig(block_size=8, max_batch=2, decode_chunk=4, spec_k=4,
                       draft_model="pythia-14m", draft_share=0.25)
    seq_len = 64
    r = audit_plan(PlanSpec(cfg=cfg, serving=sv, max_seq_length=seq_len,
                            cache_dtype="float32"))
    assert codes(r) == []
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    engine = Generator(
        cfg, params, max_seq_length=seq_len, cache_dtype="float32"
    ).serve(serving=sv)
    live = sum(int(x.nbytes)
               for x in jax.tree_util.tree_leaves(engine._draft_kv))
    kvp = r.breakdown["kv_pool"]
    assert kvp["draft_pool_bytes"] == live
    assert kvp["draft_num_blocks"] == engine.draft_pool.num_blocks
    assert kvp["draft_model"] == "pythia-14m"


def test_draft_serving_config_walls():
    """Static refusals around the draft-model knob: a draft without
    spec_k, a vocab-mismatched draft, and a draft_share that starves the
    target pool below one slot's chunk-reservation headroom."""
    # draft with nothing to draft for
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=8, draft_model="pythia-14m"),
    ))
    assert "bad-serving-config" in codes(r)
    assert any("spec_k" in f.message for f in r.findings)
    # vocab mismatch: pythia vs llama tokenizers
    r = audit_plan(PlanSpec(
        cfg=Config.from_name("tiny-llama-1.1b"),
        serving=ServingConfig(block_size=8, spec_k=4,
                              draft_model="pythia-14m"),
    ))
    assert "bad-serving-config" in codes(r)
    assert any("vocab" in f.message for f in r.findings)
    # carve-out starves the target: max_blocks=6 at share 0.5 leaves the
    # target below headroom+1 usable blocks
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=8, spec_k=4, max_blocks=6,
                              draft_model="pythia-14m", draft_share=0.5),
    ))
    assert "bad-serving-config" in codes(r)
    assert any("draft_share" in f.message for f in r.findings)
    # an unknown draft name is a finding, not a crash
    r = audit_plan(PlanSpec(
        cfg=tiny(),
        serving=ServingConfig(block_size=8, spec_k=4,
                              draft_model="no-such-model"),
    ))
    assert "bad-serving-config" in codes(r)


def test_findings_reuse_lint_baseline_machinery():
    cfg = Config.from_name("tiny-llama-1.1b")
    plan = PlanSpec(cfg=cfg, mesh=MeshSpec.from_dict({"tp": 3}), tp_axis="tp")
    findings = audit_plan(plan).findings
    b = Baseline.from_findings(findings)
    new, old = b.split(audit_plan(plan).findings)
    assert new == [] and len(old) == 1  # grandfathered, like mdi-lint


# ---------------------------------------------------------------------------
# registry-wide properties
# ---------------------------------------------------------------------------


def test_param_specs_coverage_is_total_for_every_registry_config():
    """Every params leaf of every registry family must have a PartitionSpec
    — catches future model-surgery leaves silently falling back to full
    replication.  Abstract shapes make this free (no arrays, no backend)."""
    from mdi_llm_tpu.parallel.sharding import param_specs

    for name in name_to_config:
        cfg = Config.from_name(name)
        specs = param_specs(cfg, "tp")
        shape_paths = {p for p, _ in iter_leaves(abstract_params(cfg))}
        spec_paths = {p for p, _ in iter_leaves(specs)}
        assert shape_paths <= spec_paths, (
            f"{name}: leaves without specs: {sorted(shape_paths - spec_paths)}"
        )


def test_every_registry_config_audits_clean_under_default_plan():
    for name in name_to_config:
        report = audit_plan(PlanSpec(cfg=Config.from_name(name)))
        assert report.findings == [], (
            f"{name}: " + "; ".join(report.render_findings())
        )


EXAMPLE_PLANS = sorted(
    list((REPO / "examples" / "mesh_configs").glob("*.json"))
    + list((REPO / "examples" / "node_configs").glob("*.json"))
)


@pytest.mark.parametrize("plan_file", EXAMPLE_PLANS, ids=lambda p: p.name)
def test_shipped_example_plans_audit_clean(plan_file, capsys):
    """Every shipped example topology passes `mdi-audit` with zero ERROR
    findings against a registry model deep enough for its stage count."""
    rc = audit_main(["--model", "tiny-llama-1.1b", "--plan", str(plan_file)])
    out = capsys.readouterr().out
    assert rc == 0, out


# ---------------------------------------------------------------------------
# the no-backend guarantee
# ---------------------------------------------------------------------------


def test_audit_never_touches_a_jax_backend(monkeypatch):
    """The whole point: a plan is auditable before any device exists.  Trip-
    wire every backend/device/compile entry point and run the full checker
    stack (sharding + memory + schedule + serving, quantized, budgeted)."""
    import jax
    from jax._src import xla_bridge

    def boom(*a, **k):
        raise AssertionError("mdi-audit touched the JAX backend")

    monkeypatch.setattr(xla_bridge, "backends", boom)
    monkeypatch.setattr(xla_bridge, "get_backend", boom)
    monkeypatch.setattr(jax, "devices", boom)
    monkeypatch.setattr(jax, "local_devices", boom)
    monkeypatch.setattr(jax, "jit", boom)

    cfg = Config.from_name("tiny-llama-1.1b")
    r = preflight(cfg, n_stages=4, tp=2, n_samples=8, seq_len=2048,
                  quantize="int8", hbm_gb=16)
    assert r.findings == []
    r = audit_plan(PlanSpec(
        cfg=cfg, serving=ServingConfig(max_batch=8), hbm_gb=16,
        quantize="int4",
    ))
    assert r.findings == []
    # bad plans too (every finding path must stay backend-free)
    assert codes(audit_plan(PlanSpec(
        cfg=cfg, mesh=MeshSpec.from_dict({"tp": 3}), tp_axis="tp",
    ))) == ["indivisible-dim"]


# ---------------------------------------------------------------------------
# memory estimates vs live arrays
# ---------------------------------------------------------------------------


def test_est_hbm_bytes_matches_live_arrays_within_15_percent():
    """Acceptance bound: params+KV estimate within 15% of the runtime's
    live-array total for a bench-style decode row (it is exact by
    construction — the stub tree mirrors init_params leaf for leaf)."""
    import jax
    import jax.numpy as jnp

    from mdi_llm_tpu.generation import _bucket, _run_cache_len
    from mdi_llm_tpu.models import transformer

    cfg = tiny()
    B, prompt_len, new = 2, 8, 4
    seq_len = 64
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    Tb = min(_bucket(prompt_len), seq_len)
    cache_len = _run_cache_len(seq_len, prompt_len + new, Tb)
    kv = transformer.init_kv_cache(cfg, B, cache_len, dtype=jnp.bfloat16)
    live = sum(int(x.nbytes) for x in jax.tree_util.tree_leaves((params, kv)))

    report = preflight(cfg, batch=B, seq_len=seq_len, kv_seq_len=cache_len,
                       dtype="bfloat16")
    est = audit_detail(report)["est_hbm_bytes"]
    assert abs(est - live) / live < 0.15, (est, live)
    assert est == live  # and in fact exact for the dense bf16 layout


def test_quantized_storage_estimate_matches_quantize_params():
    import jax
    import jax.numpy as jnp

    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.ops.quant import quantize_params

    cfg = tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    for flag in ("int8", "int4"):
        real = quantize_params(
            jax.tree_util.tree_map(np.asarray, params),
            mode={"int8": "w8", "int4": "w4"}[flag],
        )
        live = sum(int(x.nbytes) for x in jax.tree_util.tree_leaves(real))
        est = tree_bytes(abstract_params(cfg, "bfloat16", flag))
        assert est == live, (flag, est, live)


def test_estimate_kv_bytes_hand_computed_two_registry_models():
    # tiny-llama-1.1b: L=22, G=4, hs=64
    cfg = Config.from_name("tiny-llama-1.1b")
    assert (cfg.n_layer, cfg.n_query_groups, cfg.head_size) == (22, 4, 64)
    assert cfg.estimate_kv_bytes(2, 128, "bfloat16") == 2 * 22 * 2 * 4 * 128 * 64 * 2
    # pythia-70m: L=6, H=G=8, hs=64
    cfg = Config.from_name("pythia-70m")
    assert (cfg.n_layer, cfg.n_query_groups, cfg.head_size) == (6, 8, 64)
    assert cfg.estimate_kv_bytes(4, 256, "float32") == 2 * 6 * 4 * 8 * 256 * 64 * 4


def test_pool_bytes_hand_computed():
    cfg = Config.from_name("tiny-llama-1.1b")  # block_size (context) = 2048
    sv = ServingConfig(block_size=16, max_batch=8)
    # full coverage: 1 trash + 8 * (2048/16) = 1025 blocks
    assert sv.num_pool_blocks(2048) == 1025
    assert sv.pool_bytes(cfg, 2048, "bfloat16") == 2 * 22 * 1025 * 16 * 4 * 64 * 2
    assert ServingConfig(max_blocks=64).num_pool_blocks(2048) == 64


def test_dtype_bytes_accepts_names_and_dtypes():
    import jax.numpy as jnp

    assert dtype_bytes("bfloat16") == 2
    assert dtype_bytes("float32") == 4
    assert dtype_bytes(np.dtype("float16")) == 2
    assert dtype_bytes(np.float32) == 4
    assert dtype_bytes(jnp.bfloat16) == 2
    with pytest.raises(ValueError):
        dtype_bytes("no-such-dtype")


# ---------------------------------------------------------------------------
# satellites: mesh + partition validation
# ---------------------------------------------------------------------------


def test_make_mesh_names_offending_axis(devices):
    from mdi_llm_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match=r"axis 'tp' must have size >= 1"):
        make_mesh({"tp": 0, "dp": 2}, devices)
    with pytest.raises(ValueError, match=r"cannot infer axis 'dp'"):
        make_mesh({"tp": 3, "dp": -1}, devices)  # 8 devices % 3 != 0
    with pytest.raises(ValueError, match="only one axis size may be -1"):
        make_mesh({"tp": -1, "dp": -1}, devices)
    with pytest.raises(ValueError, match="needs 16 devices, have 8"):
        make_mesh({"pipe": 16}, devices)
    # valid inference still works and yields an integer >= 1
    m = make_mesh({"dp": -1, "tp": 2}, devices)
    assert dict(m.shape) == {"dp": 4, "tp": 2}


def test_stage_layers_rejects_oversplit_and_empty_stages():
    from mdi_llm_tpu.parallel.partition import stage_layers

    with pytest.raises(ValueError, match="n_stages <= 6"):
        stage_layers(6, 7)
    with pytest.raises(ValueError, match="n_stages must be >= 1"):
        stage_layers(6, 0)
    # every valid split sums to n_layer with no empty stage
    for n_layer in (5, 6, 7, 9, 12, 22, 24, 32, 48):
        for n_stages in range(1, min(n_layer, 9) + 1):
            counts = stage_layers(n_layer, n_stages)
            assert sum(counts) == n_layer and min(counts) >= 1


def test_split_params_rejects_oversplit_with_actionable_message():
    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.parallel.partition import split_params

    cfg = tiny()  # 6 layers
    import jax

    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="every stage needs >= 1"):
        split_params(cfg, params, 7)


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_clean_plan_exits_zero(capsys):
    assert audit_main(["--model", "tiny-llama-1.1b", "--stages", "4",
                       "--n-samples", "8", "--hbm-gb", "16"]) == 0
    out = capsys.readouterr().out
    assert "findings: none" in out and "stage layers" in out


def test_cli_bad_plan_exits_one(capsys):
    assert audit_main(["--model", "tiny-llama-1.1b", "--tp", "3"]) == 1
    assert "indivisible-dim" in capsys.readouterr().out


def test_cli_usage_errors_exit_two(capsys):
    assert audit_main([]) == 2  # no model source
    assert audit_main(["--model", "no-such-model"]) == 2


def test_cli_json_format(capsys):
    rc = audit_main(["--model", "tiny-llama-1.1b", "--serve", "--hbm-gb",
                     "16", "--format", "json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["errors"] == 0
    assert data["breakdown"]["per_device"]["kv_bytes"] > 0
    assert data["breakdown"]["kv_pool"]["num_blocks"] > 1


def test_cli_list_checks(capsys):
    assert audit_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for code in AUDIT_RULES:
        assert code in out


def test_cli_warning_does_not_fail(capsys):
    # underfilled ring: reported, but exit 0 (launch-blocking is preflight's
    # job only for ERROR findings)
    rc = audit_main(["--model", "tiny-llama-1.1b", "--stages", "4",
                     "--n-samples", "1"])
    assert rc == 0
    assert "pipeline-underfill" in capsys.readouterr().out


def test_cli_samples_per_slot_overrides_plan_file(tmp_path, capsys):
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps(
        {"pipeline_stages": 2, "samples_per_slot": 4, "n_samples": 8}
    ))
    rc = audit_main(["--model", "tiny-llama-1.1b", "--plan", str(plan),
                     "--samples-per-slot", "1", "--format", "json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["breakdown"]["ring_lanes"] == 2  # M=1 won, not the file's 4


def test_module_entrypoint_dispatches_audit():
    proc = subprocess.run(
        [sys.executable, "-m", "mdi_llm_tpu.analysis", "audit", "--list-checks"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0 and "unmatched-permute" in proc.stdout
    # bare invocation still lints
    proc = subprocess.run(
        [sys.executable, "-m", "mdi_llm_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0 and "static-float-arg" in proc.stdout


def test_pyproject_registers_console_script():
    txt = (REPO / "pyproject.toml").read_text()
    assert 'mdi-audit = "mdi_llm_tpu.analysis.audit:main"' in txt


# ---------------------------------------------------------------------------
# preflight integration (bench / serve / starter)
# ---------------------------------------------------------------------------


def _bench_args(*argv):
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    args = bench.build_parser().parse_args(["--direct", *argv])
    if args.chunk is None:
        args.chunk = 16 if args.pipeline else 256
    return bench, args


def test_bench_preflight_records_audit_and_refuses_bad_plan(capsys):
    bench, args = _bench_args("--model", "tiny-llama-1.1b", "--batch", "2",
                              "--prompt-len", "8", "--new-tokens", "4",
                              "--seq-len", "64")
    from mdi_llm_tpu.config import Config

    cfg = Config.from_name(args.model)
    detail = bench.run_preflight(args, cfg, "decode")
    assert detail["findings"] == 0 and detail["est_hbm_bytes"] > 0

    # an over-budget plan refuses...
    args.hbm_gb = 0.001
    with pytest.raises(SystemExit, match="preflight refused"):
        bench.run_preflight(args, cfg, "decode")
    # ...unless --no-preflight downgrades it to a warning
    args.no_preflight = True
    detail = bench.run_preflight(args, cfg, "decode")
    assert detail["findings"] == 1


def test_starter_preflight_refuses_bad_plan_via_abort_sentinel(tmp_path):
    """A refusal must exit cleanly through the run-spec channel (the same
    broadcast the secondaries block on), not strand the job."""
    from mdi_llm_tpu.cli.starter import main as starter_main

    cfg_p = tmp_path / "standalone.json"
    cfg_p.write_text(json.dumps({"nodes": {"starter": {
        "addr": "127.0.0.1", "communication": {"port": 1}}, "secondary": []}}))
    argv = ["--model", "pythia-14m", "--device", "cpu", "--nodes-config",
            str(cfg_p), "--pipeline-stages", "7", "--n-tokens", "4",
            "--n-samples", "8"]  # 6 layers over 7 stages: bad-stage-split
    with pytest.raises(SystemExit, match="preflight refused"):
        starter_main(argv)
    # --no-preflight downgrades; the launch then proceeds past the audit
    # (and on this jax build fails later in shard_map, like the seed does)
    with pytest.raises((SystemExit, ValueError, AttributeError)) as ei:
        starter_main(argv + ["--no-preflight"])
    assert "preflight" not in str(ei.value)


def test_serve_cli_preflight_refuses_over_budget_pool(tmp_path, capsys):
    from mdi_llm_tpu.cli.serve import main as serve_main

    argv = ["--model", "pythia-14m", "--synthetic", "2", "--n-tokens", "4",
            "--sequence-length", "64", "--max-batch", "2", "--device", "cpu",
            "--hbm-gb", "0.0001"]
    with pytest.raises(SystemExit, match="preflight refused"):
        serve_main(argv)
    assert "hbm-over-budget" in capsys.readouterr().err
