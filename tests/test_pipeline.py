"""Pipeline runtime golden-token tests on the virtual 8-device CPU mesh.

The decisive invariant (SURVEY.md §7 "output parity"): recurrent-pipeline
generation must reproduce single-device greedy generation token-for-token,
for any stage count, wave size, and prompt-length mix.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.mesh import pipeline_mesh
from mdi_llm_tpu.parallel.pipeline import PipelineEngine
from tests.test_model import tiny_config, CONFIG_VARIANTS


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=128, n_layer=5)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def single_engine(model):
    cfg, params = model
    return Generator(cfg, params, cache_dtype=jnp.float32)


PROMPTS = [[3, 1, 4, 1, 5], [2, 7, 18], [9, 9, 9, 9, 9, 9, 9], [6, 2]]


def _single(engine, prompts, n):
    outs = []
    for p in prompts:
        o, _ = engine.generate([p], n, temperature=0.0)
        outs.append(o[0])
    return outs


@pytest.mark.smoke
@pytest.mark.parametrize("n_stages", [1, 2, 4])
def test_pipeline_matches_single_device(model, single_engine, n_stages, devices):
    cfg, params = model
    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(n_stages, devices[:n_stages]),
        cache_dtype=jnp.float32,
    )
    want = _single(single_engine, PROMPTS[:n_stages], 10)
    got, stats = eng.generate(PROMPTS[:n_stages], 10, temperature=0.0)
    assert got == want
    assert stats.tokens_generated == 10 * n_stages


def test_pipeline_waves_more_samples_than_stages(model, single_engine, devices):
    """n_samples > n_stages: samples run in waves over the same slots."""
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    want = _single(single_engine, PROMPTS, 8)
    got, _ = eng.generate(PROMPTS, 8, temperature=0.0)
    assert got == want


def test_pipeline_partial_wave(model, single_engine, devices):
    """Fewer samples than stages (bubbles in the ring)."""
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(4, devices[:4]), cache_dtype=jnp.float32
    )
    want = _single(single_engine, PROMPTS[:2], 6)
    got, _ = eng.generate(PROMPTS[:2], 6, temperature=0.0)
    assert got == want


def test_pipeline_stop_sequences(model, single_engine, devices):
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    free = _single(single_engine, PROMPTS[:2], 8)
    stop = [free[0][len(PROMPTS[0]) + 3]]  # 4th generated token of sample 0
    got, _ = eng.generate(PROMPTS[:2], 8, temperature=0.0, stop_sequences=[stop])
    assert got[0] == free[0][: len(PROMPTS[0]) + 3]


def test_pipeline_stream_cb(model, single_engine, devices):
    """stream_cb surfaces every generated token, in order, per sample —
    including across waves (more samples than lanes) — and the returned
    (trimmed) token lists are a prefix of what streamed."""
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    streamed = {j: [] for j in range(len(PROMPTS))}
    got, _ = eng.generate(
        PROMPTS, 8, temperature=0.0,
        stream_cb=lambda j, t: streamed[j].append(t),
    )
    want = _single(single_engine, PROMPTS, 8)
    assert got == want
    for j, o in enumerate(got):
        gen = o[len(PROMPTS[j]) :]
        assert streamed[j] == gen  # no stop sequences → stream == result


def test_pipeline_stream_cb_with_stops(model, single_engine, devices):
    """With a stop sequence, the stream covers at least the kept tokens and
    at most the kept tokens + the stop marker (never beyond)."""
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    free = _single(single_engine, PROMPTS[:2], 8)
    stop = [free[0][len(PROMPTS[0]) + 3]]
    streamed = {0: [], 1: []}
    got, _ = eng.generate(
        PROMPTS[:2], 8, temperature=0.0, stop_sequences=[stop],
        stream_cb=lambda j, t: streamed[j].append(t),
    )
    kept0 = got[0][len(PROMPTS[0]) :]
    assert streamed[0][: len(kept0)] == kept0
    assert len(streamed[0]) <= len(kept0) + len(stop)


@pytest.mark.parametrize("n_samples", [4, 3])
def test_pipeline_samples_per_slot(model, single_engine, n_samples, devices):
    """M > 1: each ring slot carries M samples batched through the stage
    blocks (n_samples=3 leaves a ragged, invalid lane in the last group)."""
    cfg, params = model
    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(2, devices[:2]),
        cache_dtype=jnp.float32,
        samples_per_slot=2,
    )
    want = _single(single_engine, PROMPTS[:n_samples], 8)
    got, stats = eng.generate(PROMPTS[:n_samples], 8, temperature=0.0)
    assert got == want
    assert stats.tokens_generated == 8 * n_samples


def test_pipeline_samples_per_slot_waves(model, single_engine, devices):
    """n_samples > S*M still runs in waves."""
    cfg, params = model
    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(1, devices[:1]),
        cache_dtype=jnp.float32,
        samples_per_slot=2,
    )
    want = _single(single_engine, PROMPTS, 6)
    got, _ = eng.generate(PROMPTS, 6, temperature=0.0)
    assert got == want


def test_pipeline_continuous_beats_waves(model, single_engine, devices):
    """n_samples = 3×S with mixed finish times: the continuous scheduler
    refills a freed lane immediately, so total ring rotations are strictly
    fewer than wave scheduling (ceil(N/S) waves, each pinned to its slowest
    sample) at identical output (reference economics: gptserver.py:912-1001,
    README.md:33-37)."""
    cfg, params = model
    NEW = 20
    # rotations_per_call=1 isolates the scheduling policy: the default
    # steady-state chunking trades surplus rotations for fewer dispatches,
    # which is invisible here (rotation counts are the metric, wall time is
    # what chunking buys)
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32,
        rotations_per_call=1,
    )
    pool = [[3, 1, 4], [2, 7, 18], [9, 9, 9], [6, 2], [11, 5], [8, 13, 21]]
    free = _single(single_engine, pool, NEW)
    # stop sequences that cut samples 1, 3, 5 after their 2nd generated token
    stops = [[free[j][len(pool[j]) + 1]] for j in (1, 3, 5)]
    want = []
    for p in pool:
        o, _ = single_engine.generate(
            [p], NEW, temperature=0.0, stop_sequences=stops
        )
        want.append(o[0])
    gens = [len(w) - len(p) for w, p in zip(want, pool)]
    # setup sanity: even samples run long, odd samples stop early — every
    # wave of 2 would be pinned by a long sample
    assert min(gens[0], gens[2], gens[4]) >= 3 * max(gens[1], gens[3], gens[5])

    got, stats = eng.generate(pool, NEW, temperature=0.0, stop_sequences=stops)
    assert got == want
    wave_rot = sum(max(gens[w : w + 2]) for w in range(0, 6, 2))
    assert stats.rotations < wave_rot, (stats.rotations, wave_rot, gens)


def test_pipeline_batch_refill_long_prompts(model, single_engine, devices):
    """Queued samples with long prompts are refilled via a parallel prefill
    call into the freed slot, not fed token-by-token: rotations stay
    generation-bound, not prompt-length-bound."""
    cfg, params = model
    NEW = 6
    rng = np.random.default_rng(7)
    pool = [rng.integers(1, 50, 40).tolist() for _ in range(4)]
    # rotations_per_call=1: rotation counts are the scheduling metric here,
    # and steady-state chunking adds lookahead/overshoot rotations
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32,
        rotations_per_call=1,
    )
    want = _single(single_engine, pool, NEW)
    got, stats = eng.generate(pool, NEW, temperature=0.0)
    assert got == want
    # 2 generation phases of <= NEW rotations each (+ seeding/reseed and one
    # in-flight lookahead rotation per phase); a token-by-token refill would
    # need >= 40 rotations per queued prompt
    assert stats.rotations <= 2 * NEW + 8, stats.rotations


def test_pipeline_partial_slot_token_fill(model, single_engine, devices):
    """M=2 with early-stopping lanes: a freed lane whose slot sibling is
    still generating gets its queued prompt fed token-by-token through the
    override channel (batch prefill only covers fully-free slots)."""
    cfg, params = model
    NEW = 20
    rng = np.random.default_rng(11)
    pool = [rng.integers(1, 50, n).tolist() for n in (5, 3, 7, 2, 4, 6, 14, 3)]
    free = _single(single_engine, pool, NEW)
    stops = [[free[j][len(pool[j]) + 1]] for j in (1, 2, 3, 4, 5)]
    want = []
    for p in pool:
        o, _ = single_engine.generate([p], NEW, temperature=0.0, stop_sequences=stops)
        want.append(o[0])
    gens = [len(w) - len(p) for w, p in zip(want, pool)]
    # setup sanity: sample 0 occupies its lane for the whole run while its
    # slot sibling (sample 1) frees immediately
    assert gens[0] == NEW and gens[1] <= 2

    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(2, devices[:2]),
        cache_dtype=jnp.float32,
        samples_per_slot=2,
    )
    got, stats = eng.generate(pool, NEW, temperature=0.0, stop_sequences=stops)
    assert got == want
    assert stats.token_fills >= 1  # the partial-slot path actually ran


def test_pipeline_gqa_variant(devices):
    cfg = tiny_config(block_size=64, n_layer=4, **CONFIG_VARIANTS["gqa"])
    params = init_params(cfg, jax.random.PRNGKey(3))
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    want, _ = single.generate([[4, 8, 15]], 7, temperature=0.0)
    got, _ = eng.generate([[4, 8, 15]], 7, temperature=0.0)
    assert got == want


def test_pipeline_gpt2_variant(devices):
    """Learned position embeddings travel through the ring correctly."""
    cfg = tiny_config(block_size=64, n_layer=4, **CONFIG_VARIANTS["gpt2"])
    params = init_params(cfg, jax.random.PRNGKey(4))
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    want, _ = single.generate([[4, 8, 15, 16]], 6, temperature=0.0)
    got, _ = eng.generate([[4, 8, 15, 16]], 6, temperature=0.0)
    assert got == want


def test_pipeline_tp_matches_single_device(model, single_engine, devices):
    """pipe x tp mesh: stage ring manual over "pipe", per-stage matmuls
    GSPMD-sharded over the auto "tp" axis (Megatron specs) — the classic
    serving topology, token-identical to single-device generation."""
    cfg, params = model
    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(2, devices[:4], tp=2),
        cache_dtype=jnp.float32,
    )
    want = _single(single_engine, PROMPTS[:2], 10)
    got, stats = eng.generate(PROMPTS[:2], 10, temperature=0.0)
    assert got == want
    assert stats.tokens_generated == 20


def test_pipeline_tp_samples_per_slot(model, single_engine, devices):
    cfg, params = model
    eng = PipelineEngine(
        cfg,
        params,
        mesh=pipeline_mesh(2, devices[:4], tp=2),
        cache_dtype=jnp.float32,
        samples_per_slot=2,
    )
    want = _single(single_engine, PROMPTS, 8)
    got, _ = eng.generate(PROMPTS, 8, temperature=0.0)
    assert got == want


def test_pipeline_tp_quantized_parity(model, devices):
    """pipe x tp with int8 weights (pre-r5 this was rejected): the stage
    blocks' quantized leaves lay out under the adapted Megatron specs and
    generation matches the single-device quantized engine."""
    cfg, params = model
    from mdi_llm_tpu.generation import Generator

    single_q = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int8")
    want, _ = single_q.generate(PROMPTS[:2], 10, temperature=0.0)
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:4], tp=2),
        cache_dtype=jnp.float32, quantize="int8",
    )
    got, _ = eng.generate(PROMPTS[:2], 10, temperature=0.0)
    assert got == want
    # col-parallel weight_q sharded over tp; its scale follows the out dim
    qkv = eng.stage_blocks["attn"]["qkv"]
    assert "tp" in str(qkv["weight_q"].sharding.spec)
    assert "tp" in str(qkv["scale"].sharding.spec)


@pytest.mark.parametrize("overlap", [True, False])
def test_pipeline_overlap_modes_parity(model, single_engine, overlap, devices):
    """Both chunk-fetch orderings (dispatch-then-flush vs flush-then-
    dispatch) must be token-identical: the in-flight chunk's tokens are
    valid continuations and boundaries flush before building overrides."""
    cfg, params = model
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32,
        overlap_chunks=overlap,
    )
    pool = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7], [2, 7, 1], [8, 8]]
    want = _single(single_engine, pool, 12)
    got, _ = eng.generate(pool, 12, temperature=0.0)
    assert got == want


def test_pipeline_moe_matches_single_device(single_engine, devices):
    """Routed MoE blocks (LLaMAMoE) travel the ring correctly: top-k expert
    routing inside each stage's scanned block stack, token-identical to
    single-device generation."""
    cfg = tiny_config(
        block_size=64, n_layer=4, mlp_class_name="LLaMAMoE",
        n_expert=4, n_expert_per_token=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(2))
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    prompts = [[4, 8, 15], [16, 23, 42]]
    want = [single.generate([p], 6, temperature=0.0)[0][0] for p in prompts]
    eng = PipelineEngine(
        cfg, params, mesh=pipeline_mesh(2, devices[:2]), cache_dtype=jnp.float32
    )
    got, _ = eng.generate(prompts, 6, temperature=0.0)
    assert got == want
