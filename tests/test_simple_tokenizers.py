"""Char and trainable-BPE tokenizers (legacy-generation parity)."""

import numpy as np

from mdi_llm_tpu.utils.simple_tokenizers import BPETokenizer, CharTokenizer


def test_char_roundtrip(tmp_path):
    text = "hello shakespeare!\nact one."
    tok = CharTokenizer().train(text)
    ids = tok.encode("hello one")
    assert tok.decode(ids) == "hello one"
    assert tok.vocab_size == len(set(text))
    tok.save(tmp_path / "char.json")
    tok2 = CharTokenizer.load(tmp_path / "char.json")
    np.testing.assert_array_equal(tok2.encode("hello"), tok.encode("hello"))


def test_bpe_train_and_roundtrip(tmp_path):
    text = "the quick brown fox jumps over the lazy dog " * 50
    tok = BPETokenizer().train(text, vocab_size=300)
    assert 256 < tok.vocab_size <= 300
    ids = tok.encode("the quick brown fox")
    assert tok.decode(ids) == "the quick brown fox"
    # merges compress: fewer tokens than bytes
    assert len(ids) < len("the quick brown fox".encode())
    tok.save(tmp_path / "bpe.json")
    tok2 = BPETokenizer.load(tmp_path / "bpe.json")
    np.testing.assert_array_equal(tok2.encode("lazy dog"), tok.encode("lazy dog"))
    assert tok2.decode(tok2.encode("héllo wörld")) == "héllo wörld"


def test_bpe_handles_unseen_text():
    tok = BPETokenizer().train("aaaa bbbb aaaa bbbb", vocab_size=260)
    out = tok.decode(tok.encode("zzz unseen ©"))
    assert out == "zzz unseen ©"
