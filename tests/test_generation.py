"""Generation engine tests: greedy determinism, stop tokens, batching,
streaming chat parity with batch generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator, detect_stop_tokens, find_eot
from mdi_llm_tpu.models import init_params
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def small_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_stop_token_helpers():
    assert detect_stop_tokens([1, 2, 3], [[2, 3]])
    assert not detect_stop_tokens([1, 2, 3], [[3, 2]])
    assert detect_stop_tokens([5], [[5]])
    assert not detect_stop_tokens([], [[1]])
    assert find_eot([1, 2, 3, 4], [[3]]) == 2
    assert find_eot([1, 2], [[9]]) == 2
    assert find_eot([7, 8, 9], [[7, 8], [9]]) == 0


def test_greedy_generation_deterministic(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    out1, stats = gen.generate([[5, 6, 7]], 12, temperature=0.0)
    out2, _ = gen.generate([[5, 6, 7]], 12, temperature=0.0)
    assert out1 == out2
    assert len(out1[0]) == 3 + 12
    assert stats.tokens_generated == 12
    assert len(stats.tok_time) == 12


def test_batched_matches_single_greedy(small_model):
    """Batched generation with unequal prompt lengths must equal per-sample
    runs (the recurrent-parallelism analog on one chip)."""
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    p0, p1 = [3, 1, 4, 1, 5], [2, 7]
    single0, _ = gen.generate([p0], 8, temperature=0.0)
    single1, _ = gen.generate([p1], 8, temperature=0.0)
    both, _ = gen.generate([p0, p1], 8, temperature=0.0)
    assert both[0] == single0[0]
    assert both[1] == single1[0]


def test_stop_sequence_truncates(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    # discover the greedy continuation, then use its 3rd generated token as a
    # stop token — output must be truncated right before it
    free, _ = gen.generate([[9, 9]], 10, temperature=0.0)
    third = free[0][2 + 2]
    stopped, _ = gen.generate([[9, 9]], 10, temperature=0.0, stop_sequences=[[third]])
    assert stopped[0] == free[0][: 2 + 2]


def test_chat_stream_matches_generate(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32, rng_seed=7)
    batch, _ = gen.generate([[11, 12, 13]], 10, temperature=0.0)
    streamed = list(
        Generator(cfg, params, cache_dtype=jnp.float32, rng_seed=7).generate_chat(
            [11, 12, 13], 10, temperature=0.0
        )
    )
    assert batch[0][3:] == streamed


def test_sequence_length_guard(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, max_seq_length=16, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="exceeds max_seq_length"):
        gen.generate([[1] * 10], 20)


def test_non_pow2_max_seq_prompt_bucket(small_model):
    """A non-power-of-two max_seq_length with a prompt whose pow2 bucket
    exceeds it must still generate (the bucket clamps to max_seq_length so
    the run-sized cache always covers the prefill chunk)."""
    cfg, params = small_model
    gen = Generator(cfg, params, max_seq_length=50, cache_dtype=jnp.float32)
    prompt = [1 + (i % 7) for i in range(40)]  # _bucket(40)=64 > 50
    out, _ = gen.generate([prompt], 8, temperature=0.0)
    assert len(out[0]) == 48
    full = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _ = full.generate([prompt], 8, temperature=0.0)
    assert out == want


def test_speculative_matches_plain_greedy():
    """Speculative decoding must be token-identical to plain greedy decode,
    across accept/reject mixes (repetitive prompt -> long accepts; random
    tail -> rejects) and window-edge fallback."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(11), dtype=jnp.float32)
    # repetitive prompt so n-gram lookup actually drafts
    prompt = [5, 9, 2, 7, 5, 9, 2, 7, 5, 9, 2, 7, 3]

    plain = Generator(cfg, params, rng_seed=3)
    spec = Generator(cfg, params, rng_seed=3)
    for n_tokens in (5, 17, 40):
        o1, _ = plain.generate([prompt], n_tokens, temperature=0.0, chunk_size=4)
        o2, s2 = spec.generate([prompt], n_tokens, temperature=0.0, speculative=4)
        assert o1 == o2, f"n_tokens={n_tokens}: speculative diverged"
        assert not s2.interrupted

    with pytest.raises(ValueError):
        spec.generate([prompt], 5, temperature=0.8, speculative=4)
    with pytest.raises(ValueError):
        spec.generate([prompt, prompt], 5, temperature=0.0, speculative=4)


def test_speculative_stop_sequence_parity():
    """A stop sequence that fires mid-burst / mid-accept must leave the same
    trimmed output as plain decode, and `positions` accounting must not run
    past the last emitted token (drift poisons continuation)."""
    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(11), dtype=jnp.float32)
    prompt = [5, 9, 2, 7, 5, 9, 2, 7, 5, 9, 2, 7, 3]
    plain = Generator(cfg, params, rng_seed=3)
    spec = Generator(cfg, params, rng_seed=3)
    free, _ = plain.generate([prompt], 24, temperature=0.0)
    # stop on a token emitted deep enough that a draft/burst spans it
    for cut in (3, 7, 12):
        stop = [[free[0][len(prompt) + cut]]]
        o1, _ = plain.generate([prompt], 24, temperature=0.0, stop_sequences=stop)
        o2, _ = spec.generate(
            [prompt], 24, temperature=0.0, speculative=4, stop_sequences=stop
        )
        assert o1 == o2, f"cut={cut}: speculative+stop diverged"


def test_batch_compaction_greedy_parity(small_model):
    """Early-stopping samples trigger batch compaction (lane reclaim);
    greedy outputs must equal both per-sample runs and a run where
    compaction never fires (chunk_size=1 makes stops visible promptly so
    the batch shrinks through several buckets)."""
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    prompts = [[i + 1, i + 2, i + 3] for i in range(8)]

    # free-run each sample to pick stops that land at staggered times
    free = [gen.generate([p], 14, temperature=0.0)[0][0] for p in prompts]
    stops = [
        [free[0][3 + 2]],   # sample 0 stops after ~3 tokens
        [free[1][3 + 4]],
        [free[2][3 + 6]],
        [free[3][3 + 8]],
    ]
    want = []
    for p, f in zip(prompts, free):
        cut = find_eot(f[3:], stops)
        want.append(f[: 3 + cut])

    got, stats = gen.generate(
        prompts, 14, temperature=0.0, stop_sequences=stops, chunk_size=1
    )
    assert got == want
    assert stats.compactions >= 1  # the lane reclaim actually engaged

    # identical results with chunked decode (compaction at chunk edges)
    got2, _ = gen.generate(
        prompts, 14, temperature=0.0, stop_sequences=stops, chunk_size=4
    )
    assert got2 == want


def test_batch_compaction_skipped_on_mesh(small_model, devices):
    """dp-sharded batches keep their lane count (KV sharding is laid out
    for the original dp-divisible batch)."""
    from mdi_llm_tpu.parallel.mesh import make_mesh

    cfg, params = small_model
    gen = Generator(
        cfg, params, cache_dtype=jnp.float32,
        mesh=make_mesh({"dp": 2}, jax.devices()[:2]),
    )
    free, _ = gen.generate([[1, 2], [3, 4]], 10, temperature=0.0)
    stop = [free[0][2 + 2]]
    got, stats = gen.generate(
        [[1, 2], [3, 4]], 10, temperature=0.0, stop_sequences=[stop]
    )
    assert stats.compactions == 0
    assert got[0] == free[0][: 2 + find_eot(free[0][2:], [stop])]


def test_ngram_draft_lookup():
    from mdi_llm_tpu.generation import ngram_draft

    toks = [1, 2, 3, 9, 8, 1, 2, 3, 4, 5, 6, 1, 2, 3]
    # trailing [1,2,3] last occurred at index 5 -> followed by 4,5,6,...
    assert ngram_draft(toks, 3) == [4, 5, 6]
    assert ngram_draft(toks, 10) == [4, 5, 6, 1, 2, 3]
    assert ngram_draft([1, 2], 4) == []
    # latest earlier occurrence of [7,7] starts at index 2; only one token follows
    assert ngram_draft([7, 7, 7, 7, 7], 2, ngram=2) == [7]


# ---------------------------------------------------------------------------
# ChatSession: cross-turn KV reuse must be token-identical to the stateless
# full-history re-prefill the reference REPL performs every turn
# ---------------------------------------------------------------------------


def _baseline_turn(cfg, params, history, turn, n, stop=()):
    """Reference behavior: re-prefill the whole conversation every turn."""
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    return list(gen.generate_chat(history + turn, n, temperature=0.0,
                                  stop_sequences=stop))


def test_chat_session_matches_full_reprefill(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    sess = gen.chat_session()
    history: list[int] = []
    for turn in ([5, 6, 7], [11, 2], [23, 23, 4, 9]):
        want = _baseline_turn(cfg, params, history, turn, 8)
        got = list(sess.send(turn, 8, temperature=0.0))
        assert got == want, f"turn {turn}: session diverged from re-prefill"
        history += turn + want
        assert sess.history == history


def test_chat_session_stop_sequence_and_pending(small_model):
    """A turn trimmed by a stop marker must roll the cache back to the
    logical reply (dead slots invisible), and a turn that ends at max_new
    leaves its final token pending — both must keep later turns identical
    to the stateless baseline."""
    cfg, params = small_model
    # discover greedy continuation to build a real stop marker
    free = _baseline_turn(cfg, params, [], [9, 9], 10)
    stop = [[free[2]]]
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    sess = gen.chat_session()
    history: list[int] = []
    for turn, st in (([9, 9], stop), ([3, 1, 4], ()), ([1, 5], stop)):
        want = _baseline_turn(cfg, params, history, turn, 10, st)
        got = list(sess.send(turn, 10, temperature=0.0, stop_sequences=st))
        assert got == want
        history += turn + want
        assert sess.history == history


def test_chat_session_window_slide(small_model):
    """When the conversation outgrows max_seq_length the session must slide
    the window and keep matching a stateless run over the same window."""
    cfg, params = small_model
    gen = Generator(cfg, params, max_seq_length=48, cache_dtype=jnp.float32)
    sess = gen.chat_session()
    history: list[int] = []
    for i in range(5):  # 5 turns x (4 prompt + 6 reply) overflows 48
        turn = [2 + i, 3 + i, 5 + i, 7 + i]
        window = (history + turn)[-(48 - 6 - 1):]
        want = _baseline_turn(cfg, params, window[: len(window) - len(turn)],
                              window[len(window) - len(turn):], 6)
        got = list(sess.send(turn, 6, temperature=0.0))
        assert got == want, f"turn {i} diverged"
        history = sess.history[:]
    assert len(sess.history) <= 48


def test_chat_session_empty_turn_raises(small_model):
    cfg, params = small_model
    sess = Generator(cfg, params, cache_dtype=jnp.float32).chat_session()
    with pytest.raises(ValueError, match="empty turn"):
        list(sess.send([], 4, temperature=0.0))


def test_chat_session_cache_growth_preserves_parity():
    """The session cache starts run-sized and grows geometrically; growth
    copies existing entries (layout-agnostic corner update), so replies
    across a growth boundary must still match the stateless baseline."""
    cfg = tiny_config(block_size=1024)
    params = init_params(cfg, jax.random.PRNGKey(0))
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    sess = gen.chat_session()
    history: list[int] = []
    sizes = []
    rng = np.random.default_rng(3)
    for _ in range(3):
        turn = rng.integers(1, cfg.vocab_size, 60).tolist()
        want = _baseline_turn(cfg, params, history, turn, 40)
        got = list(sess.send(turn, 40, temperature=0.0))
        assert got == want
        history += turn + want
        sizes.append(sess._cache_len)
    assert sizes[0] < 1024, "cache should start run-sized, not max-sized"
    assert sizes[-1] > sizes[0], "cache never grew across 300 tokens"


def test_chat_session_rollback_after_partial_reply():
    """Abandoning a reply mid-stream then rolling back must reproduce the
    stateless baseline over (pre-turn history + turn + partial reply) —
    the chat CLI's Ctrl-C contract."""
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    sess = gen.chat_session()
    first = list(sess.send([5, 6, 7], 6, temperature=0.0))
    pre = sess.history[:]
    turn = [11, 2, 9]
    it = sess.send(turn, 8, temperature=0.0)
    partial = [next(it), next(it)]  # "Ctrl-C" after 2 tokens
    sess.rollback(pre + turn + partial)
    next_turn = [4, 4]
    want = _baseline_turn(cfg, params, pre + turn + partial, next_turn, 6)
    got = list(sess.send(next_turn, 6, temperature=0.0))
    assert got == want
    assert sess.history == pre + turn + partial + next_turn + got


def test_shared_prompt_prefill_matches_per_lane(small_model):
    """Identical prompts take the broadcast fast path (one lane of prefill
    compute); outputs must be token-identical to distinct-prompt batching
    semantics — i.e. to what each lane produces alone under greedy."""
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    single, _ = gen.generate([[7, 3, 9]], 10, temperature=0.0)
    shared, _ = gen.generate([[7, 3, 9]] * 4, 10, temperature=0.0)
    assert shared == [single[0]] * 4
    # stop sequences still apply per lane on the broadcast path
    third = single[0][3 + 2]
    stopped, _ = gen.generate(
        [[7, 3, 9]] * 3, 10, temperature=0.0, stop_sequences=[[third]]
    )
    assert stopped == [single[0][: 3 + 2]] * 3


def test_shared_prefill_auto_disables_when_sampling(small_model):
    """The rule: shared_prefill=None (default) engages the broadcast fast
    path only for greedy decoding.  At temperature > 0 the default must
    produce draw-identical streams to the per-lane path (same RNG seed),
    while an explicit shared_prefill=True still opts the fast path in."""
    cfg, params = small_model
    prompts = [[7, 3, 9]] * 3
    auto = Generator(cfg, params, cache_dtype=jnp.float32, rng_seed=11)
    forced_off = Generator(cfg, params, cache_dtype=jnp.float32, rng_seed=11)
    got_auto, _ = auto.generate(prompts, 8, temperature=0.9, top_k=20)
    got_off, _ = forced_off.generate(
        prompts, 8, temperature=0.9, top_k=20, shared_prefill=False
    )
    assert got_auto == got_off, "sampling default must match per-lane draws"
    # explicit opt-in keeps working (distribution preserved, shapes sane)
    opt_in = Generator(cfg, params, cache_dtype=jnp.float32, rng_seed=11)
    got_on, _ = opt_in.generate(
        prompts, 8, temperature=0.9, top_k=20, shared_prefill=True
    )
    assert len(got_on) == 3 and all(len(o) == 3 + 8 for o in got_on)


def test_shared_prompt_numpy_prompts_and_opt_out(small_model):
    """np.ndarray prompts must batch fine (duck-typed Sequence[int]) and
    shared_prefill=False must force the per-lane prefill path."""
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    arr = np.asarray([7, 3, 9], np.int32)
    fast, _ = gen.generate([arr, arr], 6, temperature=0.0)
    slow, _ = gen.generate([arr, arr], 6, temperature=0.0, shared_prefill=False)
    assert fast == slow


def test_chat_session_quantized_matches_quantized_reprefill(small_model):
    """ChatSession on an int8-quantized generator must equal the quantized
    stateless baseline (same tree, full re-prefill per turn)."""
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int8")
    sess = gen.chat_session()
    base = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int8")
    history: list[int] = []
    for turn in ([5, 6, 7], [11, 2]):
        want = list(base.generate_chat(history + turn, 8, temperature=0.0))
        got = list(sess.send(turn, 8, temperature=0.0))
        assert got == want
        history += turn + want


def test_chat_session_speculative_matches_plain(small_model):
    """Speculative chat turns must be token-identical to plain session
    turns (greedy), across turns so drafting draws on earlier turns, with
    reply lengths capped at max_new."""
    cfg, params = small_model
    plain = Generator(cfg, params, cache_dtype=jnp.float32).chat_session()
    spec = Generator(cfg, params, cache_dtype=jnp.float32).chat_session()
    for turn in ([5, 6, 7, 5, 6], [5, 6, 7, 5], [9, 1, 5, 6]):
        want = list(plain.send(turn, 9, temperature=0.0))
        got = list(spec.send(turn, 9, temperature=0.0, speculative=3))
        assert got == want
        assert len(got) <= 9
        assert spec.history == plain.history


def test_chat_session_speculative_stop_and_guards(small_model):
    cfg, params = small_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    free = list(gen.chat_session().send([9, 9], 10, temperature=0.0))
    stop = [[free[2]]]
    plain = Generator(cfg, params, cache_dtype=jnp.float32).chat_session()
    spec = Generator(cfg, params, cache_dtype=jnp.float32).chat_session()
    want = list(plain.send([9, 9], 10, temperature=0.0, stop_sequences=stop))
    got = list(spec.send([9, 9], 10, temperature=0.0, stop_sequences=stop,
                         speculative=4))
    assert got == want
    # follow-up turn still consistent after a speculative stop-trim
    want2 = list(plain.send([4, 2], 6, temperature=0.0))
    got2 = list(spec.send([4, 2], 6, temperature=0.0, speculative=4))
    assert got2 == want2
    with pytest.raises(ValueError, match="temperature=0"):
        spec.send([1], 4, temperature=0.8, speculative=3)
