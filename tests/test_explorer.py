"""mdi-race dynamic side (`server/explorer.py`): seeded adversarial
interleavings of submit/cancel/drain/stop against a live CPU engine,
with offline-replay token parity as the oracle.

Four layers, matching the static rules' claims in docs/analysis.md:

- explorer mechanics: per-seed determinism, single-installation guard,
  production no-op.
- the acceptance gate: 200 seeded pre-start interleavings whose token
  streams, host-sync counts and compile set are identical to offline
  `engine.run()` — the zero-interference contract under schedule
  pressure (test_server.py pins the quiet-path version).
- live adversarial episodes: mid-run submits, cancels of queued and
  running requests, drains racing arrivals — invariants, not equality.
- detector-detects: a deliberately-broken frontend (unlocked channel
  hand-off) whose lost-update the explorer must catch, proving the
  oracle has teeth; plus the drain-window regression seeds pinning the
  submit-vs-drain fix in `frontend.submit`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.server import (
    FrontendClosedError,
    ScheduleExplorer,
    ServingFrontend,
    run_episode,
)
from mdi_llm_tpu.server import frontend as frontend_mod
from mdi_llm_tpu.utils.profiling import CompileGuard
from tests.test_model import tiny_config

#: the acceptance criterion: >= 200 seeded interleavings, parity-clean
PARITY_SEEDS = range(200)

#: live-engine episodes with cancels and racing drains (invariant suite)
ADVERSARIAL_SEEDS = range(24)

#: drain-window regression fixtures: on the reference box these seeds
#: land arrivals on BOTH sides of the drain flag (some accepted, some
#: 503), the pressure pattern that exposed the original half-admit bug
#: where submit() bumped offered-load stats before the closed check.
#: The invariant asserted below holds wherever each arrival lands, so
#: the test stays sound on hosts whose scheduler times the race
#: differently.
DRAIN_REGRESSION_SEEDS = (20, 21, 24, 26, 31, 39, 44, 45, 56, 58)

#: seeds for the deliberately-broken frontend; at least one must catch
#: the planted lost-update (on the reference box three of six do)
DETECTOR_SEEDS = range(6)


@pytest.fixture(scope="module")
def harness():
    """Shared model + trace + offline oracle (one compile for the module:
    `Generator` caches the compiled serving phases across engines)."""
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    rng = np.random.default_rng(5)
    # three requests, all seatable at once (max_batch=3): host-sync
    # parity then holds for EVERY admission order — verified by running
    # the offline engine over all six permutations — so multi-threaded
    # pre-start submission may scramble the channel freely
    trace = [(f"r{i}", rng.integers(1, cfg.vocab_size, n).tolist(), m)
             for i, (n, m) in enumerate([(3, 8), (7, 12), (5, 6)])]

    def engine():
        return gen.serve(block_size=4, max_batch=3, prefill_chunk=8)

    offline = engine()
    for rid, p, m in trace:
        offline.add_request(rid, p, m)
    want, stats_off = offline.run()
    return {"gen": gen, "cfg": cfg, "trace": trace, "engine": engine,
            "want": want, "stats_off": stats_off}


# ---------------------------------------------------------------------------
# explorer mechanics
# ---------------------------------------------------------------------------


def test_explorer_is_deterministic_per_seed():
    a = ScheduleExplorer(7, record=True)
    b = ScheduleExplorer(7, record=True)
    for tag in ("submit:enter", "engine:collect", "drain:flagged", "t"):
        a.visit(tag)
        b.visit(tag)
    assert a.visits == b.visits == 4
    assert [t for _, t in a.trace] == [t for _, t in b.trace]
    # a different seed draws a different perturbation stream
    c = ScheduleExplorer(8)
    assert c._rng.random() != ScheduleExplorer(7)._rng.random()


def test_single_installation_is_enforced_and_uninstalled_on_exit():
    assert frontend_mod._YIELD is None, "production default: no explorer"
    frontend_mod._yield_point("anything")  # no-op, must not raise
    with ScheduleExplorer(1) as a:
        assert frontend_mod._YIELD == a.visit  # bound methods: ==, not is
        with pytest.raises(RuntimeError):
            ScheduleExplorer(2).install()
    assert frontend_mod._YIELD is None, "context exit uninstalls"


# ---------------------------------------------------------------------------
# the acceptance gate: 200 seeds, parity with offline, zero recompiles
# ---------------------------------------------------------------------------


def test_200_seeded_interleavings_match_offline(harness):
    """Every seed perturbs the submit/wake/drain/stop interleaving and
    the pre-start channel order; token streams, host-sync counts and the
    compile set must not notice."""
    want, stats_off = harness["want"], harness["stats_off"]
    visits = 0
    guard = CompileGuard(label="mdi-race-parity")
    with guard:
        guard.mark_warm()  # the fixture's offline run was the warmup
        for seed in PARITY_SEEDS:
            ep = run_episode(harness["engine"](), harness["trace"], seed,
                             live=False)
            assert ep["errors"] == {}, (seed, ep["errors"])
            assert ep["drained"], f"seed {seed}: drain timed out"
            for rid, p, _m in harness["trace"]:
                h = ep["handles"][rid]
                assert h.result == want[rid], f"seed {seed}: {rid} diverged"
                assert h.tokens == want[rid][len(p):], \
                    f"seed {seed}: {rid} streamed tokens diverged"
            engine = ep["frontend"].engine
            assert engine.stats.host_syncs == stats_off.host_syncs, \
                f"seed {seed}: sync cadence changed under schedule pressure"
            assert ep["frontend"].idle
            assert engine.scheduler.finished == [], \
                "long-lived server must not accumulate finished bookkeeping"
            visits += ep["explorer"].visits
    guard.expect_clean()  # zero post-warmup recompiles across all seeds
    assert visits > len(PARITY_SEEDS) * 10, \
        "the explorer must actually be perturbing the yield points"


# ---------------------------------------------------------------------------
# live adversarial episodes: cancels + racing drains (invariants)
# ---------------------------------------------------------------------------


def test_live_adversarial_episodes_hold_invariants(harness):
    """Submitters race the running engine, a canceller kills queued and
    live requests, and every third seed adds a drain racing the
    arrivals.  Step composition now differs from the replay, so the
    claims are per-request: greedy per-lane decode is composition-
    independent, every handle completes exactly once, rejections are
    deterministic 503s, and the frontend lands idle."""
    want = harness["want"]
    for seed in ADVERSARIAL_SEEDS:
        cancel = ("r1",) if seed % 2 else ("r0", "r2")
        ep = run_episode(harness["engine"](), harness["trace"], seed,
                         live=True, cancel=cancel,
                         drain_race=(seed % 3 == 0))
        assert ep["drained"], f"seed {seed}: drain timed out"
        for rid, p, _m in harness["trace"]:
            if rid in ep["errors"]:
                assert isinstance(ep["errors"][rid], FrontendClosedError), \
                    f"seed {seed}: {rid} rejected with the wrong error"
                assert rid not in ep["handles"]
                continue
            h = ep["handles"][rid]
            assert h.done.is_set(), f"seed {seed}: {rid} never completed"
            assert h.error is None, f"seed {seed}: {rid}: {h.error}"
            if h.cancelled:
                # retired at a step boundary with the tokens so far: a
                # prefix of the offline stream, never garbage
                gen_want = want[rid][len(p):]
                assert h.tokens == gen_want[:len(h.tokens)], \
                    f"seed {seed}: cancelled {rid} streamed wrong tokens"
                assert rid in cancel
            else:
                assert h.result == want[rid], \
                    f"seed {seed}: {rid} diverged (live)"
        front = ep["frontend"]
        assert front.idle, f"seed {seed}: frontend not idle after episode"
        assert front.engine.scheduler.finished == []


# ---------------------------------------------------------------------------
# the drain window: arrivals racing drain() get a deterministic 503
# ---------------------------------------------------------------------------


def test_drained_frontend_rejects_with_zero_side_effects(harness):
    """The deterministic half of the drain-window fix: a submit after
    drain() raises FrontendClosedError BEFORE touching any stats — a
    rejected arrival is not offered load against a closed server."""
    engine = harness["engine"]()
    front = ServingFrontend(engine).start()
    assert front.drain(timeout=60.0)
    with pytest.raises(FrontendClosedError):
        front.submit([1, 2, 3], 4, rid="late")
    assert front._offered == 0, "the 503 path must not count the arrival"
    assert engine.stats.offered_qps == 0.0
    assert "late" not in front._handles
    front.stop()


def test_drain_window_regression_seeds(harness):
    """The racing half, pinned by explorer seeds: with a drain thread
    racing the submitters, every arrival either completes with the
    offline stream or raises FrontendClosedError — never hangs, never
    half-admits — and the offered-load stats count exactly the accepted
    side of the race."""
    want, trace = harness["want"], harness["trace"]
    for seed in DRAIN_REGRESSION_SEEDS:
        ep = run_episode(harness["engine"](), trace, seed,
                         live=True, drain_race=True)
        assert ep["drained"], f"seed {seed}: drain timed out"
        accepted, rejected = set(ep["handles"]), set(ep["errors"])
        assert accepted | rejected == {rid for rid, _, _ in trace}
        assert not (accepted & rejected), "half-admitted request"
        for rid in rejected:
            assert isinstance(ep["errors"][rid], FrontendClosedError)
        for rid, _p, _m in trace:
            if rid in accepted:
                h = ep["handles"][rid]
                assert h.done.is_set() and h.result == want[rid], \
                    f"seed {seed}: accepted {rid} did not finish cleanly"
        assert ep["frontend"]._offered == len(accepted), \
            "rejected arrivals leaked into the offered-load stats"


# ---------------------------------------------------------------------------
# detector-detects: a planted lost-update the explorer must catch
# ---------------------------------------------------------------------------


class RacyFrontend(ServingFrontend):
    """Deliberately broken: the channel hand-off snapshots and clears
    WITHOUT the lock, re-creating the classic check-then-act lost
    update.  A submit whose append lands in the window between `list()`
    and `clear()` is silently dropped — its handle never completes, so
    the episode's drain times out.  The `racy:window` yield point lets
    the explorer hold the window open."""

    def _drain_channel(self):
        frontend_mod._yield_point("engine:drain-channel")
        batch = list(self._channel)  # racy snapshot (no lock)
        frontend_mod._yield_point("racy:window")
        self._channel.clear()  # lost-update window closes here
        for _handle, req in batch:
            self.engine.scheduler.add(req)


def test_explorer_catches_the_planted_lost_update(harness):
    """The explorer suite is only evidence if it can FAIL: against the
    broken frontend, at least one seed must lose a request and surface
    it as a drain timeout + a failed handle."""
    rng = np.random.default_rng(11)
    cfg = harness["cfg"]
    trace = [(f"x{i}", rng.integers(1, cfg.vocab_size, 4).tolist(), 4)
             for i in range(6)]
    detections = []
    for seed in DETECTOR_SEEDS:
        # p_pause=1.0: sleep at EVERY yield point, holding the racy
        # window open for up to 4ms while the submitters keep arriving
        ep = run_episode(harness["engine"](), trace, seed, live=True,
                         frontend_cls=RacyFrontend, drain_timeout_s=0.75,
                         explorer_kwargs={"p_pause": 1.0,
                                          "max_pause_s": 0.004})
        if ep["drained"]:
            continue
        lost = [rid for rid, h in ep["handles"].items()
                if h.error == "frontend stopped before completion"]
        assert lost, f"seed {seed}: undrained but no handle reports the loss"
        detections.append((seed, lost))
    assert detections, (
        "no seed caught the planted lost-update: the explorer has "
        "stopped exercising the channel hand-off race"
    )
