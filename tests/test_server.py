"""Open-system front-end tests (mdi_llm_tpu/server/): the acceptance
contract — greedy token streams THROUGH the server are identical to the
offline engine on the same trace, with zero post-warmup recompiles and
bit-identical host syncs with the front-end attached — plus the fast CPU
HTTP e2e: one SSE completion streamed end to end, 429 backpressure at
the admission bound, graceful drain, and request cancellation."""

import asyncio
import http.client
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.server import (
    FrontendClosedError,
    QueueFullError,
    ServingFrontend,
)
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def served_model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _trace(cfg, lengths=(3, 9, 17, 5), news=(8, 12, 6, 10), seed=5):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in lengths]
    return list(zip([f"r{i}" for i in range(len(prompts))], prompts,
                    list(news)))


def _engine(gen, obs=None, policy=None, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_batch", 3)
    kw.setdefault("prefill_chunk", 8)
    return gen.serve(obs=obs, policy=policy, **kw)


# ---------------------------------------------------------------------------
# the acceptance contract: server == offline engine, zero interference
# ---------------------------------------------------------------------------


def test_frontend_streams_match_offline_engine(served_model):
    """Same trace, all submitted before the engine thread starts: every
    per-request greedy stream, the host-sync count, and the compile set
    are identical to `engine.run()` offline — the front-end adds threads
    AROUND the loop, never inside it.  Holds under every policy (default
    attributes make them all reduce to FCFS ordering)."""
    from mdi_llm_tpu.serving.policy import make_policy
    from mdi_llm_tpu.utils.profiling import CompileGuard

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    trace = _trace(cfg)

    guard = CompileGuard(label="server-overhead")
    with guard:
        offline = _engine(gen)
        for rid, p, m in trace:
            offline.add_request(rid, p, m)
        want, stats_off = offline.run()  # warmup: compiles allowed
        guard.mark_warm()

        for policy_name in (None, "priority", "fair", "deadline"):
            engine = _engine(gen, policy=make_policy(policy_name))
            front = ServingFrontend(engine)
            handles = {rid: front.submit(p, m, rid=rid)
                       for rid, p, m in trace}
            front.start()
            assert front.drain(timeout=300.0), "drain timed out"
            front.stop()
            for rid, p, _m in trace:
                assert handles[rid].result == want[rid], \
                    f"{rid} diverged under policy={policy_name}"
                assert handles[rid].tokens == want[rid][len(p):], \
                    f"{rid} streamed tokens diverged"
            assert engine.stats.host_syncs == stats_off.host_syncs, \
                "the front-end changed the sync cadence"
            assert engine.stats.tokens_generated == stats_off.tokens_generated
    guard.expect_clean()  # zero post-warmup recompiles, server attached


def test_frontend_open_arrivals_complete(served_model):
    """Requests submitted WHILE the engine is running (the open-system
    case) are admitted via the step_hook seam and complete correctly."""
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    trace = _trace(cfg)
    offline = _engine(gen)
    for rid, p, m in trace:
        offline.add_request(rid, p, m)
    want, _ = offline.run()

    engine = _engine(gen)
    front = ServingFrontend(engine).start()
    handles = {}
    for rid, p, m in trace:
        handles[rid] = front.submit(p, m, rid=rid)
        # stagger arrivals into the running engine
        handles[rid].done.wait(timeout=0.02)
    assert front.drain(timeout=300.0)
    front.stop()
    for rid, p, _m in trace:
        assert handles[rid].result == want[rid], f"{rid} diverged (open)"
    assert front.idle


def test_frontend_backpressure_and_stats(served_model):
    """Arrivals past the admission bound raise QueueFullError BEFORE the
    engine thread starts consuming; the rejection lands in the canonical
    stats and the observer counter."""
    from mdi_llm_tpu.obs import ServingObserver

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    obs = ServingObserver()
    engine = _engine(gen, obs=obs)
    front = ServingFrontend(engine, max_queue=2)  # engine NOT started:
    # submissions pile in the channel deterministically
    p = [1, 2, 3]
    front.submit(p, 4, rid="a")
    front.submit(p, 4, rid="b")
    with pytest.raises(QueueFullError):
        front.submit(p, 4, rid="c")
    assert engine.stats.requests_rejected == 1
    assert engine.stats.offered_qps > 0.0
    d = engine.stats.to_dict()
    assert d["requests_rejected"] == 1 and d["offered_qps"] > 0.0
    c = obs.metrics.to_dict()["counters"]
    assert c["serving_requests_rejected_total"] == 1
    # infeasible request: synchronous ValueError (HTTP 400), NOT a 429
    with pytest.raises(ValueError, match="exceeds max_seq_length"):
        front.submit([1] * 100, 100, rid="huge")
    # the two accepted requests still complete
    front.start()
    assert front.drain(timeout=300.0)
    front.stop()
    assert engine.stats.requests_finished == 2


def test_frontend_rejects_after_drain_and_cancel(served_model):
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = _engine(gen)
    front = ServingFrontend(engine).start()
    h = front.submit([5, 6, 7], 12, rid="long")
    assert front.cancel("long") is True
    assert front.cancel("nope") is False
    h.done.wait(timeout=60.0)
    assert h.cancelled and h.result is None
    front.drain(timeout=60.0)
    with pytest.raises(FrontendClosedError):
        front.submit([1, 2], 2, rid="late")
    front.stop()


def test_queue_depth_peak_rides_replay_stats(served_model):
    """queue_depth_peak is an engine-side field: a replay run with more
    requests than slots records the backlog high-water mark."""
    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = _engine(gen, max_batch=1)
    for rid, p, m in _trace(cfg):
        engine.add_request(rid, p, m)
    _results, stats = engine.run()
    assert stats.queue_depth_peak >= 1
    assert stats.to_dict()["queue_depth_peak"] == stats.queue_depth_peak
    assert stats.to_dict()["offered_qps"] == 0.0  # replay: no open loop


# ---------------------------------------------------------------------------
# HTTP e2e (CPU-fast): SSE stream, 429, graceful drain
# ---------------------------------------------------------------------------


def _http(port, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _sse_events(raw: bytes):
    events = []
    for block in raw.decode().split("\n\n"):
        if not block.strip():
            continue
        ev = {}
        for line in block.splitlines():
            k, _, v = line.partition(": ")
            ev[k] = v
        if "data" in ev:
            ev["data"] = json.loads(ev["data"])
        events.append(ev)
    return events


def test_http_server_e2e(served_model):
    """The fast CPU e2e: start the HTTP server on an ephemeral port,
    stream one SSE completion token-for-token against the offline
    reference, exercise 429 backpressure with the engine stalled, then
    drain gracefully — in-flight work finishes, late arrivals get
    refused, and the whole session runs zero post-warmup recompiles."""
    from mdi_llm_tpu.server.http import ServingHTTPServer
    from mdi_llm_tpu.utils.profiling import CompileGuard

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    trace = _trace(cfg)
    offline = _engine(gen)
    for rid, p, m in trace:
        offline.add_request(rid, p, m)
    want, _ = offline.run()  # also the warmup for the compile guard

    guard = CompileGuard(label="http-e2e")
    with guard:
        guard.mark_warm()
        engine = _engine(gen)
        front = ServingFrontend(engine, max_queue=16)
        srv = ServingHTTPServer(front, port=0, drain_timeout_s=120.0)
        results = {}

        async def drive():
            await srv.start()
            loop = asyncio.get_running_loop()

            def call(*a, **kw):
                return loop.run_in_executor(None, lambda: _http(*a, **kw))

            # health up
            st, _h, body = await call(srv.port, "GET", "/healthz")
            results["health"] = (st, json.loads(body))
            # one SSE stream
            rid, prompt, new = trace[0]
            st, hdrs, raw = await call(
                srv.port, "POST", "/v1/completions",
                json.dumps({"prompt": prompt, "max_tokens": new,
                            "stream": True}),
            )
            results["sse"] = (st, hdrs, _sse_events(raw))
            # non-streaming JSON
            rid2, prompt2, new2 = trace[1]
            st, _h, body = await call(
                srv.port, "POST", "/v1/completions",
                json.dumps({"prompt": prompt2, "max_tokens": new2}),
            )
            results["json"] = (st, json.loads(body))
            # malformed body → 400
            st, _h, body = await call(
                srv.port, "POST", "/v1/completions", "{not json")
            results["bad"] = (st, json.loads(body))
            # drain: in-flight finishes, server refuses new work and the
            # listener closes
            st, _h, _b = await call(srv.port, "GET", "/v1/stats")
            results["stats_status"] = st
            await srv.shutdown()

        asyncio.run(drive())
    guard.expect_clean()  # zero post-warmup recompiles, HTTP attached

    st, health = results["health"]
    assert st == 200 and health["status"] == "ok"
    assert health["queue_bound"] == 16

    st, hdrs, events = results["sse"]
    assert st == 200
    assert hdrs.get("Content-Type") == "text/event-stream"
    token_evs = [e for e in events if e.get("event") == "token"]
    done_evs = [e for e in events if e.get("event") == "done"]
    rid, prompt, new = trace[0]
    assert [e["data"]["token"] for e in token_evs] == want[rid][len(prompt):]
    assert len(done_evs) == 1
    assert done_evs[0]["data"]["tokens"] == want[rid][len(prompt):]
    assert done_evs[0]["data"]["n_generated"] == len(want[rid]) - len(prompt)

    st, body = results["json"]
    rid2, prompt2, _new2 = trace[1]
    assert st == 200 and body["tokens"] == want[rid2][len(prompt2):]

    assert results["bad"][0] == 400
    assert results["stats_status"] == 200
    # post-shutdown: engine thread stopped, nothing leaked
    assert front.idle


def test_http_backpressure_429(served_model):
    """With the engine thread NOT consuming, arrivals past the bound get
    429 + Retry-After while earlier ones are still queued."""
    from mdi_llm_tpu.server.http import ServingHTTPServer

    cfg, params = served_model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = _engine(gen)
    front = ServingFrontend(engine, max_queue=1)
    # start ONLY the HTTP listener — not the engine thread — so the
    # first request parks in the channel deterministically
    srv = ServingHTTPServer(front, port=0)
    results = {}

    async def drive():
        # bypass srv.start()'s frontend auto-start: bind the listener
        srv._loop = asyncio.get_running_loop()
        srv._server = await asyncio.start_server(
            srv._handle_conn, srv.host, srv.port)
        srv.port = srv._server.sockets[0].getsockname()[1]
        loop = asyncio.get_running_loop()

        def call(*a, **kw):
            return loop.run_in_executor(None, lambda: _http(*a, **kw))

        # park one request in the channel directly (an HTTP submission
        # would block its connection waiting on a completion the stopped
        # engine never produces)
        front.submit([1, 2, 3], 4, rid="parked")
        st, hdrs, body = await call(
            srv.port, "POST", "/v1/completions",
            json.dumps({"prompt": [4, 5, 6], "max_tokens": 4}),
        )
        results["second"] = (st, hdrs, json.loads(body))
        srv._server.close()
        await srv._server.wait_closed()

    asyncio.run(drive())
    st, hdrs, body = results["second"]
    assert st == 429
    assert hdrs.get("Retry-After") == "1"
    assert "admission queue full" in body["error"]
    assert engine.stats.requests_rejected == 1


# ---------------------------------------------------------------------------
# CLI surface + docs coverage
# ---------------------------------------------------------------------------


def test_server_cli_help_covers_new_flags():
    from mdi_llm_tpu.cli.serve import build_parser as serve_parser
    from mdi_llm_tpu.cli.server import build_parser as server_parser

    server_help = " ".join(server_parser().format_help().split())
    for flag in ("--host", "--port", "--admission-queue", "--drain-timeout",
                 "--policy"):
        assert flag in server_help, f"{flag} missing from mdi-server --help"
    assert "429" in server_help  # backpressure semantics are documented
    serve_help = " ".join(serve_parser().format_help().split())
    assert "--policy" in serve_help
    for policy in ("fcfs", "priority", "fair", "deadline"):
        assert policy in serve_help
    # rejection-sampled speculative knobs surface on BOTH front-ends
    # (mdi-server inherits serve's parser)
    for flag in ("--spec-k", "--temperature", "--top-k", "--top-p",
                 "--draft-model"):
        assert flag in serve_help, f"{flag} missing from mdi-serve --help"
        assert flag in server_help, f"{flag} missing from mdi-server --help"


def test_server_console_script_registered():
    from pathlib import Path

    # plain-text check (this interpreter build ships no tomllib)
    text = (Path(__file__).resolve().parents[1] / "pyproject.toml").read_text()
    assert 'mdi-server = "mdi_llm_tpu.cli.server:main"' in text


def test_serving_docs_cover_http_api():
    from pathlib import Path

    doc = (Path(__file__).resolve().parents[1] / "docs" / "serving.md")
    text = doc.read_text()
    for needle in ("POST /v1/completions", "event: token", "event: done",
                   "429", "Graceful drain", "serve-open",
                   "bad-server-config", "ttft_slo_ms"):
        assert needle in text, f"docs/serving.md missing {needle!r}"
