"""Serving roofline math (`mdi_llm_tpu/obs/roofline.py`): hand-computed
decode FLOPs/bytes for two registry models (fp and int8 KV), the device
peak table, MFU/MBU derivation, and THE tripwire — analytic FLOPs must
agree with the XLA compiler's own `cost_analysis` on a real serving
executable within the pinned tolerance, so the hand model can never
silently rot away from what the executables compute.
"""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config, ServingConfig, dtype_bytes
from mdi_llm_tpu.obs import roofline as rf

# ---------------------------------------------------------------------------
# hand-computed FLOPs: independent component-wise derivations for two
# registry models (never via estimate_params — that is what's under test)
# ---------------------------------------------------------------------------


def test_decode_flops_pythia_14m_hand_computed():
    """pythia-14m: GPT-NeoX family — parallel residual, bias=True,
    LayerNorm, GptNeoxMLP (2 matmuls, 4x intermediate), untied head."""
    cfg = Config.from_name("pythia-14m")
    assert cfg.mlp_class_name == "GptNeoxMLP" and cfg.bias
    assert not cfg.tie_embeddings
    D, L, V = cfg.n_embd, cfg.n_layer, cfg.padded_vocab_size
    I, hs, H = cfg.intermediate_size, cfg.head_size, cfg.n_head
    # per-layer linear params: fused QKV (+bias), attn out proj (+bias),
    # MLP up/down (+biases), two LayerNorms (weight+bias each)
    qkv = D * cfg.qkv_size + cfg.qkv_size
    attn_out = hs * H * D + D
    mlp = D * I + I + I * D + D
    norms = 2 * D * 2
    # non-gather params: L layers + final norm(+bias counts D via the
    # trailing +D in estimate_params) + the lm_head matmul (V*D, untied)
    lin = L * (qkv + attn_out + mlp + norms) + D + V * D
    S = 96
    expected = 2.0 * lin + 4.0 * L * H * hs * S
    assert rf.decode_flops_per_token(cfg, S) == pytest.approx(expected)


def test_decode_flops_tinyllama_hand_computed():
    """tiny-llama-1.1b: Llama family — no bias, RMSNorm, LLaMAMLP
    (3 matmuls), GQA (4 query groups), untied head."""
    cfg = Config.from_name("tiny-llama-1.1b")
    assert cfg.mlp_class_name == "LLaMAMLP" and not cfg.bias
    D, L, V = cfg.n_embd, cfg.n_layer, cfg.padded_vocab_size
    I, hs, H, G = cfg.intermediate_size, cfg.head_size, cfg.n_head, cfg.n_query_groups
    q_per_kv = H // G
    qkv = D * (q_per_kv + 2) * hs * G  # fused QKV at GQA width
    attn_out = hs * H * D
    mlp = 3 * D * I  # gate + up + down
    norms = 2 * D  # two RMSNorm weights per layer
    lin = L * (qkv + attn_out + mlp + norms) + D + V * D
    S = 544
    expected = 2.0 * lin + 4.0 * L * H * hs * S
    assert rf.decode_flops_per_token(cfg, S) == pytest.approx(expected)
    # and the inference estimate is exactly one third of the training
    # 6N + 12·L·H·hs·T ... minus the gather-only embedding term
    from mdi_llm_tpu.training import estimate_flops_per_token

    train = estimate_flops_per_token(cfg, S)
    assert rf.decode_flops_per_token(cfg, S) == pytest.approx(
        train / 3.0 - 2.0 * V * D
    )


def test_prefill_flops_use_causal_mean_context():
    cfg = Config.from_name("pythia-14m")
    assert rf.prefill_flops_per_token(cfg, 100) == pytest.approx(
        rf.decode_flops_per_token(cfg, 50)
    )


# ---------------------------------------------------------------------------
# hand-computed HBM bytes: fp vs int8 paged pools at one block geometry
# ---------------------------------------------------------------------------


def test_decode_hbm_bytes_fp_vs_int8_hand_computed():
    cfg = Config.from_name("pythia-14m")
    L, G, hs = cfg.n_layer, cfg.n_query_groups, cfg.head_size
    BS, S, B, Wb = 16, 100, 8, 10_000_000
    n_blocks = math.ceil(S / BS)  # 7 whole blocks cover 100 tokens
    fp_block = 2 * L * BS * G * hs * 2  # k+v, bf16
    q8_block = 2 * L * BS * G * hs * 1 + 2 * L * G * 4  # int8 + f32 scales

    got_fp = rf.decode_hbm_bytes_per_token(
        cfg, ServingConfig(block_size=BS), B, S, Wb
    )
    assert got_fp["kv_read_bytes"] == n_blocks * fp_block
    assert got_fp["kv_write_bytes"] == pytest.approx(2 * L * G * hs * 2)
    assert got_fp["weight_bytes"] == pytest.approx(Wb / B)
    assert got_fp["total_bytes"] == pytest.approx(
        Wb / B + n_blocks * fp_block + 2 * L * G * hs * 2
    )

    got_q8 = rf.decode_hbm_bytes_per_token(
        cfg, ServingConfig(block_size=BS, kv_dtype="int8"), B, S, Wb
    )
    assert got_q8["kv_dtype"] == "int8"
    assert got_q8["kv_read_bytes"] == n_blocks * q8_block
    # the int8 pool's MBU credit: roughly half the KV read traffic
    assert got_q8["kv_read_bytes"] < 0.52 * got_fp["kv_read_bytes"]

    # dense-cache path (serving=None): contiguous bytes, no block rounding
    got_dense = rf.decode_hbm_bytes_per_token(cfg, None, B, S, Wb)
    assert got_dense["kv_read_bytes"] == 2 * L * G * hs * S * 2


def test_param_bytes_counts_storage_width():
    # a mixed tree: f32 + int8 leaves count at their stored widths
    tree = {
        "w": jnp.zeros((4, 8), jnp.float32),
        "q": jnp.zeros((16,), jnp.int8),
    }
    assert rf.param_bytes(tree) == 4 * 8 * 4 + 16
    cfg = Config.from_name("pythia-14m")
    assert cfg.estimate_param_bytes("float32") == cfg.estimate_params() * 4
    assert cfg.estimate_param_bytes("bfloat16") == cfg.estimate_params() * 2


# ---------------------------------------------------------------------------
# the device-peak table
# ---------------------------------------------------------------------------


def test_device_peaks_matches_known_kinds():
    assert rf.device_peaks("TPU v4") is rf.DEVICE_PEAKS["v4"]
    assert rf.device_peaks("TPU v5 lite") is rf.DEVICE_PEAKS["v5e"]
    assert rf.device_peaks("TPU v5e") is rf.DEVICE_PEAKS["v5e"]
    assert rf.device_peaks("TPU v5p") is rf.DEVICE_PEAKS["v5p"]
    assert rf.device_peaks("TPU v5") is rf.DEVICE_PEAKS["v5p"]  # bare v5 = p
    assert rf.device_peaks("TPU v6 lite") is rf.DEVICE_PEAKS["v6e"]
    assert rf.device_peaks("TPU v6e") is rf.DEVICE_PEAKS["v6e"]
    # unknown kinds MUST map to None, never a guessed chip
    for kind in ("cpu", "NVIDIA H100", "", None):
        assert rf.device_peaks(kind) is None
    # the table itself is sane: every row has both peaks, positive
    for row in rf.DEVICE_PEAKS.values():
        assert row["bf16_tflops"] > 0 and row["hbm_gbps"] > 0


def test_serving_roofline_mfu_mbu_derivation():
    cfg = Config.from_name("pythia-14m")
    sv = ServingConfig(block_size=16)
    tps, S, B, Wb = 1000.0, 256, 8, 28_000_000
    out = rf.serving_roofline(
        cfg, sv, tokens_per_s=tps, context=S, batch=B, weight_bytes=Wb,
        device_kind="TPU v5 lite", n_chips=2,
    )
    flops_tok = rf.decode_flops_per_token(cfg, S)
    bytes_tok = rf.decode_hbm_bytes_per_token(cfg, sv, B, S, Wb)["total_bytes"]
    assert out["mfu"] == pytest.approx(tps * flops_tok / (2 * 197e12))
    assert out["mbu"] == pytest.approx(tps * bytes_tok / (2 * 819e9))
    assert out["achieved_tflops_per_s"] == pytest.approx(tps * flops_tok / 1e12)
    json.dumps(out)  # the detail.device.roofline block must be JSON-clean

    # unknown device: utilization is null, absolutes still report
    out_cpu = rf.serving_roofline(
        cfg, sv, tokens_per_s=tps, context=S, batch=B, weight_bytes=Wb,
        device_kind="cpu",
    )
    assert out_cpu["mfu"] is None and out_cpu["mbu"] is None
    assert out_cpu["achieved_tflops_per_s"] > 0


# ---------------------------------------------------------------------------
# THE tripwire: analytic FLOPs vs XLA cost_analysis on a real executable
# ---------------------------------------------------------------------------


def _cost_analysis_available() -> bool:
    try:
        f = jax.jit(lambda x: x @ x)
        ca = f.lower(
            jax.ShapeDtypeStruct((8, 8), jnp.float32)
        ).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return bool(ca) and ca.get("flops") is not None
    except Exception:
        return False


@pytest.mark.skipif(
    not _cost_analysis_available(),
    reason="backend does not expose AOT cost_analysis flops",
)
def test_analytic_flops_agree_with_xla_cost_analysis():
    """Introspect the REAL serving mixed executable for a registry model
    and pin analytic-vs-XLA agreement within `XLA_AGREEMENT_RTOL` — the
    acceptance criterion that keeps `decode_flops_per_token` honest."""
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.obs.device import introspect

    cfg = Config.from_name("pythia-14m")
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    gen = Generator(cfg, params, max_seq_length=128, cache_dtype=jnp.float32)
    engine = gen.serve(block_size=8, max_batch=2, prefill_chunk=32)
    B, T = 2, engine.token_budget
    fn = engine._mixed_fn(B, T)
    args = (
        gen.params, np.zeros((1, T), np.int32), engine._kv, engine._tables,
        np.zeros((1, T), np.int32), np.zeros((T,), np.int32),
        np.zeros((B,), np.int32), np.zeros((B,), np.int32),
        np.zeros((B,), np.int32), gen.key, engine._t_op, engine._p_op,
    )
    rep = introspect(
        fn, args, {"mode": engine._sample_mode, "top_k": engine.cfg.top_k},
        label="mixed", key=(B, T),
    )
    assert rep.error is None, rep.error
    assert rep.flops and rep.flops > 0
    assert rep.argument_bytes and rep.argument_bytes > 0
    # every packed token attends the full table window (the fallback
    # gathers every covered block) — the shape the analytic model costs
    window = engine.max_blocks_per_seq * engine.pool.block_size
    cross = rf.crosscheck_flops(
        rep, T * rf.decode_flops_per_token(cfg, window)
    )
    assert cross["agrees"] is True, cross
    assert cross["rel_err"] < rf.XLA_AGREEMENT_RTOL
    json.dumps(cross)


@pytest.mark.skipif(
    not _cost_analysis_available(),
    reason="backend does not expose AOT cost_analysis flops",
)
def test_int8_pool_executable_introspects_and_agrees():
    """The quantized pool's executable (dict pytree of int8 blocks + f32
    scales) must lower abstractly too, and its FLOPs stay within the same
    tolerance — the in-kernel dequant is elementwise noise next to the
    matmul terms."""
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.obs.device import introspect

    cfg = Config.from_name("pythia-14m")
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    gen = Generator(cfg, params, max_seq_length=64, cache_dtype=jnp.float32)
    engine = gen.serve(
        serving=ServingConfig(block_size=8, max_batch=2, kv_dtype="int8")
    )
    B = 2
    fn = engine._decode_fn(B)
    args = (
        gen.params, np.zeros((B,), np.int32), engine._kv, engine._tables,
        np.zeros((B,), np.int32), gen.key, engine._t_op, engine._p_op,
    )
    rep = introspect(
        fn, args, {"mode": engine._sample_mode, "top_k": engine.cfg.top_k},
        label="decode", key=(B,), variant="int8",
    )
    assert rep.error is None, rep.error
    window = engine.max_blocks_per_seq * engine.pool.block_size
    cross = rf.crosscheck_flops(
        rep, B * rf.decode_flops_per_token(cfg, window)
    )
    assert cross["agrees"] is True, cross


def test_crosscheck_handles_missing_flops():
    from mdi_llm_tpu.obs.device import ExecutableReport

    rep = ExecutableReport(label="mixed", key=(1,), error="no AOT API")
    out = rf.crosscheck_flops(rep, 1e9)
    assert out["agrees"] is None and out["rel_err"] is None
