"""Expert parallelism (parallel/expert.py): golden parity vs the dense MoE.

The dense path (`transformer.moe_forward`) is itself pinned against the
reference `LLaMAMoE` semantics (`/root/reference/src/sub/model.py:823-853`)
by test_model/test_quant; here the token-dispatch all_to_all variant must
reproduce it:

- layer-level parity on an 8-device `ep` mesh (exact capacity → no drops),
  for E=8/k=2 (Mixtral-shaped) and E=4/k=1 (switch-style);
- capacity semantics: a cf-bounded buffer drops overflow assignments and
  only then (checked against a host-side reference dropper);
- full-model decode parity through `transformer.forward(moe_impl=...)`;
- Generator-level greedy decode parity (ep mesh vs single device).
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.models.transformer import init_params, moe_forward
from mdi_llm_tpu.parallel.expert import ep_moe_forward, expert_capacity
from mdi_llm_tpu.parallel.mesh import make_mesh


def moe_config(E=8, k=2, **kw):
    base = dict(
        name="ep-test",
        block_size=64,
        vocab_size=128,
        padded_vocab_size=128,
        n_layer=2,
        n_head=4,
        n_embd=32,
        n_query_groups=4,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMoE",
        n_expert=E,
        n_expert_per_token=k,
        intermediate_size=48,
    )
    base.update(kw)
    return Config(**base)


def moe_layer_params(cfg, seed=0):
    """One layer's mlp param dict (no leading layer axis), f32."""
    rng = np.random.default_rng(seed)
    E, D, I = cfg.n_expert, cfg.n_embd, cfg.intermediate_size

    def w(*shape):
        return jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 0.05)

    return {
        "gate": {"weight": w(E, D)},
        "experts": {
            "fc_1": {"weight": w(E, I, D)},
            "fc_2": {"weight": w(E, I, D)},
            "proj": {"weight": w(E, D, I)},
        },
    }


@pytest.mark.parametrize("E,k,ep", [(8, 2, 8), (8, 2, 4), (4, 1, 2)])
def test_layer_parity_exact_capacity(devices, E, k, ep):
    cfg = moe_config(E=E, k=k)
    p = moe_layer_params(cfg)
    mesh = make_mesh({"ep": ep}, devices[:ep])
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((3, 5, cfg.n_embd)).astype(np.float32))

    dense = moe_forward(cfg, p, x)
    sparse = ep_moe_forward(cfg, p, x, mesh, capacity_factor=None)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense), atol=2e-5)


def test_layer_parity_under_jit(devices):
    cfg = moe_config()
    p = moe_layer_params(cfg)
    mesh = make_mesh({"ep": 8}, devices)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 8, cfg.n_embd)).astype(np.float32)
    )
    fn = jax.jit(lambda pp, xx: ep_moe_forward(cfg, pp, xx, mesh))
    np.testing.assert_allclose(
        np.asarray(fn(p, x)), np.asarray(moe_forward(cfg, p, x)), atol=2e-5
    )


def _host_reference_with_drops(cfg, p, x, ep, C):
    """NumPy re-implementation of capacity-bounded routing: same top-k and
    renormalization as the dense path, but assignments past C per
    (expert, source-device) contribute nothing."""
    B, T, D = x.shape
    N = B * T
    n_loc = math.ceil(N / ep)
    xf = np.zeros((n_loc * ep, D), np.float32)
    xf[:N] = np.asarray(x, np.float32).reshape(N, D)
    gate = np.asarray(p["gate"]["weight"], np.float32)
    out = np.zeros_like(xf)
    for d in range(ep):
        counts = {e: 0 for e in range(cfg.n_expert)}
        for i in range(d * n_loc, (d + 1) * n_loc):
            if i >= N:
                continue
            logits = gate @ xf[i]
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            top = np.argsort(-probs, kind="stable")[: cfg.n_expert_per_token]
            w = probs[top] / probs[top].sum()
            for e, wv in zip(top, w):
                if counts[e] >= C:
                    continue
                counts[e] += 1
                fc1 = np.asarray(p["experts"]["fc_1"]["weight"][e], np.float32)
                fc2 = np.asarray(p["experts"]["fc_2"]["weight"][e], np.float32)
                pr = np.asarray(p["experts"]["proj"]["weight"][e], np.float32)
                h1 = fc1 @ xf[i]
                h = h1 / (1 + np.exp(-h1)) * (fc2 @ xf[i])
                out[i] += wv * (pr @ h)
    return out[:N].reshape(B, T, D)


def test_capacity_drops_match_host_reference(devices):
    cfg = moe_config(E=4, k=2)
    p = moe_layer_params(cfg, seed=3)
    ep = 2
    mesh = make_mesh({"ep": ep}, devices[:ep])
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((1, 12, cfg.n_embd)).astype(np.float32))
    cf = 0.5  # force drops: capacity < assignments for popular experts
    C = expert_capacity(cfg, 6, cf)
    assert C < 6  # the test is vacuous unless the buffer can overflow

    got = ep_moe_forward(cfg, p, x, mesh, capacity_factor=cf)
    want = _host_reference_with_drops(cfg, p, x, ep, C)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4)
    # and the dropped assignments must make it differ from the dense output
    dense = moe_forward(cfg, p, x)
    assert float(jnp.abs(dense - got).max()) > 1e-4


def test_full_forward_with_moe_impl(devices):
    """transformer.forward(moe_impl=ep_moe_forward) ≡ dense forward."""
    cfg = moe_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh({"ep": 4}, devices[:4])
    tokens = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % cfg.vocab_size
    pos0 = jnp.zeros((2,), jnp.int32)

    dense_logits, _ = transformer.forward(cfg, params, tokens, pos0)
    impl = partial(ep_moe_forward, mesh=mesh)
    ep_logits, _ = transformer.forward(cfg, params, tokens, pos0, moe_impl=impl)
    np.testing.assert_allclose(
        np.asarray(ep_logits), np.asarray(dense_logits), atol=3e-5
    )


@pytest.mark.parametrize("mode,wkey", [
    ("int8", "weight_q"), ("w8a8", "weight_q8"), ("int4", "weight_q4"),
])
def test_generator_ep_quantized_decode_parity(devices, mode, wkey):
    """Quantized MoE decode over an ep mesh (Mixtral-int8/int4 serving
    shapes) equals single-device quantized decode: the name-agnostic expert
    placement + quantized_einsum dispatch inside the shard_map, for every
    storage mode the Generator guard admits over ep."""
    from mdi_llm_tpu.generation import Generator

    cfg = moe_config()
    params = init_params(cfg, jax.random.PRNGKey(2))
    prompts = [[3, 7, 11, 2], [5, 1, 9, 13, 4]]

    ref, _ = Generator(cfg, params, max_seq_length=64, quantize=mode).generate(
        prompts, 10, temperature=0.0
    )
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    eng = Generator(
        cfg, params, max_seq_length=64, quantize=mode, mesh=mesh
    )
    assert eng._moe_impl is not None
    got, _ = eng.generate(prompts, 10, temperature=0.0)
    assert got == ref
    # expert leaves really are sharded over ep (not replicated)
    wq = eng.params["blocks"]["mlp"]["experts"]["fc_1"][wkey]
    assert "ep" in str(wq.sharding.spec)


def test_generator_ep_prequantized_tree(devices):
    """A pre-quantized tree (quantize='none' flag, weight_q leaves) loads
    onto an ep mesh — the structural guard must allow the MoE exception."""
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.ops.quant import quantize_params

    cfg = moe_config()
    qp = quantize_params(init_params(cfg, jax.random.PRNGKey(3)))
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    eng = Generator(cfg, qp, max_seq_length=64, mesh=mesh)
    outs, _ = eng.generate([[2, 4, 6]], 6, temperature=0.0)
    assert len(outs[0]) == 9
    # quantized + tp now shards through the adapted Megatron specs (the
    # pre-r5 reject is gone): same tokens, experts sharded over tp
    ref, _ = Generator(cfg, qp, max_seq_length=64).generate(
        [[2, 4, 6]], 6, temperature=0.0
    )
    tp_eng = Generator(
        cfg, qp, max_seq_length=64,
        mesh=make_mesh({"tp": 2}, jax.devices()[:2]),
    )
    got, _ = tp_eng.generate([[2, 4, 6]], 6, temperature=0.0)
    assert got == ref


def test_generator_ep_decode_parity(devices):
    """Greedy decode through Generator on an ep mesh equals single-device."""
    from mdi_llm_tpu.generation import Generator

    cfg = moe_config()
    params = init_params(cfg, jax.random.PRNGKey(1))
    prompts = [[3, 7, 11, 2], [5, 1, 9, 13, 4]]

    ref, _ = Generator(cfg, params, max_seq_length=64).generate(
        prompts, 12, temperature=0.0
    )
    mesh = make_mesh({"ep": 8}, devices)
    eng = Generator(cfg, params, max_seq_length=64, mesh=mesh)
    # the ep mesh must actually engage token dispatch, not dense fallback
    assert eng._moe_impl is not None
    got, _ = eng.generate(prompts, 12, temperature=0.0)
    assert got == ref
    # and the compiled decode step must contain the all_to_all exchange
    import jax as _jax

    decode = eng._decode_fn(2)
    kv = transformer.init_kv_cache(cfg, 2, 64)
    lowered = decode.lower(
        eng.params, jnp.zeros((2, 1), jnp.int32), kv,
        jnp.zeros((2,), jnp.int32), _jax.random.PRNGKey(0),
        jnp.float32(1.0), jnp.float32(1.0), mode="greedy", top_k=None,
    )
    txt = lowered.as_text()
    assert "all_to_all" in txt or "all-to-all" in txt


# ---------------------------------------------------------------------------
# Expert-parallel TRAINING (VERDICT r4 #4): aux loss + differentiable dispatch
# ---------------------------------------------------------------------------


def test_moe_aux_loss_dense_vs_ep(devices):
    """The load-balancing aux loss psum-reduced across the ep mesh equals the
    dense single-device formula, and behaves (≈1 uniform, >1 skewed)."""
    cfg = moe_config(E=4, k=2)
    p = moe_layer_params(cfg, seed=5)
    mesh = make_mesh({"ep": 4}, devices[:4])
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((2, 8, cfg.n_embd)).astype(np.float32)
    )

    yd, aux_d = moe_forward(cfg, p, x, with_aux=True)
    ye, aux_e = ep_moe_forward(cfg, p, x, mesh, with_aux=True)
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yd), atol=2e-5)
    np.testing.assert_allclose(float(aux_e), float(aux_d), rtol=1e-5)

    # deterministically skewed routing (identical tokens, gate favoring
    # expert 0) must score worse than the near-uniform random case
    p_skew = dict(p)
    gate = np.zeros_like(np.asarray(p["gate"]["weight"]))
    gate[0] = 1.0
    p_skew["gate"] = {"weight": jnp.asarray(gate)}
    x_const = jnp.full((2, 8, cfg.n_embd), 0.1, jnp.float32)
    _, aux_skew = moe_forward(cfg, p_skew, x_const, with_aux=True)
    assert float(aux_skew) > 1.5 > float(aux_d) >= 0.99  # near-uniform ≈ 1


def test_ep_training_grad_parity(devices):
    """Grads of the CE loss through token-dispatch EP (exact capacity) match
    the dense formulation — all_to_all and the scatter/gather transpose
    correctly.  Checked with and without the aux term."""
    from mdi_llm_tpu.training import cross_entropy_loss

    cfg = moe_config(E=4, k=2, n_layer=2)
    params = init_params(cfg, jax.random.PRNGKey(7))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), params)
    mesh = make_mesh({"ep": 4}, devices[:4])
    rng = np.random.default_rng(8)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    tgts = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    impl = partial(ep_moe_forward, mesh=mesh, capacity_factor=None)

    gate_grads = {}
    for aux_w in (0.0, 0.05):
        ld, gd = jax.value_and_grad(
            lambda p: cross_entropy_loss(
                cfg, p, toks, tgts, remat=False, moe_aux_weight=aux_w
            )
        )(params)
        le, ge = jax.value_and_grad(
            lambda p: cross_entropy_loss(
                cfg, p, toks, tgts, remat=False, moe_impl=impl,
                moe_aux_weight=aux_w,
            )
        )(params)
        np.testing.assert_allclose(float(le), float(ld), rtol=2e-5)
        flat_d = jax.tree_util.tree_leaves_with_path(gd)
        flat_e = {
            jax.tree_util.keystr(k): v
            for k, v in jax.tree_util.tree_leaves_with_path(ge)
        }
        for k, vd in flat_d:
            ve = flat_e[jax.tree_util.keystr(k)]
            np.testing.assert_allclose(
                np.asarray(ve), np.asarray(vd), atol=5e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(k)} aux_w={aux_w}",
            )
        gate_grads[aux_w] = np.asarray(ge["blocks"]["mlp"]["gate"]["weight"])
    # the aux term must actually move the gate gradient (a vacuous check
    # against zero would pass even if aux were constant w.r.t. the gate,
    # since CE alone already reaches the gate through the top-k weights)
    assert float(np.abs(gate_grads[0.05] - gate_grads[0.0]).max()) > 1e-7


def test_trainer_ep_step_and_jaxpr(devices):
    """Trainer on a (dp, ep) mesh: experts sharded over ep, one optimizer
    step runs, and the compiled step contains the all_to_all dispatch."""
    from mdi_llm_tpu.training import Trainer, TrainingConfig

    cfg = moe_config(E=8, k=2, n_layer=2)
    mesh = make_mesh({"dp": 2, "ep": 4}, devices)
    tc = TrainingConfig(
        batch_size=4, block_size=16, max_iters=2, dtype="float32",
        warmup_iters=1, eval_iters=1, moe_aux_weight=0.01,
    )
    tr = Trainer(cfg, tc, mesh=mesh)
    assert tr._moe_impl is not None
    fc1 = tr.params["blocks"]["mlp"]["experts"]["fc_1"]["weight"]
    assert "ep" in str(fc1.sharding.spec)

    rng = np.random.default_rng(9)
    xs = rng.integers(0, cfg.vocab_size, (1, 4, 16)).astype(np.int32)
    ys = rng.integers(0, cfg.vocab_size, (1, 4, 16)).astype(np.int32)
    lowered = tr._step.lower(
        tr.params, tr.opt_state, jnp.asarray(xs), jnp.asarray(ys)
    ).as_text()
    assert "all_to_all" in lowered or "all-to-all" in lowered

    losses = [tr.train_step(xs, ys) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # same batch: the optimizer makes progress


def test_trainer_ep_requires_moe():
    from mdi_llm_tpu.training import Trainer, TrainingConfig
    from mdi_llm_tpu.config import Config

    cfg = Config.from_name("pythia-14m")
    mesh = make_mesh({"ep": 4}, jax.devices()[:4])
    with pytest.raises(ValueError, match="MoE config"):
        Trainer(cfg, TrainingConfig(batch_size=2, block_size=16), mesh=mesh)


def test_ep_dispatch_splits_tokens_over_dp(devices):
    """With dp_axis, tokens split over dp×ep (each device routes N/(dp·ep))
    and the result + aux still match the dense formulation — and so do the
    GRADIENTS (the configuration Trainer actually builds on a (dp, ep)
    mesh; a wrong psum factor in the shard_map transpose over dp would
    pass the forward checks and still let training loss decrease)."""
    from mdi_llm_tpu.training import cross_entropy_loss

    cfg = moe_config(E=4, k=2, n_layer=2)
    p = moe_layer_params(cfg, seed=11)
    mesh = make_mesh({"dp": 2, "ep": 4}, devices)
    x = jnp.asarray(
        np.random.default_rng(12).standard_normal((2, 8, cfg.n_embd)).astype(np.float32)
    )
    yd, aux_d = moe_forward(cfg, p, x, with_aux=True)
    ye, aux_e = ep_moe_forward(cfg, p, x, mesh, with_aux=True, dp_axis="dp")
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yd), atol=2e-5)
    np.testing.assert_allclose(float(aux_e), float(aux_d), rtol=1e-5)

    params = init_params(cfg, jax.random.PRNGKey(13))
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), params)
    rng = np.random.default_rng(14)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    tgts = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    impl = partial(
        ep_moe_forward, mesh=mesh, capacity_factor=None, dp_axis="dp"
    )
    ld, gd = jax.value_and_grad(
        lambda q: cross_entropy_loss(
            cfg, q, toks, tgts, remat=False, moe_aux_weight=0.05
        )
    )(params)
    le, ge = jax.value_and_grad(
        lambda q: cross_entropy_loss(
            cfg, q, toks, tgts, remat=False, moe_impl=impl, moe_aux_weight=0.05
        )
    )(params)
    np.testing.assert_allclose(float(le), float(ld), rtol=2e-5)
    for (k1, vd), (k2, ve) in zip(
        jax.tree_util.tree_leaves_with_path(gd),
        jax.tree_util.tree_leaves_with_path(ge),
    ):
        assert jax.tree_util.keystr(k1) == jax.tree_util.keystr(k2)
        np.testing.assert_allclose(
            np.asarray(ve), np.asarray(vd), atol=5e-5,
            err_msg=f"dp-split grad mismatch at {jax.tree_util.keystr(k1)}",
        )


def test_sp_moe_training_aux_matches_single_device(devices):
    """MoE training on a (dp, sp) mesh applies the aux loss EXACTLY (router
    stats psum across the mesh before the formula): params after 3 steps
    match unmeshed training with the same aux weight."""
    from mdi_llm_tpu.training import Trainer, TrainingConfig

    cfg = moe_config(E=4, k=2, n_layer=2, block_size=32)
    rng = np.random.default_rng(21)
    data = rng.integers(0, cfg.vocab_size, 2048).astype(np.int32)

    def run(mesh):
        tc = TrainingConfig(
            batch_size=4, block_size=16, max_iters=3, dtype="float32",
            warmup_iters=1, moe_aux_weight=0.05, remat=True,
        )
        tr = Trainer(cfg, tc, mesh=mesh)
        r = np.random.default_rng(2)
        for _ in range(3):
            i = r.integers(0, len(data) - 17, 4)
            x = np.stack([data[j : j + 16] for j in i])
            y = np.stack([data[j + 1 : j + 17] for j in i])
            tr.train_step(x[None], y[None])
        return jax.tree_util.tree_map(np.asarray, tr.params)

    base = run(None)
    sp = run(make_mesh({"dp": 2, "sp": 4}, devices))
    for (k1, a), (k2, b) in zip(
        jax.tree_util.tree_leaves_with_path(base),
        jax.tree_util.tree_leaves_with_path(sp),
    ):
        np.testing.assert_allclose(
            a, b, rtol=2e-4, atol=2e-5,
            err_msg=f"param divergence at {jax.tree_util.keystr(k1)}",
        )


def test_chat_session_on_ep_mesh(devices):
    """ChatSession cross-turn KV reuse over an ep mesh: the token-dispatch
    MoE path must stay token-identical to single-device full-history
    re-prefill across turns (the offset prefill and decode both route
    through ep_moe_forward)."""
    from mdi_llm_tpu.generation import Generator

    cfg = moe_config()
    params = init_params(cfg, jax.random.PRNGKey(1))
    single = Generator(cfg, params, max_seq_length=64)
    eng = Generator(cfg, params, max_seq_length=64, mesh=make_mesh({"ep": 4}, devices[:4]))
    assert eng._moe_impl is not None
    sess = eng.chat_session()
    history: list[int] = []
    for turn in ([3, 7, 11], [2, 5]):
        want = list(single.generate_chat(history + turn, 8, temperature=0.0))
        got = list(sess.send(turn, 8, temperature=0.0))
        assert got == want
        history += turn + want
