"""Paged attention parity: the block-table op must reproduce the dense
`ops/attention.py` softmax chain exactly (the guarantee the serving
engine's greedy parity rests on), across GQA/MQA head layouts, block
sizes, ragged last blocks, and both the lax fallback and the Pallas
kernel (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.paged_attention import (
    KernelParams,
    gather_paged_kv,
    paged_attention,
    paged_prefill,
    paged_update,
)


def build_pool(k, v, block_size, n_extra_blocks=2, shuffle_seed=0):
    """Scatter contiguous (B, G, S, hs) K/V into a pooled block cache with
    SHUFFLED block ids (placement must be invisible) and return
    (pool_k, pool_v, tables)."""
    B, G, S, hs = k.shape
    assert S % block_size == 0
    mb = S // block_size
    nb = 1 + B * mb + n_extra_blocks
    rng = np.random.default_rng(shuffle_seed)
    ids = rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb)
    pool_k = rng.standard_normal((nb, block_size, G, hs)).astype(k.dtype)
    pool_v = rng.standard_normal((nb, block_size, G, hs)).astype(v.dtype)
    for b in range(B):
        for i in range(mb):
            sl = slice(i * block_size, (i + 1) * block_size)
            pool_k[ids[b, i]] = k[b, :, sl].transpose(1, 0, 2)
            pool_v[ids[b, i]] = v[b, :, sl].transpose(1, 0, 2)
    return jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(ids, jnp.int32)


def rand_qkv(B, H, G, S, hs, Tq, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, Tq, hs)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, G, S, hs)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, G, S, hs)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
@pytest.mark.parametrize("block_size", [4, 16])
@pytest.mark.parametrize("q_lens", [[13, 17], [1, 20], [7, 19]])
def test_paged_decode_matches_dense(heads, block_size, q_lens):
    """Decode (Tq=1) at ragged positions — including a last block that is
    only partially filled — must match the dense op bit-for-bit on the
    lax fallback."""
    H, G = heads
    B, hs, S = len(q_lens), 16, 32
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=3)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), block_size)
    q_pos = jnp.asarray([[p] for p in q_lens], jnp.int32)
    ref = multihead_attention(q, k, v, q_pos)
    got = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("block_size", [4, 8])
def test_paged_chunk_matches_dense(block_size):
    """Chunked prefill through the pool (Tq > 1, nonzero offset) matches
    the dense op — the path serving prefill chunks exercise."""
    B, H, G, hs, S, Tq = 2, 6, 3, 8, 24, 5
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=11)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), block_size)
    starts = [9, 3]
    q_pos = jnp.asarray([np.arange(s, s + Tq) for s in starts], jnp.int32)
    ref = multihead_attention(q, k, v, q_pos)
    got = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_pallas_kernel_matches_fallback(heads):
    """The Pallas block-table decode kernel (interpreter mode on CPU) must
    agree with the exact gather fallback to float tolerance."""
    H, G = heads
    B, hs, S, BS = 2, 16, 32, 8
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=7)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([[13], [30]], jnp.int32)
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
@pytest.mark.parametrize("starts", [[13, 3], [0, 25], [7, 7]])
def test_ragged_multiquery_kernel_matches_fallback(heads, starts):
    """The ragged multi-query decode kernel (interpreter mode on CPU) —
    each sequence attending with Tq query tokens at its OWN absolute
    positions, the speculative-verify shape — must agree with the exact
    gather fallback, which itself is bit-equal to the dense op."""
    H, G = heads
    B, hs, S, BS, Tq = len(starts), 16, 32, 8, 5
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=7)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([np.arange(s, s + Tq) for s in starts], jnp.int32)
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )
    # the fallback is the dense softmax chain bit-for-bit (greedy parity)
    dense = multihead_attention(q, k, v, q_pos)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(dense))


def test_ragged_kernel_crossing_block_boundary():
    """Queries spanning a block boundary mask correctly: query t sees key
    slot j iff j <= q_pos[t], even when the Tq window straddles blocks."""
    B, H, G, hs, S, BS, Tq = 2, 4, 2, 8, 24, 4, 6
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=13)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([np.arange(1, 1 + Tq), np.arange(15, 15 + Tq)],
                        jnp.int32)
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


def test_wide_tq_runs_through_kernel():
    """Prefill-width Tq (wider than the old RAGGED_KERNEL_MAX_TQ=16 cap
    the legacy ragged kernel silently fell back at) now runs THROUGH the
    unified kernel with use_kernel=True and matches the fallback — the
    silent-degradation cliff is gone."""
    B, H, G, hs, S, BS = 2, 4, 2, 8, 64, 8
    Tq = 33  # > the old cap
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=1)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([np.arange(Tq), np.arange(20, 20 + Tq)], jnp.int32)
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(
        q, pool_k, pool_v, tables, q_pos, use_kernel=True, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


def _pack_mixed(slots_spec, H, hs, T, seed=0):
    """Build a packed ragged mixed batch: slots_spec is [(slot, start_pos,
    n_tokens), ...] laid out slot-major; the tail up to T pads with slot 0
    at an arbitrary in-window position (the op's contract: padding rows are
    garbage, the caller discards them)."""
    rng = np.random.default_rng(seed)
    n_slots = max(s for s, _, _ in slots_spec) + 1
    q = jnp.asarray(rng.standard_normal((1, H, T, hs)), jnp.float32)
    q_slot = np.zeros((T,), np.int32)
    q_pos = np.zeros((T,), np.int32)
    q_start = np.zeros((n_slots,), np.int32)
    q_len = np.zeros((n_slots,), np.int32)
    off = 0
    for slot, p0, n in slots_spec:
        q_slot[off : off + n] = slot
        q_pos[off : off + n] = np.arange(p0, p0 + n)
        q_start[slot] = off
        q_len[slot] = n
        off += n
    return (q, jnp.asarray(q_slot), jnp.asarray(q_start),
            jnp.asarray(q_len), jnp.asarray(q_pos), off)


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_paged_prefill_fallback_matches_dense(heads):
    """The unified mixed step's ragged op: a decode lane (1 token), a
    prefill chunk (5 tokens crossing a block boundary), an absent slot,
    and batch-tail padding, all packed into ONE query axis — every real
    row must equal the dense op on that slot's contiguous KV bit-for-bit
    (the greedy parity contract of the serving engine)."""
    H, G = heads
    B, hs, S, BS, T = 3, 16, 32, 8, 9
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=5)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    # slot 0: decode at pos 13; slot 1: chunk at 6..10 (crosses block 1);
    # slot 2: absent (q_len 0); 3 padding rows ride the tail
    qp, q_slot, q_start, q_len, q_pos, off = _pack_mixed(
        [(0, 13, 1), (1, 6, 5)], H, hs, T, seed=9
    )
    got = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=False)
    # dense reference on the SAME per-token lane layout, but with the
    # CONTIGUOUS (unpaged) KV: shuffled block placement must be invisible
    # bit-for-bit (reduction order across different lane layouts is XLA's
    # to choose, so cross-shape comparisons are only token-level — pinned
    # end-to-end by tests/test_serving.py)
    qt = qp[0].transpose(1, 0, 2)[:, :, None, :]  # (T, H, 1, hs)
    ref = multihead_attention(qt, k[q_slot], v[q_slot], q_pos[:, None])
    np.testing.assert_array_equal(
        np.asarray(got)[0, :, :off],
        np.asarray(ref)[:off, :, 0, :].transpose(1, 0, 2),
    )


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_paged_prefill_kernel_matches_fallback(heads):
    """The ragged prefill Pallas kernel (interpreter mode on CPU) must
    agree with the exact per-token gather fallback on every REAL packed
    row — per-slot scalar-prefetched spans, online softmax per
    (head, packed token), masked scratch updates across slots."""
    H, G = heads
    B, hs, S, BS, T = 3, 16, 32, 8, 12
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=7)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    # three live slots at very different depths + 2 padding rows
    qp, q_slot, q_start, q_len, q_pos, off = _pack_mixed(
        [(0, 30, 1), (1, 0, 6), (2, 17, 3)], H, hs, T, seed=11
    )
    ref = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=False)
    got = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref)[0, :, :off], np.asarray(got)[0, :, :off],
        rtol=2e-5, atol=2e-5,
    )


def test_paged_prefill_wide_batch_chunked_fallback():
    """A packed batch wider than the fallback's gather chunk (the real
    serving shape: token_budget ~ 68-136) runs the chunked lax.map path —
    the gathered-KV transient stays ∝ chunk, the math per row is unchanged
    (kernel agreement on every real row)."""
    from mdi_llm_tpu.ops.paged_attention import _LAX_FALLBACK_CHUNK

    H, G, hs, S, BS = 4, 2, 8, 64, 8
    T = 2 * _LAX_FALLBACK_CHUNK + 8  # crosses two chunk boundaries
    q, k, v = rand_qkv(3, H, G, S, hs, Tq=1, seed=19)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    # slot 1 carries a long prefill chunk; slots 0/2 are decode lanes
    qp, q_slot, q_start, q_len, q_pos, off = _pack_mixed(
        [(0, 50, 1), (1, 0, 34), (2, 21, 1)], H, hs, T, seed=23
    )
    assert off > _LAX_FALLBACK_CHUNK
    ref = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=False)
    got = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref)[0, :, :off], np.asarray(got)[0, :, :off],
        rtol=2e-5, atol=2e-5,
    )


def test_paged_prefill_kernel_isolates_slots():
    """A slot's rows must be untouched by OTHER slots' grid steps: the
    masked-row scratch update is load-bearing (NEG_INF is finite, so an
    unmasked update would add exp(0)=1-weighted V garbage to every other
    slot's accumulator on each visited block).  A one-slot packing and the
    same slot inside a multi-slot packing must agree exactly."""
    H, G, hs, S, BS, T = 4, 2, 8, 24, 4, 8
    q, k, v = rand_qkv(3, H, G, S, hs, Tq=1, seed=3)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    qp, q_slot, q_start, q_len, q_pos, _ = _pack_mixed(
        [(0, 9, 2), (1, 20, 3), (2, 2, 1)], H, hs, T, seed=13
    )
    multi = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start,
                          q_len, q_pos, use_kernel=True, interpret=True)
    # re-run with ONLY slot 1 live (same packed offsets, others absent)
    solo_len = jnp.asarray(np.array([0, 3, 0], np.int32))
    solo = paged_prefill(qp, pool_k, pool_v, tables, q_slot, q_start,
                         solo_len, q_pos, use_kernel=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(multi)[0, :, 2:5], np.asarray(solo)[0, :, 2:5],
        rtol=1e-6, atol=1e-6,
    )


def test_paged_update_slots_and_trash():
    """Writes resolve to (table[pos // bs], pos % bs); positions past the
    table's coverage land in the reserved trash block 0 and can never
    touch a live block."""
    B, G, hs, BS, MB, NB = 2, 2, 4, 4, 3, 8
    rng = np.random.default_rng(0)
    pool = jnp.asarray(rng.standard_normal((NB, BS, G, hs)), jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    pos = jnp.asarray([[5, 6], [0, 1]], jnp.int32)
    new = jnp.asarray(rng.standard_normal((B, 2, G, hs)), jnp.float32)
    pk, pv = paged_update(pool, pool, new, new, tables, pos)
    np.testing.assert_array_equal(np.asarray(pk[2, 1]), np.asarray(new[0, 0]))
    np.testing.assert_array_equal(np.asarray(pk[2, 2]), np.asarray(new[0, 1]))
    np.testing.assert_array_equal(np.asarray(pk[4, 0]), np.asarray(new[1, 0]))
    np.testing.assert_array_equal(np.asarray(pk[4, 1]), np.asarray(new[1, 1]))

    # overflow positions (block index >= MB) -> trash block 0 only
    pos2 = jnp.asarray([[MB * BS], [MB * BS + 3]], jnp.int32)
    new2 = jnp.asarray(rng.standard_normal((B, 1, G, hs)), jnp.float32)
    pk2, _ = paged_update(pool, pool, new2, new2, tables, pos2)
    np.testing.assert_array_equal(np.asarray(pk2[1:]), np.asarray(pool[1:]))


def test_gather_layout_roundtrip():
    """gather_paged_kv recovers the contiguous layout: flattened slot j of
    the gathered view holds the entry written at absolute position j."""
    B, G, hs, BS = 1, 2, 4, 4
    k = np.arange(B * G * 8 * hs, dtype=np.float32).reshape(B, G, 8, hs)
    pool_k, _, tables = build_pool(k, k, BS)
    out = gather_paged_kv(pool_k, tables)
    np.testing.assert_array_equal(np.asarray(out), k)


# ---------------------------------------------------------------------------
# int8 quantized pool: {"q": int8 blocks, "scale": (NB, G) per-block scales}
# ---------------------------------------------------------------------------


def empty_q8_pool(nb, bs, g, hs):
    return {"q": jnp.zeros((nb, bs, g, hs), jnp.int8),
            "scale": jnp.zeros((nb, g), jnp.float32)}


def build_q8_pool(k, v, block_size, seed=0):
    """Quantize contiguous (B, G, S, hs) K/V into int8 pool dicts through
    the REAL quantizing scatter (`paged_update`), one whole-range write per
    sequence, with shuffled block placement like `build_pool`."""
    B, G, S, hs = k.shape
    mb = S // block_size
    nb = 1 + B * mb + 2
    rng = np.random.default_rng(seed)
    ids = rng.permutation(np.arange(1, nb))[: B * mb].reshape(B, mb)
    tables = jnp.asarray(ids, jnp.int32)
    kp = empty_q8_pool(nb, block_size, G, hs)
    vp = empty_q8_pool(nb, block_size, G, hs)
    pos = jnp.broadcast_to(jnp.arange(S), (B, S))
    kp, vp = paged_update(
        kp, vp, jnp.asarray(k).transpose(0, 2, 1, 3),
        jnp.asarray(v).transpose(0, 2, 1, 3), tables, pos,
    )
    return kp, vp, tables


def test_q8_update_roundtrip_error_bounded():
    """Quantize-on-scatter then gather-dequantize must reproduce the
    written values within half the block's scale per entry — the symmetric
    int8 rounding bound, per (block, group)."""
    B, G, hs, S, BS = 2, 3, 8, 24, 4
    rng = np.random.default_rng(1)
    k = rng.standard_normal((B, G, S, hs)).astype(np.float32)
    kp, _, tables = build_q8_pool(k, k, BS)
    got = np.asarray(gather_paged_kv(kp, tables))  # (B, G, S, hs)
    scale = np.asarray(kp["scale"])[np.asarray(tables)]  # (B, MB, G)
    bound = np.repeat(scale, BS, axis=1).transpose(0, 2, 1)  # (B, G, S)
    assert np.all(np.abs(got - k) <= 0.5 * bound[..., None] + 1e-7)
    # and the bound is tight enough to matter: scales track the data
    assert np.all(scale > 0)


def test_q8_rewrite_same_value_is_byte_idempotent():
    """The frozen-lane contract: re-scattering the SAME (token, position)
    pair must leave payload bytes AND scales bit-identical (the chunked
    decode scan rewrites frozen lanes every step)."""
    B, G, hs, S, BS = 2, 2, 8, 16, 4
    rng = np.random.default_rng(3)
    k = rng.standard_normal((B, G, S, hs)).astype(np.float32)
    kp, vp, tables = build_q8_pool(k, k, BS)
    knew = jnp.asarray(k).transpose(0, 2, 1, 3)
    for p in (0, 7, S - 1):
        pos = jnp.full((B, 1), p, jnp.int32)
        k2, v2 = paged_update(
            kp, vp, knew[:, p : p + 1], knew[:, p : p + 1], tables, pos
        )
        np.testing.assert_array_equal(np.asarray(k2["q"]), np.asarray(kp["q"]))
        np.testing.assert_array_equal(
            np.asarray(k2["scale"]), np.asarray(kp["scale"])
        )


def test_q8_scale_growth_requantizes_block():
    """Appending a larger-magnitude token to a block grows its scale
    monotonically and requantizes the block's existing entries under the
    new scale — older values stay within the (grown) rounding bound
    instead of silently dequantizing wrong."""
    G, hs, BS, NB = 1, 4, 4, 3
    kp = empty_q8_pool(NB, BS, G, hs)
    vp = empty_q8_pool(NB, BS, G, hs)
    tables = jnp.asarray([[1]], jnp.int32)
    small = jnp.full((1, 1, G, hs), 0.1, jnp.float32)
    big = jnp.full((1, 1, G, hs), 10.0, jnp.float32)
    kp, vp = paged_update(kp, vp, small, small, tables,
                          jnp.asarray([[0]], jnp.int32))
    s0 = float(kp["scale"][1, 0])
    kp, vp = paged_update(kp, vp, big, big, tables,
                          jnp.asarray([[1]], jnp.int32))
    s1 = float(kp["scale"][1, 0])
    assert s1 > s0  # scale grew with the bigger token
    deq = np.asarray(kp["q"][1].astype(jnp.float32)) * s1
    # the first token survived the requantization within the NEW bound
    # (one extra re-rounding: <= old half-ulp rescaled + new half-ulp)
    assert abs(deq[0, 0, 0] - 0.1) <= 0.5 * s0 + 0.5 * s1 + 1e-7
    assert abs(deq[1, 0, 0] - 10.0) <= 0.5 * s1 + 1e-7


def test_q8_update_trash_redirect():
    """Positions past the table's coverage land in trash block 0 only —
    live int8 blocks (payload and scale) stay untouched."""
    G, hs, BS, MB, NB = 2, 4, 4, 2, 6
    rng = np.random.default_rng(0)
    k = rng.standard_normal((1, G, MB * BS, hs)).astype(np.float32)
    kp, vp, tables = build_q8_pool(k, k, BS)
    new = jnp.asarray(rng.standard_normal((1, 1, G, hs)), jnp.float32)
    pos = jnp.asarray([[MB * BS + 1]], jnp.int32)  # past coverage
    k2, _ = paged_update(kp, vp, new, new, tables, pos)
    np.testing.assert_array_equal(
        np.asarray(k2["q"][1:]), np.asarray(kp["q"][1:])
    )
    np.testing.assert_array_equal(
        np.asarray(k2["scale"][1:]), np.asarray(kp["scale"][1:])
    )


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_q8_decode_kernel_matches_fallback(heads):
    """The Pallas decode kernel's IN-LOOP dequant (int8 block × per-group
    scale, f32) must agree with the gather-dequantize fallback — the same
    parity contract the fp pool pins, now at int8."""
    H, G = heads
    B, hs, S, BS = 2, 16, 32, 8
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=1, seed=7)
    kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([[13], [30]], jnp.int32)
    ref = paged_attention(q, kp, vp, tables, q_pos, use_kernel=False)
    got = paged_attention(q, kp, vp, tables, q_pos, use_kernel=True,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )
    # the dequantized attention itself stays near the fp dense op: the
    # per-layer max-abs drift of int8 KV is bounded by the block scales
    dense = multihead_attention(q, k, v, q_pos)
    assert np.max(np.abs(np.asarray(ref) - np.asarray(dense))) < 0.05


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_q8_ragged_kernel_matches_fallback(heads):
    """Ragged multi-query decode (the speculative-verify shape) over an
    int8 pool: kernel == fallback."""
    H, G = heads
    B, hs, S, BS, Tq = 2, 16, 32, 8, 5
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=11)
    kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([np.arange(9, 9 + Tq), np.arange(21, 21 + Tq)],
                        jnp.int32)
    ref = paged_attention(q, kp, vp, tables, q_pos, use_kernel=False)
    got = paged_attention(q, kp, vp, tables, q_pos, use_kernel=True,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_q8_prefill_kernel_matches_fallback(heads):
    """The unified mixed step's ragged prefill kernel over an int8 pool:
    per-slot spans, in-loop dequant, masked scratch — kernel == fallback
    on every real packed row."""
    H, G = heads
    hs, S, BS, T = 16, 32, 8, 12
    q, k, v = rand_qkv(3, H, G, S, hs, Tq=1, seed=13)
    kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
    qp, q_slot, q_start, q_len, q_pos, off = _pack_mixed(
        [(0, 30, 1), (1, 0, 6), (2, 17, 3)], H, hs, T, seed=17
    )
    ref = paged_prefill(qp, kp, vp, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=False)
    got = paged_prefill(qp, kp, vp, tables, q_slot, q_start, q_len,
                        q_pos, use_kernel=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref)[0, :, :off], np.asarray(got)[0, :, :off],
        rtol=2e-5, atol=2e-5,
    )

# ---------------------------------------------------------------------------
# unified-kernel property grid: ONE kernel serves every (q_len, q_pos) mix
# (mha/gqa/mqa x fp/int8 x Tq x ragged mixed spans), pinned against the
# fallback (which the fp rows pin against dense bit-for-bit above)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv", ["fp", "int8"])
@pytest.mark.parametrize("Tq", [1, 7, 16, 33])
@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_unified_kernel_property_grid(heads, Tq, kv):
    """The tentpole contract: decode (Tq=1), narrow and exactly-at-the-old-
    cap verifies (7, 16), and beyond-the-old-cap width (33) all run the
    SAME kernel and agree with the exact fallback at both pool dtypes."""
    H, G = heads
    B, hs, S, BS = 2, 16, 64, 8
    q, k, v = rand_qkv(B, H, G, S, hs, Tq=Tq, seed=Tq)
    if kv == "int8":
        kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
    else:
        kp, vp, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    starts = [3, S - Tq]
    q_pos = jnp.asarray([np.arange(s, s + Tq) for s in starts], jnp.int32)
    ref = paged_attention(q, kp, vp, tables, q_pos, use_kernel=False)
    got = paged_attention(q, kp, vp, tables, q_pos, use_kernel=True,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )
    if kv == "fp":
        # the fallback anchor: dense softmax chain bit-for-bit
        dense = multihead_attention(q, k, v, q_pos)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(dense))


@pytest.mark.parametrize("kv", ["fp", "int8"])
@pytest.mark.parametrize("heads", [(8, 8), (8, 2), (4, 1)],
                         ids=["mha", "gqa", "mqa"])
def test_unified_kernel_mixed_span_widths(heads, kv):
    """One packed batch mixing every grid width at once — a decode lane, a
    7-token verify, a 16-token chunk and a 33-token prefill run — through
    the one kernel; every real row agrees with the fallback."""
    H, G = heads
    hs, S, BS = 16, 64, 8
    T = 1 + 7 + 16 + 33 + 3  # + 3 padding rows
    q, k, v = rand_qkv(4, H, G, S, hs, Tq=1, seed=29)
    if kv == "int8":
        kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
    else:
        kp, vp, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    qp, q_slot, q_start, q_len, q_pos, off = _pack_mixed(
        [(0, 50, 1), (1, 12, 7), (2, 30, 16), (3, 0, 33)], H, hs, T, seed=31
    )
    ref = paged_prefill(qp, kp, vp, tables, q_slot, q_start, q_len, q_pos,
                        use_kernel=False)
    got = paged_prefill(qp, kp, vp, tables, q_slot, q_start, q_len, q_pos,
                        use_kernel=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(ref)[0, :, :off], np.asarray(got)[0, :, :off],
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize(
    "params",
    [
        KernelParams(kv_step=8, q_pack=1),   # sub-block KV walk, no packing
        KernelParams(kv_step=4, q_pack=2),   # finer walk + explicit packing
        KernelParams(kv_step=None, q_pack=None, scratch_width=256),
    ],
    ids=["kv8-qp1", "kv4-qp2", "wide-scratch"],
)
def test_explicit_params_keep_parity(params):
    """Tuned-table entries change LAYOUT only: any valid (kv_step, q_pack,
    scratch_width) choice must agree with the fallback — the autotuner can
    never trade correctness for speed."""
    H, G, hs, S, BS, Tq = 8, 2, 16, 32, 16, 5
    q, k, v = rand_qkv(2, H, G, S, hs, Tq=Tq, seed=37)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([np.arange(3, 3 + Tq), np.arange(27 - Tq, 27)],
                        jnp.int32)
    ref = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=False)
    got = paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=True,
                          interpret=True, params=params)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5
    )


def test_invalid_params_raise_actionably():
    """use_kernel=True with an entry the geometry cannot run must RAISE
    with the problem named — never silently fall back (the old cap's
    failure mode) and never compile garbage."""
    H, G, hs, S, BS = 4, 2, 8, 32, 16
    q, k, v = rand_qkv(1, H, G, S, hs, Tq=1, seed=41)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([[9]], jnp.int32)
    with pytest.raises(ValueError, match="kv_step=5"):
        paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=True,
                        interpret=True, params=KernelParams(kv_step=5))
    with pytest.raises(ValueError, match="scratch_width"):
        paged_attention(q, pool_k, pool_v, tables, q_pos, use_kernel=True,
                        interpret=True,
                        params=KernelParams(scratch_width=0))


def test_tuned_table_lookup_is_compile_free(tmp_path, monkeypatch):
    """Tuning-table resolution happens host-side at trace time: re-running
    the jitted op after warmup — table file present, env var set, lookup on
    every call — performs ZERO new traces (the zero-post-warmup-recompile
    contract of the tuned path)."""
    from functools import partial

    from mdi_llm_tpu.ops.tuning import (
        TUNE_TABLE_ENV, geometry_key, save_tuning_table,
    )
    from mdi_llm_tpu.utils.profiling import CompileGuard

    H, G, hs, S, BS = 4, 2, 8, 32, 8
    key = geometry_key(H, G, hs, None, BS)
    path = tmp_path / "tuned.json"
    save_tuning_table(str(path), "v5e", {key: {"kv_step": 8, "q_pack": 1}})
    monkeypatch.setenv(TUNE_TABLE_ENV, str(path))
    q, k, v = rand_qkv(2, H, G, S, hs, Tq=1, seed=43)
    pool_k, pool_v, tables = build_pool(np.asarray(k), np.asarray(v), BS)
    q_pos = jnp.asarray([[13], [30]], jnp.int32)
    fn = jax.jit(partial(paged_attention, use_kernel=True, interpret=True))
    fn(q, pool_k, pool_v, tables, q_pos).block_until_ready()  # warmup
    guard = CompileGuard(label="tuned-lookup")
    with guard:
        guard.mark_warm()
        for _ in range(3):
            fn(q, pool_k, pool_v, tables, q_pos).block_until_ready()
    assert guard.traces_after_warmup == 0
    guard.expect_clean()


def test_prefill_fallback_bit_identical_to_old_shape():
    """Satellite pin: the vectorized fallback (gather the pool ONCE into
    per-slot views, index per chunk) must be BIT-identical to the old
    per-chunk-gather shape (`pool[tables][sc] == pool[tables[sc]]`
    row-for-row; reduction orders inside each lane are unchanged).  The
    old algorithm is reimplemented here verbatim as the oracle."""
    from mdi_llm_tpu.ops.paged_attention import (
        _LAX_FALLBACK_CHUNK,
        _paged_attention_lax,
    )

    def old_prefill_lax(q, k_pool, v_pool, block_tables, q_slot, q_pos,
                        scale):
        qt = q[0].transpose(1, 0, 2)[:, :, None, :]
        T = qt.shape[0]
        C = _LAX_FALLBACK_CHUNK
        if T <= C:
            out = _paged_attention_lax(
                qt, k_pool, v_pool, block_tables[q_slot], q_pos[:, None],
                scale,
            )
            return out[:, :, 0, :].transpose(1, 0, 2)[None]
        pad = -T % C
        qt_p = jnp.pad(qt, ((0, pad), (0, 0), (0, 0), (0, 0)))
        slot_p = jnp.pad(q_slot, (0, pad))
        pos_p = jnp.pad(q_pos, (0, pad))

        def chunk(args):
            qc, sc, pc = args
            return _paged_attention_lax(
                qc, k_pool, v_pool, block_tables[sc], pc[:, None], scale
            )

        out = jax.lax.map(chunk, (
            qt_p.reshape(-1, C, *qt.shape[1:]),
            slot_p.reshape(-1, C),
            pos_p.reshape(-1, C),
        ))
        out = out.reshape(-1, *out.shape[2:])[:T]
        return out[:, :, 0, :].transpose(1, 0, 2)[None]

    H, G, hs, S, BS = 4, 2, 8, 64, 8
    scale = 1.0 / hs ** 0.5
    for kv, T, spans, seed in [
        ("fp", 9, [(0, 13, 1), (1, 6, 5)], 47),        # short: no chunking
        ("fp", 2 * _LAX_FALLBACK_CHUNK + 8,            # crosses chunks
         [(0, 50, 1), (1, 0, 34), (2, 21, 1)], 53),
        ("int8", 9, [(0, 13, 1), (1, 6, 5)], 59),
        ("int8", 2 * _LAX_FALLBACK_CHUNK + 8,
         [(0, 50, 1), (1, 0, 34), (2, 21, 1)], 61),
    ]:
        q, k, v = rand_qkv(3, H, G, S, hs, Tq=1, seed=seed)
        if kv == "int8":
            kp, vp, tables = build_q8_pool(np.asarray(k), np.asarray(v), BS)
        else:
            kp, vp, tables = build_pool(np.asarray(k), np.asarray(v), BS)
        qp, q_slot, q_start, q_len, q_pos, _ = _pack_mixed(
            spans, H, hs, T, seed=seed + 1
        )
        want = old_prefill_lax(qp, kp, vp, tables, q_slot, q_pos, scale)
        got = paged_prefill(qp, kp, vp, tables, q_slot, q_start, q_len,
                            q_pos, use_kernel=False)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
