"""Quantized paged-KV pool (ServingConfig.kv_dtype="int8") interacting
with the full serving machinery: greedy drift vs the fp engine is pinned
(per-layer max-abs error bound + token-match-rate floor), the fp path
stays structurally untouched, prefix caching / preemption-recompute /
frozen-lane chunked decode / speculative verify all run over int8 blocks,
the pool roughly doubles its blocks at a fixed HBM budget, mdi-audit's
byte accounting stays exact against the live quantized pool (single
device and per-device under tp), and CompileGuard shows zero post-warmup
recompiles with int8 enabled on the full mixed trace."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mdi_llm_tpu.config import Config, ServingConfig
from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import init_params
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.utils.profiling import CompileGuard
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(block_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _trace(cfg, lengths, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, int(n)).tolist() for n in lengths]


def _run_engine(gen, prompts, max_news, **knobs):
    engine = gen.serve(**knobs)
    for i, (p, m) in enumerate(zip(prompts, max_news)):
        engine.add_request(f"r{i}", p, m)
    results, stats = engine.run()
    return [results[f"r{i}"] for i in range(len(prompts))], stats, engine


def _match_rate(want, got, prompts):
    """Aggregate longest-matching-prefix rate over the generated suffixes —
    the drift metric of the acceptance criterion (post-divergence tokens
    never count as matches)."""
    total = match = 0
    for w, g, p in zip(want, got, prompts):
        a, b = w[len(p):], g[len(p):]
        n = 0
        while n < min(len(a), len(b)) and a[n] == b[n]:
            n += 1
        match += n
        total += max(len(a), 1)
    return match / total


# ---------------------------------------------------------------------------
# greedy drift vs the fp engine (the quality half of the acceptance bar)
# ---------------------------------------------------------------------------


def test_int8_engine_matches_fp_engine_streams(model):
    """Mixed-length serving-cb-style trace: the int8 engine's greedy
    streams must match the fp engine's at >= 99% token-match rate."""
    cfg, params = model
    prompts = _trace(cfg, (3, 9, 17, 5, 33))
    max_news = [8, 12, 6, 10, 7]
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    fp, _, _ = _run_engine(gen, prompts, max_news, **knobs)
    q8, stats, engine = _run_engine(
        gen, prompts, max_news, kv_dtype="int8", **knobs
    )
    assert _match_rate(fp, q8, prompts) >= 0.99
    assert stats.requests_finished == len(prompts)
    assert engine.kv_dtype_name == "int8"
    assert engine.pool.used == 0  # every retirement released int8 blocks


def test_int8_pool_drift_bounded_per_layer(model):
    """Per-layer max-abs error bound: after identical traces, every live
    entry of the dequantized int8 pool sits within 2 scales of the fp
    engine's pool (0.5 scale of direct rounding plus re-rounding slack
    from monotone scale growth) — blocks are placed identically because
    the allocator is dtype-blind."""
    cfg, params = model
    prompts = _trace(cfg, (5, 19, 11))
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8)
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    _, _, fp_eng = _run_engine(gen, prompts, [6, 6, 6], **knobs)
    _, _, q8_eng = _run_engine(
        gen, prompts, [6, 6, 6], kv_dtype="int8", **knobs
    )
    for side in ("k", "v"):
        fp_pool = np.asarray(fp_eng._kv[side])  # (L, NB, BS, G, hs)
        q = np.asarray(q8_eng._kv[side]["q"], np.float32)
        s = np.asarray(q8_eng._kv[side]["scale"])  # (L, NB, G)
        deq = q * s[:, :, None, :, None]
        err = np.abs(deq - fp_pool)[:, 1:]  # trash block 0 is garbage
        bound = 2.0 * s[:, 1:, None, :, None] + 1e-6
        L = fp_pool.shape[0]
        for layer in range(L):
            assert np.all(err[layer] <= bound[layer]), (
                f"{side} layer {layer}: max-abs drift "
                f"{err[layer].max():.4g} exceeds 2x scale bound"
            )


def test_fp_path_structurally_untouched(model):
    """kv_dtype=None keeps the fp pool bit-identical to before the knob
    existed: bare arrays at the cache dtype, no scale leaves, and the
    engine resolves the dtype name from the Generator."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(block_size=4, max_batch=2)
    assert isinstance(engine._kv["k"], jnp.ndarray)
    assert engine._kv["k"].dtype == jnp.float32
    assert engine.kv_dtype_name == "float32"


def test_unknown_kv_dtype_refused(model):
    """kv_dtype names the byte table doesn't know are refused at engine
    construction (the same dtype_bytes wall mdi-audit uses)."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="unknown dtype"):
        gen.serve(block_size=4, max_batch=2, kv_dtype="int9")
    # known-but-non-storage dtypes are refused with the actionable message
    with pytest.raises(ValueError, match="not a paged-pool storage dtype"):
        gen.serve(block_size=4, max_batch=2, kv_dtype="int32")
    with pytest.raises(ValueError, match="unknown dtype"):
        ServingConfig(kv_dtype="int9").block_bytes(cfg)


# ---------------------------------------------------------------------------
# int8 blocks x existing machinery
# ---------------------------------------------------------------------------


def test_int8_chunked_decode_token_identical_to_per_step(model):
    """Chunked decode over an int8 pool is BIT-identical to the per-step
    int8 engine: frozen lanes rewrite the same quantized bytes (monotone
    scales make the rewrite idempotent), so the multi-token scan changes
    nothing — the same contract the fp engine pins, surviving
    quantization."""
    cfg, params = model
    prompts = _trace(cfg, (3, 9, 17), seed=7)
    max_news = [10, 6, 12]
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    base = dict(block_size=4, max_batch=3, prefill_chunk=8, kv_dtype="int8")
    want, _, _ = _run_engine(gen, prompts, max_news, decode_chunk=1, **base)
    for buffered in (False, True):
        got, stats, _ = _run_engine(
            gen, prompts, max_news, decode_chunk=4,
            double_buffer=buffered, **base,
        )
        assert got == want
        assert stats.tokens_per_sync > 1.0


def test_int8_prefix_cache_reuses_quantized_blocks(model):
    """A prefix-cache hit reuses int8 blocks (payload AND scale) copy-free:
    the second identical prompt skips its cached blocks' prefill and still
    emits the identical greedy stream."""
    cfg, params = model
    rng = np.random.default_rng(11)
    prompt = rng.integers(1, cfg.vocab_size, 21).tolist()
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(block_size=4, max_batch=1, prefill_chunk=8,
                       kv_dtype="int8")
    engine.add_request("a", prompt, 8)
    engine.add_request("b", prompt, 8)
    results, stats = engine.run()
    assert stats.prefix_cache_hits > 0
    assert results["a"] == results["b"]


def test_int8_preemption_recompute_roundtrip(model):
    """A pool-pressure preemption recomputes the victim's prompt+progress
    into FRESH int8 blocks; the resumed stream must stay on the
    non-preempted int8 engine's tokens at >= 99% match (recompute
    re-quantizes under possibly different block groupings, so bit equality
    is not the contract — bounded drift is)."""
    cfg, params = model
    prompts = _trace(cfg, (9, 13, 11), seed=9)
    max_news = [10, 10, 10]
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    base = dict(block_size=4, prefill_chunk=8, kv_dtype="int8",
                prefix_caching=False, decode_chunk=1)
    want, _, _ = _run_engine(gen, prompts, max_news, max_batch=3, **base)
    # the pool sizing that forces the per-step engine's one-block-at-a-time
    # growth dry (test_engine_preemption_preserves_parity's recipe)
    got, stats, engine = _run_engine(
        gen, prompts, max_news, max_batch=3, max_blocks=1 + 14, **base,
    )
    assert stats.preemptions > 0
    assert engine.pool.used == 0
    assert _match_rate(want, got, prompts) >= 0.99
    assert all(len(g) > len(p) for g, p in zip(got, prompts))


def test_int8_speculative_verify_over_quantized_pool(model):
    """spec_k batched verify dispatches the ragged multi-query forward over
    the int8 pool; accepted bursts keep the stream on the plain int8
    engine's greedy tokens (>= 99% — a rejected draft's write can ratchet
    a tail block's scale, so bit equality is not guaranteed)."""
    cfg, params = model
    # prompts whose greedy continuation echoes earlier context (the tiny
    # random model falls into cycles), so n-gram drafting genuinely fires —
    # test_serving._cycling_prompts' recipe
    prompts = [np.random.default_rng(s).integers(1, cfg.vocab_size, 5).tolist()
               for s in (5, 7)]
    max_news = [40, 35]
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    base = dict(block_size=4, max_batch=2, prefill_chunk=8, kv_dtype="int8")
    want, _, _ = _run_engine(gen, prompts, max_news, **base)
    got, stats, _ = _run_engine(gen, prompts, max_news, spec_k=4, **base)
    assert stats.spec_drafted > 0
    assert _match_rate(want, got, prompts) >= 0.99


def test_int8_zero_postwarmup_recompiles_mixed_trace(model):
    """The CompileGuard half of the acceptance bar: a warmup int8 engine
    and its timed twin share the jit cache; the full mixed trace (prefill
    chunks + decode + retirement) builds no new executable after warmup —
    donation round-trips keep the quantized pool's pytree layout."""
    cfg, params = model
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    prompts = _trace(cfg, (3, 9, 17), seed=17)
    knobs = dict(block_size=4, max_batch=3, prefill_chunk=8,
                 decode_chunk=4, kv_dtype="int8")

    def drive(engine):
        for i, p in enumerate(prompts):
            engine.add_request(f"r{i}", p, 8)
        engine.run()

    guard = CompileGuard(label="int8-serve")
    with guard:
        drive(gen.serve(**knobs))
        guard.mark_warm()
        drive(gen.serve(**knobs))
    assert guard.traces_after_warmup == 0
    assert guard.backend_compiles_after_warmup == 0
    guard.expect_clean()


# ---------------------------------------------------------------------------
# capacity + byte accounting (the HBM half of the acceptance bar)
# ---------------------------------------------------------------------------


def test_int8_blocks_roughly_double_at_fixed_budget():
    """At a fixed --hbm-gb budget, the int8 pool admits >= 1.8x the blocks
    of the fp pool (and therefore >= 1.8x the resident sequences a block-
    bound pool can hold) — through the ONE itemized bytes-per-block helper
    the audit fit and the estimates share, scale arrays included."""
    cfg = Config.from_name("tiny-llama-1.1b")
    from mdi_llm_tpu.analysis.audit import preflight

    fits = {}
    for name, kv_dtype in (("fp", None), ("int8", "int8")):
        sv = ServingConfig(kv_dtype=kv_dtype)
        report = preflight(cfg, batch=8, seq_len=512, serving=sv,
                           hbm_gb=8.0, quantize="int8")
        fits[name] = report.breakdown["fits"]["max_pool_blocks"]
        assert report.breakdown["kv_pool"]["blocks_at_budget"] == fits[name]
    assert fits["int8"] >= 1.8 * fits["fp"]
    # the per-block ratio itself: ~2x for bf16 -> int8 at hs=64
    bfp = ServingConfig().block_bytes(cfg, "bfloat16")
    b8 = ServingConfig(kv_dtype="int8").block_bytes(cfg, "bfloat16")
    assert b8["scale_bytes"] > 0 and bfp["scale_bytes"] == 0
    assert bfp["total_bytes"] >= 1.8 * b8["total_bytes"]


def test_audit_pool_bytes_exact_vs_live_int8_engine(model):
    """mdi-audit's pool estimate (payload + scale arrays) must equal the
    live quantized engine's device bytes EXACTLY, and the breakdown's
    scale_bytes must equal the scale leaves alone."""
    cfg, params = model
    sv = ServingConfig(block_size=4, max_batch=3, prefill_chunk=8,
                       kv_dtype="int8")
    from mdi_llm_tpu.analysis.audit import preflight

    report = preflight(cfg, batch=3, seq_len=128, cache_dtype="float32",
                       serving=sv)
    assert not report.errors
    pool = report.breakdown["kv_pool"]
    assert pool["kv_dtype"] == "int8"
    gen = Generator(cfg, params, cache_dtype=jnp.float32)
    engine = gen.serve(serving=sv)
    leaves = jax.tree_util.tree_leaves(engine._kv)
    live_total = sum(int(x.nbytes) for x in leaves)
    live_scales = sum(
        int(side["scale"].nbytes) for side in engine._kv.values()
    )
    assert pool["pool_bytes"] == live_total
    assert pool["scale_bytes"] == live_scales
    assert report.breakdown["per_device"]["kv_bytes"] == live_total


def test_audit_pool_bytes_exact_per_device_under_tp(model, devices):
    """Under a tp mesh the int8 pool shards its KV-group axis — scale
    arrays included (paged_kv_scale_spec) — and the audit's per-device
    estimate equals the bytes actually resident on one device's shards."""
    cfg, params = model
    sv = ServingConfig(block_size=4, max_batch=3, prefill_chunk=8,
                       kv_dtype="int8")
    from mdi_llm_tpu.analysis.audit import preflight

    report = preflight(cfg, tp=2, batch=3, seq_len=128,
                       cache_dtype="float32", serving=sv)
    assert not report.errors
    pool = report.breakdown["kv_pool"]
    gen = Generator(cfg, params, cache_dtype=jnp.float32,
                    mesh=make_mesh({"tp": 2}, devices[:2]))
    engine = gen.serve(serving=sv)
    leaves = jax.tree_util.tree_leaves(engine._kv)
    live_total = sum(int(x.nbytes) for x in leaves)
    dev0 = devices[0]
    live_dev = sum(
        int(s.data.nbytes)
        for x in leaves for s in x.addressable_shards if s.device == dev0
    )
    assert pool["tp"] == 2
    assert pool["pool_bytes"] == live_total
    assert pool["pool_bytes_per_device"] == live_total // 2 == live_dev
    # the scale leaves really are group-sharded, not replicated
    for side in engine._kv.values():
        assert "tp" in str(side["scale"].sharding.spec)


def test_int8_engine_runs_under_tp_mesh(model, devices):
    """The sharded engine serves an int8 pool with streams matching the
    single-device int8 engine token-for-token (per-head math never crosses
    a shard, and each device dequantizes with its own scale slice)."""
    cfg, params = model
    prompts = _trace(cfg, (3, 9), seed=23)
    knobs = dict(block_size=4, max_batch=2, kv_dtype="int8")
    single = Generator(cfg, params, cache_dtype=jnp.float32)
    want, _, _ = _run_engine(single, prompts, [8, 8], **knobs)
    tp = Generator(cfg, params, cache_dtype=jnp.float32,
                   mesh=make_mesh({"tp": 2}, devices[:2]))
    got, _, engine = _run_engine(tp, prompts, [8, 8], **knobs)
    assert got == want
    assert "tp" in str(engine._kv["k"]["q"].sharding.spec)


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_help_covers_kv_dtype_int8():
    """--kv-dtype int8 is documented on mdi-serve, bench, and mdi-audit;
    the dense-cache entry points refuse it."""
    import bench
    from mdi_llm_tpu.analysis.audit import build_parser as audit_parser
    from mdi_llm_tpu.cli.sample import build_parser as sample_parser
    from mdi_llm_tpu.cli.serve import build_parser as serve_parser

    # collapse argparse's line wrapping before matching phrases
    serve_help = " ".join(serve_parser().format_help().split())
    assert "int8" in serve_help and "per-block" in serve_help
    bench_help = " ".join(bench.build_parser().format_help().split())
    assert "Quantized paged KV" in bench_help and "kernel" in bench_help
    audit_help = " ".join(audit_parser().format_help().split())
    assert "int8" in audit_help and "quantized pool" in audit_help
    # dense entry points keep the original choices (argparse refuses int8)
    with pytest.raises(SystemExit):
        sample_parser().parse_args(["--kv-dtype", "int8"])
