"""Weight-only int8 quantization: numerics, tree handling, engine parity.

Beyond-reference capability (reference is fp16/bf16-only,
`gptserver.py:199-209`); targets the HBM-bandwidth bound of batched decode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.generation import Generator
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.quant import (
    dequantize_tensor,
    quantize_params,
    quantize_tensor,
    quantized_einsum,
)


def tiny_cfg(**kw):
    base = dict(
        name="tiny-q",
        block_size=64,
        vocab_size=96,
        padded_vocab_size=96,
        n_layer=3,
        n_head=4,
        n_embd=32,
        n_query_groups=2,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMLP",
        intermediate_size=64,
    )
    base.update(kw)
    return Config(**base)


def test_quantize_tensor_roundtrip_error():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 64)).astype(np.float32)
    q, s = quantize_tensor(w)
    assert q.dtype == np.int8 and s.shape == (16,)
    wd = dequantize_tensor(q, s)
    # per-channel symmetric int8: max error <= scale/2 per element
    assert np.max(np.abs(wd - w) - s[:, None] / 2) < 1e-6

    # zero rows quantize to exact zeros (no div-by-zero)
    w0 = np.zeros((4, 8), np.float32)
    q0, s0 = quantize_tensor(w0)
    assert np.all(q0 == 0) and np.all(dequantize_tensor(q0, s0) == 0)


def test_quantized_einsum_matches_dequantized():
    rng = np.random.default_rng(1)
    w = rng.normal(size=(24, 32)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(5, 32)).astype(np.float32))
    q, s = quantize_tensor(w)
    p = {"weight_q": jnp.asarray(q), "scale": jnp.asarray(s)}
    got = quantized_einsum("...i,oi->...o", x, p)
    want = jnp.einsum("...i,oi->...o", x, jnp.asarray(dequantize_tensor(q, s)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_quantize_params_tree_shape():
    cfg = tiny_cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params)
    # embeddings untouched, norms untouched (1-D), linears quantized
    assert "weight" in qp["wte"]
    assert "weight" in qp["ln_f"]
    blocks = qp["blocks"]
    assert blocks["attn"]["qkv"]["weight_q"].dtype == jnp.int8
    # stacked layout: (L, out, in) -> scale (L, out)
    assert (
        blocks["attn"]["qkv"]["scale"].shape
        == blocks["attn"]["qkv"]["weight_q"].shape[:2]
    )
    assert qp["lm_head"]["weight_q"].dtype == jnp.int8
    # cast_params must not clobber int8 leaves
    cast = transformer.cast_params(qp, jnp.bfloat16)
    assert cast["blocks"]["attn"]["qkv"]["weight_q"].dtype == jnp.int8
    assert transformer.param_dtype(cast) == jnp.bfloat16


def test_generator_int8_close_to_fp32():
    cfg = tiny_cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    prompts = [[5, 9, 2, 7], [11, 3]]
    g32 = Generator(cfg, params, rng_seed=7)
    g8 = Generator(cfg, params, rng_seed=7, quantize="int8")
    assert g8.cache_dtype == jnp.float32  # inferred from float leaves

    # bf16 weights + int8: cache must follow the weight dtype, not the f32
    # quantization scales (sorted-key flattening puts "scale" first)
    bf = transformer.cast_params(params, jnp.bfloat16)
    g8b = Generator(cfg, bf, rng_seed=7, quantize="int8")
    assert g8b.cache_dtype == jnp.bfloat16

    out32, _ = g32.generate(prompts, 8, temperature=0.0)
    out8, _ = g8.generate(prompts, 8, temperature=0.0)
    # random tiny weights leave logit gaps narrow, so allow small divergence:
    # the first few greedy tokens must agree
    for a, b in zip(out32, out8):
        assert a[: len(prompts[0]) + 2] == b[: len(prompts[0]) + 2]


def test_w8a8_einsum_close_to_fp32():
    """Dynamic activation quant + int8 matmul: error bounded by the combined
    weight/activation rounding, across the plain and expert einsum shapes."""
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(2, 5, 32)).astype(np.float32))
    for spec, wshape in (
        ("...i,oi->...o", (16, 32)),
        ("...i,ei->...e", (4, 32)),
        ("...d,eid->...ei", (4, 16, 32)),
    ):
        w = rng.normal(size=wshape).astype(np.float32)
        q, s = quantize_tensor(w)
        p8 = {"weight_q8": jnp.asarray(q), "scale": jnp.asarray(s)}
        got = np.asarray(quantized_einsum(spec, x, p8))
        want = np.asarray(quantized_einsum(spec, x, {"weight": jnp.asarray(w)}))
        err = got - want
        # pointwise outliers are intrinsic at D=32 (quant noise ~ sqrt(D));
        # the aggregate error must stay small
        rms_ratio = np.sqrt((err**2).mean()) / np.sqrt((want**2).mean())
        assert rms_ratio < 0.02 and np.max(np.abs(err)) < 0.5, (spec, rms_ratio)
    # the trailing-contraction expert shape: x (..., E, I) @ (E, D, I)
    xe = jnp.asarray(rng.normal(size=(2, 5, 4, 16)).astype(np.float32))
    wp = rng.normal(size=(4, 32, 16)).astype(np.float32)
    q, s = quantize_tensor(wp)
    got = quantized_einsum(
        "...ei,edi->...ed", xe, {"weight_q8": jnp.asarray(q), "scale": jnp.asarray(s)}
    )
    want = quantized_einsum("...ei,edi->...ed", xe, {"weight": jnp.asarray(wp)})
    err = np.asarray(got) - np.asarray(want)
    rms_ratio = np.sqrt((err**2).mean()) / np.sqrt((np.asarray(want) ** 2).mean())
    assert rms_ratio < 0.02 and np.max(np.abs(err)) < 0.5


def test_generator_w8a8_close_to_fp32():
    cfg = tiny_cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    prompts = [[5, 9, 2, 7], [11, 3]]
    g32 = Generator(cfg, params, rng_seed=7)
    g8 = Generator(cfg, params, rng_seed=7, quantize="w8a8")
    out32, _ = g32.generate(prompts, 8, temperature=0.0)
    out8, _ = g8.generate(prompts, 8, temperature=0.0)
    # coarser than weight-only: the first greedy tokens must still agree
    for a, b, p in zip(out32, out8, prompts):
        assert a[: len(p) + 2] == b[: len(p) + 2]


def test_pipeline_engine_int8_runs(devices):
    from mdi_llm_tpu.parallel.pipeline import PipelineEngine

    cfg = tiny_cfg(n_layer=4)
    params = transformer.init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    eng = PipelineEngine(cfg, params, n_stages=2, quantize="int8", devices=devices[:2])
    outs, stats = eng.generate([[5, 9, 2], [7, 1, 3]], 6, temperature=0.0)
    assert all(len(o) == 9 for o in outs)
    assert stats.tokens_generated == 12


def test_init_quantized_params_generates():
    """Direct-to-int8 random init (large-model bench path): tree has the
    quantized layout and drives the Generator end to end."""
    from mdi_llm_tpu.ops.quant import init_quantized_params

    cfg = tiny_cfg()
    qp = init_quantized_params(cfg, seed=1, dtype=jnp.float32)
    assert qp["blocks"]["attn"]["qkv"]["weight_q"].dtype == np.int8
    assert "weight" in qp["wte"] and "weight_q" not in qp["wte"]
    g = Generator(cfg, jax.device_put(qp), rng_seed=5, cache_dtype=jnp.float32)
    outs, _ = g.generate([[5, 9, 2]], 6, temperature=0.0)
    assert len(outs[0]) == 9

    qp8 = init_quantized_params(cfg, seed=1, mode="w8a8", dtype=jnp.float32)
    assert qp8["blocks"]["attn"]["qkv"]["weight_q8"].dtype == np.int8


def test_moe_quantized_forward():
    cfg = tiny_cfg(
        mlp_class_name="LLaMAMoE", n_expert=4, n_expert_per_token=2, intermediate_size=32
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    qp = quantize_params(params)
    toks = jnp.asarray([[3, 1, 4]], jnp.int32)
    pos = jnp.zeros((1,), jnp.int32)
    lg32, _ = transformer.forward(cfg, params, toks, pos)
    lg8, _ = transformer.forward(cfg, qp, toks, pos)
    # int8 noise is small relative to logit scale
    denom = np.maximum(np.abs(np.asarray(lg32)), 1.0)
    assert np.max(np.abs(np.asarray(lg8) - np.asarray(lg32)) / denom) < 0.15


def test_fp8_kv_cache_generation():
    """float8 KV cache: runs end to end, early greedy tokens match the f32
    cache (fp8 noise accumulates slowly at tiny scale)."""
    cfg = tiny_cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(6), dtype=jnp.float32)
    prompts = [[5, 9, 2, 7, 1]]
    g32 = Generator(cfg, params, rng_seed=2)
    g8 = Generator(cfg, params, rng_seed=2, cache_dtype=jnp.float8_e4m3fn)
    o32, _ = g32.generate(prompts, 8, temperature=0.0)
    o8, s8 = g8.generate(prompts, 8, temperature=0.0)
    assert len(o8[0]) == len(prompts[0]) + 8
    assert o8[0][: len(prompts[0]) + 2] == o32[0][: len(prompts[0]) + 2]


def test_resolve_kv_dtype():
    from mdi_llm_tpu.cli._common import resolve_kv_dtype

    assert resolve_kv_dtype("auto") is None
    assert resolve_kv_dtype("float8") == jnp.float8_e4m3fn
    assert resolve_kv_dtype("bfloat16") == jnp.bfloat16


# ---------------------------------------------------------------------------
# int4 (packed-nibble, group-wise scales)
# ---------------------------------------------------------------------------


def test_quantize_tensor4_roundtrip_error():
    from mdi_llm_tpu.ops.quant import quantize_tensor4, unpack_w4

    rng = np.random.default_rng(3)
    w = rng.normal(size=(3, 16, 256)).astype(np.float32)  # stacked layout
    packed, scale = quantize_tensor4(w)
    assert packed.dtype == np.int8 and packed.shape == (3, 16, 128)
    assert scale.shape == (3, 16, 2)  # 256 / group 128
    wd = np.asarray(unpack_w4(jnp.asarray(packed), jnp.asarray(scale), jnp.float32))
    # symmetric int4: |err| <= scale/2 per element, per group
    err = np.abs(wd - w).reshape(3, 16, 2, 128).max(-1)
    assert np.all(err <= scale / 2 + 1e-6)

    # zero weights stay exactly zero
    p0, s0 = quantize_tensor4(np.zeros((4, 8), np.float32))
    assert np.all(
        np.asarray(unpack_w4(jnp.asarray(p0), jnp.asarray(s0), jnp.float32)) == 0
    )


def test_quantized_einsum_w4_matches_dequantized():
    from mdi_llm_tpu.ops.quant import quantize_tensor4, unpack_w4

    rng = np.random.default_rng(4)
    w = rng.normal(size=(24, 64)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(5, 64)).astype(np.float32))
    packed, scale = quantize_tensor4(w)
    p = {"weight_q4": jnp.asarray(packed), "scale": jnp.asarray(scale)}
    got = quantized_einsum("...i,oi->...o", x, p)
    want = jnp.einsum(
        "...i,oi->...o", x, unpack_w4(jnp.asarray(packed), jnp.asarray(scale), jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_int4_generation_runs_and_tracks_f32():
    cfg = tiny_cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    ref = Generator(cfg, params, cache_dtype=jnp.float32)
    eng = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int4")
    prompts = [[5, 9, 2], [7, 1, 3]]
    want, _ = ref.generate(prompts, 8, temperature=0.0)
    got, stats = eng.generate(prompts, 8, temperature=0.0)
    assert all(len(o) == 11 for o in got)
    assert stats.tokens_generated == 16
    # int4 rounding shifts logits; outputs need not match token-for-token,
    # but the first generated token comes from near-identical prompt logits
    # on this tiny model
    assert got[0][3] == want[0][3]


def test_int4_pipeline_runs(devices):
    from mdi_llm_tpu.parallel.pipeline import PipelineEngine

    cfg = tiny_cfg(n_layer=4)
    params = transformer.init_params(cfg, jax.random.PRNGKey(6), dtype=jnp.float32)
    eng = PipelineEngine(cfg, params, n_stages=2, quantize="int4", devices=devices[:2])
    outs, stats = eng.generate([[5, 9, 2], [7, 1, 3]], 6, temperature=0.0)
    assert all(len(o) == 9 for o in outs)
    assert stats.tokens_generated == 12


def test_init_quantized_params_w4_generates():
    from mdi_llm_tpu.ops.quant import init_quantized_params

    cfg = tiny_cfg()
    params = init_quantized_params(cfg, mode="w4", dtype=jnp.float32)
    eng = Generator(cfg, jax.device_put(params), cache_dtype=jnp.float32)
    outs, _ = eng.generate([[3, 1, 4]], 5, temperature=0.0)
    assert len(outs[0]) == 8


def test_int8_pipeline_matches_int8_single(devices):
    """Quantized ring == quantized single-device generation token-for-token
    (same int8 weights, greedy sampling; f32 compute on CPU)."""
    cfg = tiny_cfg(n_layer=4)
    params = transformer.init_params(cfg, jax.random.PRNGKey(9), dtype=jnp.float32)
    prompts = [[5, 9, 2], [7, 1, 3], [2, 2, 8, 4]]
    single = Generator(cfg, params, cache_dtype=jnp.float32, quantize="int8")
    want = []
    for p in prompts:
        o, _ = single.generate([p], 8, temperature=0.0)
        want.append(o[0])

    from mdi_llm_tpu.parallel.pipeline import PipelineEngine

    eng = PipelineEngine(
        cfg, params, n_stages=2, quantize="int8", devices=devices[:2],
        cache_dtype=jnp.float32,
    )
    got, _ = eng.generate(prompts, 8, temperature=0.0)
    assert got == want


def test_init_quantized_params_moe_matches_real_quantizer():
    """The synthetic MoE tree must be structurally identical (leaf names,
    shapes, dtypes) to quantize_params(init_params) on the same config —
    that equivalence is what lets bench rows run quantized MoE models that
    never exist unquantized."""
    from mdi_llm_tpu.ops.quant import init_quantized_params

    cfg = tiny_cfg(mlp_class_name="LLaMAMoE", n_expert=4, n_expert_per_token=2)
    for mode in ("w8", "w8a8", "w4"):
        # compare the MoE mlp subtree (what the synthetic branch builds);
        # outside it the trees intentionally differ — the synthetic init
        # keeps lm_head/embeddings in bf16 to skip a pointless quantize of
        # random values, while the real path quantizes lm_head too
        real = quantize_params(
            jax.device_get(transformer.init_params(cfg, jax.random.PRNGKey(0))),
            mode=mode,
        )["blocks"]["mlp"]
        synth = init_quantized_params(cfg, mode=mode)["blocks"]["mlp"]
        shape_of = lambda tree: jax.tree_util.tree_map_with_path(
            lambda p, x: (np.asarray(x).shape, np.asarray(x).dtype.name), tree
        )
        real_leaves, synth_leaves = shape_of(real), shape_of(synth)
        assert jax.tree_util.tree_structure(real_leaves) == (
            jax.tree_util.tree_structure(synth_leaves)
        ), f"{mode}: mlp tree structure diverged"
        for (rp, rv), (sp, sv) in zip(
            jax.tree_util.tree_leaves_with_path(real_leaves),
            jax.tree_util.tree_leaves_with_path(synth_leaves),
        ):
            assert rp == sp
            assert rv == sv, f"{mode}: mismatch at {rp}: {rv} vs {sv}"


@pytest.mark.parametrize("mode", ["w8", "w8a8", "w4"])
def test_generator_runs_synthetic_quantized_moe(mode):
    from mdi_llm_tpu.ops.quant import init_quantized_params
    from mdi_llm_tpu.generation import Generator

    cfg = tiny_cfg(mlp_class_name="LLaMAMoE", n_expert=4, n_expert_per_token=2)
    qp = init_quantized_params(cfg, mode=mode)
    gen = Generator(cfg, jax.device_put(qp), cache_dtype=jnp.float32)
    out, _ = gen.generate([[3, 1, 4]], 6, temperature=0.0)
    assert len(out[0]) == 9
